"""RelayService: the dissemination layer's binding to leadership.

Wired into GossipService's election transitions (gossip/service.py):

  elected leader   -> sole DeliverClient; each committed block's frame
                      comes off this service's BlockFanout ring and is
                      pushed down the tree (``on_leader_commit``)
  demotion         -> the relay root tears down (queued frames
                      dropped; whatever the children miss, the
                      anti-entropy pull repairs)
  promotion        -> rebuilt from the channel's CURRENT height (a
                      returning leader relays new commits only — bulk
                      history is anti-entropy's job, same as the
                      DeliverClient's resume-from-committed-height)

Non-leaders never see this path's write side: relayed blocks enter
through ``BlockRelay.on_relay`` -> MCS verify ->
``GossipStateProvider.add_block`` — the identical in-order buffer +
commit pipeline every gossiped block already rides, so ordering and
commit semantics are untouched by the relay.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

from fabric_mod_tpu.concurrency.locks import RegisteredLock
from fabric_mod_tpu.dissemination.relay import BlockRelay
from fabric_mod_tpu.dissemination.tree import RelayTree
from fabric_mod_tpu.observability import get_logger
from fabric_mod_tpu.peer.fanout import BlockFanout, encode_frame
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.utils import knobs

log = get_logger("dissemination.service")


class RelayService:
    """One channel's relay composition over a started GossipNode."""

    def __init__(self, node, degree: Optional[int] = None,
                 queue_cap: Optional[int] = None,
                 ring_size: Optional[int] = None,
                 leader_source: Optional[Callable[[], str]] = None,
                 epoch: int = 0):
        """`leader_source`: () -> the leader ENDPOINT the tree roots
        at; the default mirrors the deterministic election (min
        PKI-ID over {self} ∪ alive), so every peer with a converged
        view derives the same root the election elects."""
        self._node = node
        channel = node._channel
        self._cid = channel.channel_id
        if ring_size is None:
            ring_size = knobs.get_int("FABRIC_MOD_TPU_FANOUT_RING")
        # the leader's frame source: the SAME bounded ring the deliver
        # fan-out runs on — one materialize + one encode per block,
        # shared with any co-located event-deliver engine's semantics
        self._ring = BlockFanout(self._cid, channel.ledger, "full",
                                 ring_size)
        self._degree = degree
        self._epoch = int(epoch)
        self._leader_source = leader_source or self._elected_leader
        self.relay = BlockRelay(node, self.tree, queue_cap=queue_cap)
        self._lock = RegisteredLock("dissemination.service._lock")
        self._is_root = False
        self._root_from = 0
        # the membership view the current epoch was minted for: any
        # change (join, crash-expiry, partition heal) rotates the
        # epoch so the next tree() re-deals interior positions — the
        # plumbed-but-never-advanced epoch of the PR 18/19 seam
        self._epoch_members: Optional[frozenset] = None

    # -- tree derivation ---------------------------------------------------
    def _elected_leader(self) -> str:
        """Deterministic mirror of LeaderElectionService: min PKI-ID
        over {self} ∪ alive, mapped to its endpoint — agreement comes
        from the shared membership view, not coordination."""
        cands = [(self._node.pki_id, self._node.endpoint)]
        for mb in self._node.discovery.alive_members():
            cands.append((mb.pki_id, mb.endpoint))
        return min(cands)[1]

    def tree(self) -> RelayTree:
        members = [self._node.endpoint] + \
            [mb.endpoint for mb in self._node.discovery.alive_members()]
        self._note_membership(members)
        return RelayTree(members, self._leader_source(),
                         epoch=self._epoch, degree=self._degree)

    def _note_membership(self, members) -> None:
        """Advance the epoch when the alive set changes: a joiner, an
        expired crash victim, or a healed partition re-forms the tree
        instead of freezing the old interior under the same rotation."""
        key = frozenset(members)
        with self._lock:
            if self._epoch_members is None:
                self._epoch_members = key
            elif key != self._epoch_members:
                self._epoch_members = key
                self._epoch += 1
                log.info("%s: membership changed -> relay epoch %d",
                         self._node.endpoint, self._epoch)

    def bump_epoch(self) -> int:
        """Explicit rotation (the world's heal hook): the next tree()
        re-parents even with an unchanged member set."""
        with self._lock:
            self._epoch += 1
            return self._epoch

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._node.on_relay = self.relay.on_relay
        self.relay.start()

    def stop(self) -> None:
        self.relay.stop()
        if self._node.on_relay == self.relay.on_relay:
            self._node.on_relay = None

    # -- leadership transitions (driven by GossipService) ------------------
    def on_leadership(self, is_leader: bool) -> None:
        with self._lock:
            was, self._is_root = self._is_root, bool(is_leader)
        if is_leader and not was:
            self.promote()
        elif was and not is_leader:
            self.demote()

    def promote(self) -> None:
        """Rebuild the relay root from the channel's CURRENT height:
        a returning leader pushes new commits; anything a peer is
        missing below that is a gap its anti-entropy already knows
        how to pull."""
        self._root_from = self._node._channel.ledger.height
        self.relay.clear()
        log.info("%s: relay root up from height %d",
                 self._node.endpoint, self._root_from)

    def demote(self) -> None:
        dropped = self.relay.clear()
        log.info("%s: relay root torn down (%d queued frames dropped)",
                 self._node.endpoint, dropped)

    # -- the leader's commit hook (DeliverClient on_commit) ----------------
    def on_leader_commit(self, block: m.Block) -> None:
        """Frame the committed block off the fan-out ring and push it
        down the tree.  Replaces the leader's epidemic gossip_block:
        every peer is a tree member, so coverage comes from the
        forest, loss repair from anti-entropy."""
        with self._lock:
            if not self._is_root:
                return                     # demoted mid-callback
        num = block.header.number
        fr = self._ring.get(num)
        if fr is not None:
            self.relay.push_frame(fr.num, fr.payload, fr.is_config)
            return
        # commit signaled but the ledger read raced it (async commit
        # pipe edge): encode from the in-hand block — same bytes, the
        # ring picks the window up on the next commit
        self.relay.push_frame(
            num, encode_frame(self._cid, "full", block))

    # -- introspection -----------------------------------------------------
    @property
    def stats(self) -> Dict[str, int]:
        return self.relay.stats

    @property
    def ring_stats(self) -> Dict[str, int]:
        return self._ring.stats
