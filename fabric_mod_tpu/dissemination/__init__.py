"""Cross-peer block dissemination: one orderer pull per org, a
deterministic relay forest to every other peer.

(reference: the gossip layer's org-leader pull + state transfer — here
grown into a real dissemination subsystem: PR 17's BlockFanout made a
single peer fan one encoded frame out to 10k local subscribers; this
package pushes those once-encoded frames ACROSS peers down a relay
tree every member derives independently, so orderer deliver load is
O(orgs) regardless of peer count.)

* ``tree.py``     — RelayTree: a pure function of (sorted alive
                    membership, elected leader, epoch) with fan-out
                    degree ``FABRIC_MOD_TPU_RELAY_DEGREE``; zero
                    coordination, deterministic reparenting.
* ``relay.py``    — BlockRelay: frames off the leader's BlockFanout
                    ring, pushed child-ward over the existing gossip
                    comm with bounded per-child queues + counted
                    drops; gaps fall back to anti-entropy pull.
* ``service.py``  — RelayService: wired into GossipService leadership
                    transitions (sole DeliverClient at the leader,
                    teardown on demotion, rebuild-from-height on
                    promotion); non-leaders commit through the
                    existing GossipStateProvider buffer.
"""
from fabric_mod_tpu.dissemination.tree import (RelayTree,     # noqa: F401
                                               reparent_plan)
from fabric_mod_tpu.dissemination.relay import BlockRelay     # noqa: F401
from fabric_mod_tpu.dissemination.service import RelayService  # noqa: F401
