"""BlockRelay: push once-encoded deliver frames down the tree.

The leader's DeliverClient commits a block; the frame comes straight
off the BlockFanout ring (peer/fanout.py — materialized and encoded
exactly ONCE, PR 17's contract) and is pushed to this node's current
tree children over the existing gossip comm senders.  Interior peers
verify, commit through the GossipStateProvider buffer, and forward
the SAME frame bytes to their own children — so what lands at every
peer is byte-identical to a direct orderer pull, at orderer cost
O(leaders).

Loss tolerance needs no new protocol: a frame dropped anywhere (the
``dissemination.push`` seam, a bounded child queue overflowing, a
dead interior peer) leaves a GAP in the receiver's payload buffer,
and the existing anti-entropy machinery (state.py missing_range ->
node._pull_range, plus the quiescent-channel pull_tick) repairs it.
The relay only adds a PROD: a child that just saw a frame BEYOND its
next needed block knows about the gap now, so it fires the repair
request immediately instead of waiting out the anti-entropy cadence.

Per-child queues are bounded (``FABRIC_MOD_TPU_RELAY_QUEUE``): a slow
or dead child sheds its own OLDEST frames, counted, never blocking
the committing thread or the other children — the dropped range is
contiguous at the old end, exactly the shape one anti-entropy pull
repairs.
"""
from __future__ import annotations

import collections
import threading
from typing import Callable, Dict, Optional

from fabric_mod_tpu import faults
from fabric_mod_tpu.concurrency import (RegisteredLock, RegisteredThread,
                                        assert_joined)
from fabric_mod_tpu.observability import tracing
from fabric_mod_tpu.observability.logging import get_logger
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.utils import knobs

log = get_logger("dissemination.relay")

_PUSHED = default_provider().new_counter(MetricOpts(
    "fabric", "relay", "frames_pushed_total",
    "relay frames sent to tree children", ("channel",)))
_DROPPED = default_provider().new_counter(MetricOpts(
    "fabric", "relay", "frames_dropped_total",
    "relay frames shed (queue overflow / injected push fault)",
    ("channel",)))
_REPAIRS = default_provider().new_counter(MetricOpts(
    "fabric", "relay", "repair_prods_total",
    "gap-observed anti-entropy prods fired by the relay",
    ("channel",)))


class BlockRelay:
    """One node's relay engine: root push + interior forward + the
    gap-repair prod.  `tree_source()` returns the CURRENT RelayTree
    (recomputed from the live membership view per push, so
    reparenting needs no callback plumbing)."""

    # sign-once memo: one frame signs ONE envelope reused for every
    # child (and for the immediate re-forward of a just-received
    # frame); tiny because pushes are tip-sequential
    _ENV_MEMO = 8

    def __init__(self, node, tree_source: Callable[[], object],
                 queue_cap: Optional[int] = None,
                 on_deliver: Optional[Callable[[int, bytes],
                                               None]] = None):
        if queue_cap is None:
            queue_cap = knobs.get_int("FABRIC_MOD_TPU_RELAY_QUEUE")
        self._node = node
        self._tree_source = tree_source
        self._cap = max(1, int(queue_cap))
        self._cid = node._channel.channel_id
        self._lock = RegisteredLock("dissemination.relay._lock")
        self._ready = threading.Condition(self._lock)
        self._queues: Dict[str, collections.deque] = {}
        self._envs: "collections.OrderedDict[int, bytes]" = \
            collections.OrderedDict()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fwd_high = -1            # highest num already forwarded
        self._last_gap_start = -1      # throttles the repair prod
        self.on_deliver = on_deliver   # (num, frame) tap (bench/tests)
        self.stats: Dict[str, int] = {
            "pushed": 0, "forwarded": 0, "received": 0, "dropped": 0,
            "send_failures": 0, "repair_prods": 0, "duplicates": 0}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = RegisteredThread(target=self._sender_loop,
                                        name="relay-push",
                                        structure="dissemination.relay")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            self._ready.notify_all()
        if self._thread is not None:
            assert_joined((self._thread,), owner="BlockRelay",
                          timeout=5)
            self._thread = None

    def clear(self) -> int:
        """Demotion/promotion teardown: drop every queued frame (the
        children's buffers gap and anti-entropy repairs — a torn-down
        root must not keep pushing a dead stream's tail).  Returns the
        number of frames discarded."""
        with self._lock:
            n = sum(len(q) for q in self._queues.values())
            self._queues.clear()
            self._envs.clear()
        return n

    # -- push (root and interior alike) ------------------------------------
    def push_frame(self, num: int, frame: bytes,
                   is_config: bool = False) -> int:
        """Enqueue one ready frame toward every CURRENT tree child;
        returns children queued.  Bounded per child: overflow sheds
        that child's OLDEST frame, counted (never the committing
        caller's problem)."""
        children = self._tree_source().children(self._node.endpoint)
        if not children:
            return 0
        queued = 0
        with self._lock:
            for child in children:
                q = self._queues.get(child)
                if q is None:
                    q = self._queues[child] = collections.deque()
                if len(q) >= self._cap:
                    q.popleft()
                    self.stats["dropped"] += 1
                    _DROPPED.with_labels(self._cid).add(1)
                q.append((num, frame, is_config))
                queued += 1
            if queued:
                self._ready.notify_all()
        return queued

    def _sender_loop(self) -> None:
        while not self._stop.is_set():
            batch = []
            with self._lock:
                while not self._stop.is_set():
                    for child, q in self._queues.items():
                        if q:
                            batch.append((child, q.popleft()))
                    if batch:
                        break
                    self._ready.wait(timeout=0.5)
            if self._stop.is_set():
                return
            for child, (num, frame, is_config) in batch:
                self._send_one(child, num, frame, is_config)

    def _send_one(self, child: str, num: int, frame: bytes,
                  is_config: bool) -> bool:
        # the chaos seam: an armed drop loses THIS child's copy on the
        # wire — the child's buffer gaps and the repair prod + anti-
        # entropy pull must recover it (what the soak's relay lane and
        # the gap-repair test assert)
        if faults.point("dissemination.push"):
            with self._lock:
                self.stats["dropped"] += 1
            _DROPPED.with_labels(self._cid).add(1)
            return False
        with tracing.span("relay.push", block=num):
            env = self._envelope(num, frame, is_config)
            ok = self._node.comm.send_signed(child, env)
        with self._lock:
            self.stats["pushed" if ok else "send_failures"] += 1
        if ok:
            _PUSHED.with_labels(self._cid).add(1)
        return ok

    def _envelope(self, num: int, frame: bytes,
                  is_config: bool) -> bytes:
        """Sign once per frame, ship the same envelope to every child
        (the frame itself was already encoded once on the leader —
        degree sends must not mean degree signatures either)."""
        with self._lock:
            env = self._envs.get(num)
            if env is not None:
                return env
        msg = m.GossipMessage(
            channel=self._cid.encode(),
            relay_msg=m.RelayMessage(seq_num=num, frame=frame,
                                     config=1 if is_config else 0))
        env = self._node.comm.sign_once(msg)
        with self._lock:
            self._envs[num] = env
            while len(self._envs) > self._ENV_MEMO:
                self._envs.popitem(last=False)
        return env

    # -- receive (wired as GossipNode.on_relay) ----------------------------
    def on_relay(self, msg: m.GossipMessage) -> None:
        """A frame from our tree parent: verify -> commit through the
        state buffer -> forward the SAME bytes to our children ->
        prod repair if the frame revealed a gap."""
        rm = msg.relay_msg
        if rm is None or not rm.frame:
            return
        if msg.channel != self._cid.encode():
            return                         # cross-channel guard
        with self._lock:
            self.stats["received"] += 1
        try:
            resp = m.DeliverResponse.decode(rm.frame)
            block = resp.block
            if block is None or block.header is None:
                return
            # the same MCS gate every gossip data message passes
            # BEFORE the state buffer (node._handle_data): a relayed
            # frame is as untrusted as any gossiped block
            self._node._channel.mcs.verify_block(self._cid, block)
        except Exception:
            return                         # unverifiable: drop, no relay
        num = rm.seq_num
        if self.on_deliver is not None:
            self.on_deliver(num, rm.frame)
        self._node.state.add_block(block)
        with self._lock:
            dup = num <= self._fwd_high
            if not dup:
                self._fwd_high = num
            self.stats["duplicates" if dup else "forwarded"] += 1
        if not dup:
            # verbatim forward: children receive the leader's bytes
            self.push_frame(num, rm.frame, bool(rm.config))
        self._maybe_repair()

    def _maybe_repair(self) -> None:
        """A received frame landed BEYOND the next needed block: the
        gap exists NOW — fire the anti-entropy request immediately
        instead of waiting out the tick cadence.  Throttled per gap
        head so a burst of tip frames prods once, not per frame."""
        gap = self._node.state.buffer.missing_range()
        if gap is None:
            with self._lock:
                self._last_gap_start = -1
            return
        with self._lock:
            if gap.start == self._last_gap_start:
                return
            self._last_gap_start = gap.start
            self.stats["repair_prods"] += 1
        _REPAIRS.with_labels(self._cid).add(1)
        with tracing.span("relay.repair", start=gap.start,
                          stop=gap.stop):
            # the repair seam: an armed drop suppresses the PROD only
            # — the periodic anti-entropy tick is the backstop that
            # must still converge the channel (asserted in tests)
            if faults.point("dissemination.repair"):
                return
            self._node.state.request_gap()
