"""Broadcast ingress: envelope in, routed + validated + ordered.

(reference: orderer/common/broadcast/broadcast.go — Handle at :66
receiving the stream, ProcessMessage at :136-180 doing classify →
msgprocessor → WaitReady → Order/Configure.)

In-process this round: `Broadcast.submit` is the unary equivalent of
one stream message; the gRPC server wraps this same object when the
comm layer lands (SURVEY §5.8 keeps gRPC as the control plane).

Robustness: a consenter that momentarily has NO leader (raft election
in flight, leader just crashed) raises the typed, retryable
NotLeaderError instead of silently dropping the envelope.  submit()
retries it on a jittered-backoff schedule bounded by the
FABRIC_MOD_TPU_BROADCAST_RETRY_S deadline — a leader crash costs one
election of latency, not a lost transaction — and re-raises it typed
when the window outlasts the budget, carrying the best leader hint so
the transport layer can answer SERVICE_UNAVAILABLE + redirect
(reference: etcdraft's ErrNoLeader → Status SERVICE_UNAVAILABLE).

Overload (the other half): when any admission knob is armed
(orderer/admission.py), submit() consults the AdmissionController
BEFORE the processor's signature work — per-client token buckets and
the occupancy/latency overload gate shed normal txs with the typed,
retryable ResourceExhaustedError (+ retry-after) while config and
lifecycle traffic always passes.  Unarmed, this path is one None
check: PR 6 behavior exactly.

Throughput (the staged half): with FABRIC_MOD_TPU_STAGED_BROADCAST
armed, concurrent submitters' normal-tx Writers-policy verifies
coalesce through the per-channel staging lanes of
orderer/stagedbroadcast.py — one batched `verify_many` dispatch per
drain instead of one per submission.  The verdict, `chain.order`, the
NotLeaderError retrier, and admission's note_latency all stay on the
SUBMITTER's thread, so typed errors and the overload gate's EWMA stay
per-envelope.  Config txs always take the blocking path.
"""
from __future__ import annotations

import time
from typing import Optional

from fabric_mod_tpu.channelconfig import ConfigTxError
from fabric_mod_tpu.observability import tracing
from fabric_mod_tpu.orderer import admission as admission_mod
from fabric_mod_tpu.orderer.consensus import NotLeaderError
from fabric_mod_tpu.orderer.msgprocessor import MsgRejectedError
from fabric_mod_tpu.orderer.registrar import Registrar
from fabric_mod_tpu.orderer.stagedbroadcast import (
    StagedIngress, staged_batch)
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.utils import knobs
from fabric_mod_tpu.utils.retry import Retrier

# client-attributable rejections -> BAD_REQUEST on the wire; anything
# else propagates as an internal error (the gRPC handler maps it to
# INTERNAL_SERVER_ERROR) — misattributing bugs to clients masks them
_CLIENT_FAULTS = (MsgRejectedError, ConfigTxError, ValueError)


def broadcast_retry_s() -> float:
    """FABRIC_MOD_TPU_BROADCAST_RETRY_S: how long submit() retries a
    leaderless consenter before surfacing NotLeaderError; 0 disables
    (every NotLeaderError is immediate — the pre-retry behavior)."""
    return max(0.0, knobs.get_float("FABRIC_MOD_TPU_BROADCAST_RETRY_S"))


class BroadcastError(Exception):
    pass


class Broadcast:
    def __init__(self, registrar: Registrar,
                 retrier: Optional[Retrier] = None,
                 admission=None):
        """`retrier` overrides the NOT_LEADER retry policy (tests pass
        one whose sleep drives a ManualClock); default: jittered
        backoff under the FABRIC_MOD_TPU_BROADCAST_RETRY_S deadline.
        `admission` overrides the knob-built AdmissionController
        (tests pass one with a ManualClock); with every admission knob
        unset the default is None and submit() is the PR 6 path."""
        self._registrar = registrar
        if retrier is None:
            deadline = broadcast_retry_s()
            retrier = Retrier(
                base_s=0.05, max_s=0.5,
                deadline_s=deadline if deadline > 0 else None,
                max_attempts=1 if deadline <= 0 else None,
                retry_on=(NotLeaderError,), name="broadcast")
        self._retrier = retrier
        if admission is None:
            admission = admission_mod.AdmissionController.from_env()
        self._admission = admission
        depth = staged_batch()
        self._staged: Optional[StagedIngress] = \
            StagedIngress(depth) if depth > 0 else None

    def close(self) -> None:
        """Stop the staging lanes (no-op unstaged); racing submitters
        resolve typed, never hang."""
        if self._staged is not None:
            self._staged.close()

    def submit(self, env: m.Envelope) -> None:
        """Accept one envelope for ordering; raises BroadcastError on
        client-caused rejection (maps to BAD_REQUEST on the wire),
        NotLeaderError — after the retry budget — when the ordering
        service has no leader (maps to SERVICE_UNAVAILABLE: the
        client's cue to back off or follow the leader hint), and
        admission_mod.ResourceExhaustedError when admission sheds the
        submission (maps to RESOURCE_EXHAUSTED + retry-after)."""
        with tracing.span("broadcast.submit"):
            self._submit_traced(env)

    def _submit_traced(self, env: m.Envelope) -> None:
        adm = self._admission
        t0 = time.perf_counter() if adm is not None else 0.0
        try:
            support, is_config_update = \
                self._registrar.broadcast_channel_support(env)
        except Exception as e:
            raise BroadcastError(f"routing: {e}") from e
        if adm is not None:
            # BEFORE the processor: shedding must cost ONE header
            # parse, not a signature-policy evaluation (classify
            # decodes the payload once; the client hash is skipped
            # when no limiter is armed).  Gate state is per channel —
            # a hot channel never sheds its idle neighbor
            client, priority = admission_mod.classify(
                env, is_config_update, need_client=adm.has_limiter)
            adm.admit(client, priority,
                      admission_mod.chain_occupancy(support.chain),
                      channel=support.channel_id)
        if is_config_update:
            try:
                wrapped, seq = \
                    support.processor.process_config_update_msg(env)
                # consenters' pre-order checks (e.g. the raft
                # one-membership-change rule) are client faults too
                self._retrier.call(
                    support.chain.configure, wrapped, seq)
            except NotLeaderError:
                raise
            except _CLIENT_FAULTS as e:
                raise BroadcastError(f"config update rejected: {e}") from e
        else:
            try:
                if self._staged is not None:
                    # coalesced Writers verify; verdict is still OURS —
                    # order/retry/latency stay on this thread
                    seq = self._staged.submit(
                        support.channel_id, support.processor, env)
                else:
                    seq = support.processor.process_normal_msg(env)
            except _CLIENT_FAULTS as e:
                raise BroadcastError(f"rejected: {e}") from e
            self._retrier.call(support.chain.order, env, seq)
        if adm is not None:
            # accepted-path latency only: a shed raised before this
            # point, and feeding shed latencies into the EWMA would
            # let fast rejections close the gate they caused (the
            # gate's wall-time decay handles the all-shed window)
            adm.note_latency(time.perf_counter() - t0,
                             channel=support.channel_id)
