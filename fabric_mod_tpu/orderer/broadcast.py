"""Broadcast ingress: envelope in, routed + validated + ordered.

(reference: orderer/common/broadcast/broadcast.go — Handle at :66
receiving the stream, ProcessMessage at :136-180 doing classify →
msgprocessor → WaitReady → Order/Configure.)

In-process this round: `Broadcast.submit` is the unary equivalent of
one stream message; the gRPC server wraps this same object when the
comm layer lands (SURVEY §5.8 keeps gRPC as the control plane).
"""
from __future__ import annotations

from fabric_mod_tpu.channelconfig import ConfigTxError
from fabric_mod_tpu.orderer.msgprocessor import MsgRejectedError
from fabric_mod_tpu.orderer.registrar import Registrar
from fabric_mod_tpu.protos import messages as m

# client-attributable rejections -> BAD_REQUEST on the wire; anything
# else propagates as an internal error (the gRPC handler maps it to
# INTERNAL_SERVER_ERROR) — misattributing bugs to clients masks them
_CLIENT_FAULTS = (MsgRejectedError, ConfigTxError, ValueError)


class BroadcastError(Exception):
    pass


class Broadcast:
    def __init__(self, registrar: Registrar):
        self._registrar = registrar

    def submit(self, env: m.Envelope) -> None:
        """Accept one envelope for ordering; raises BroadcastError on
        client-caused rejection (maps to BAD_REQUEST on the wire)."""
        try:
            support, is_config_update = \
                self._registrar.broadcast_channel_support(env)
        except Exception as e:
            raise BroadcastError(f"routing: {e}") from e
        if is_config_update:
            try:
                wrapped, seq = \
                    support.processor.process_config_update_msg(env)
                # consenters' pre-order checks (e.g. the raft
                # one-membership-change rule) are client faults too
                support.chain.configure(wrapped, seq)
            except _CLIENT_FAULTS as e:
                raise BroadcastError(f"config update rejected: {e}") from e
        else:
            try:
                seq = support.processor.process_normal_msg(env)
            except _CLIENT_FAULTS as e:
                raise BroadcastError(f"rejected: {e}") from e
            support.chain.order(env, seq)
