"""Channel participation: join/remove/list channels without a system
channel, including onboarding from a later config block and follower
chains for non-members.

(reference: orderer/common/channelparticipation/restapi.go:408 — the
operator REST API; orderer/common/onboarding/onboarding.go:447 — chain
replication when joining an existing channel; orderer/consensus/
follower/chain.go — the chain placeholder that keeps pulling blocks
until this orderer appears in the consenter set.)

Trust model for onboarding, same as the reference: the operator-
supplied join block is the anchor.  Replicated blocks are accepted
only if they hash-chain forward from genesis AND the block at the join
height hashes to exactly the join block; anything a malicious source
alters breaks one of the two.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from fabric_mod_tpu.channelconfig.configtx import config_from_block
from fabric_mod_tpu.orderer.consensus import ChainHaltedError
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.concurrency.threads import RegisteredThread


class ParticipationError(Exception):
    pass


# status values (reference: channelparticipation's ChannelInfo)
ACTIVE, ONBOARDING, FOLLOWER = "active", "onboarding", "follower"


class FollowerChain:
    """Consenter-shaped placeholder for a channel this orderer stores
    but does not order: rejects Broadcast, keeps the ledger growing by
    pulling blocks from cluster peers (reference: follower/chain.go).

    `is_member`/`on_member` are the promotion seam: deployments whose
    channel config encodes a consenter set wire `is_member` to check
    it and `on_member` to swap in a real consenter (the reference's
    follower→member transition).  They are optional — without them a
    follower stays a follower until the operator removes and rejoins
    as a member."""

    POLL_INTERVAL_S = 0.2

    def __init__(self, support, block_fetcher,
                 is_member: Optional[Callable[[], bool]] = None,
                 on_member: Optional[Callable[[], None]] = None):
        self._support = support
        self._fetch = block_fetcher
        self._is_member = is_member
        self._on_member = on_member
        self._halted = threading.Event()
        self._thread = RegisteredThread(
            target=self._run, name="participation",
            structure="orderer.participation")

    # -- consenter surface (order/configure refuse) ----------------------
    def start(self) -> None:
        self._thread.start()

    def halt(self) -> None:
        self._halted.set()
        self._thread.join(timeout=5)

    def wait_ready(self) -> None:
        raise ChainHaltedError("this orderer is a follower of the "
                               "channel; it does not accept Broadcast")

    def order(self, env, config_seq) -> None:
        self.wait_ready()

    def configure(self, env, config_seq) -> None:
        self.wait_ready()

    # -- the pull loop ----------------------------------------------------
    def poll_once(self) -> int:
        """One catch-up attempt; returns blocks appended.  Every pulled
        block is verified against the channel's BlockValidation policy
        (the fetch source is untrusted — same gate as the raft
        catch-up's _append_fetched; reference: cluster.VerifyBlocks)."""
        if self._fetch is None:
            return 0
        from fabric_mod_tpu.peer.mcs import MessageCryptoService
        store = self._support.store
        h = store.height
        try:
            blocks = self._fetch(h, 0)     # 0 = "to the source's tip"
        except Exception:
            return 0
        appended = 0
        for block in blocks or []:
            if block.header.number != store.height:
                break
            if store.height and \
                    block.header.previous_hash != store.last_block_hash:
                break                      # broken chain: stop pulling
            try:
                MessageCryptoService(self._support.bundle).verify_block(
                    self._support.channel_id, block)
            except Exception:
                break                      # unverifiable: stop pulling
            if _is_config_block(block):
                envs = protoutil.get_envelopes(block)
                try:
                    self._support.process_config(envs[0], block)
                except Exception:
                    break
            else:
                self._support.writer.write_block(block)
            appended += 1
        if appended and self._is_member is not None and self._is_member():
            if self._on_member is not None:
                cb, self._on_member = self._on_member, None
                cb()
        return appended

    def _run(self) -> None:
        while not self._halted.is_set():
            self.poll_once()
            self._halted.wait(self.POLL_INTERVAL_S)


def _is_config_block(block: m.Block) -> bool:
    try:
        envs = protoutil.get_envelopes(block)
        if len(envs) != 1:
            return False
        payload = protoutil.unmarshal_envelope_payload(envs[0])
        ch = m.ChannelHeader.decode(payload.header.channel_header)
        return ch.type == m.HeaderType.CONFIG
    except Exception:
        return False


def replicate_chain(store, join_block: m.Block, block_fetcher) -> None:
    """Onboard: pull blocks [height, join_height], verify the WHOLE
    chain against the join-block anchor, then append (reference:
    onboarding.go:447 ReplicateChains + cluster replication.go:677).
    Nothing is written until the anchor check passes — a lying source
    must not leave poisoned partial state that would block an honest
    re-join.  Raises ParticipationError when the source lies."""
    target = join_block.header.number
    if block_fetcher is None:
        raise ParticipationError(
            "joining at height %d needs a block fetcher" % target)
    start = store.height
    blocks: List[m.Block] = []
    while start + len(blocks) <= target:
        batch = block_fetcher(start + len(blocks), target + 1)
        if not batch:
            raise ParticipationError(
                "replication source has no blocks %d..%d"
                % (start + len(blocks), target))
        for block in batch:
            if block.header.number != start + len(blocks):
                raise ParticipationError("replicated block out of order")
            blocks.append(block)
    # verify before writing: hash-chain continuity + the anchor
    prev = store.last_block_hash if start else None
    for block in blocks:
        if prev is not None and block.header.previous_hash != prev:
            raise ParticipationError(
                "replicated block %d breaks the hash chain"
                % block.header.number)
        prev = protoutil.block_header_hash(block.header)
    if prev != protoutil.block_header_hash(join_block.header):
        raise ParticipationError(
            "replicated chain does not end at the join block "
            "(forged history)")
    for block in blocks:
        store.add_block(block)


class ChannelParticipation:
    """The operator surface (reference: restapi.go:408).  Wraps a
    Registrar; `http_routes()` exposes it on the operations server."""

    def __init__(self, registrar, block_fetcher=None):
        self._registrar = registrar
        self._fetcher = block_fetcher

    # -- queries ----------------------------------------------------------
    def list_channels(self) -> List[Dict]:
        out = []
        for cid in self._registrar.channel_ids():
            out.append(self.channel_info(cid))
        return out

    def channel_info(self, channel_id: str) -> Dict:
        support = self._registrar.get_chain(channel_id)
        if support is None:
            raise ParticipationError(f"unknown channel {channel_id!r}")
        chain = support.chain
        status = FOLLOWER if isinstance(chain, FollowerChain) else ACTIVE
        info = {"name": channel_id, "height": support.store.height,
                "status": status}
        # consensus leadership, when the consenter knows it (raft):
        # operators and the process-network harness use this to find
        # the node to kill/drain (reference: channelparticipation's
        # consensusRelation field)
        if hasattr(chain, "is_leader"):
            info["is_leader"] = bool(chain.is_leader)
            # which node this consenter BELIEVES leads: a follower
            # that hasn't learned the leader yet drops forwarded
            # submits (clients retry by design), so harnesses must be
            # able to wait for leader knowledge to propagate before
            # ordering through a follower
            if hasattr(chain, "leader_id"):
                info["leader_id"] = chain.leader_id
        return info

    # -- join / remove ----------------------------------------------------
    def join(self, join_block: m.Block, as_follower: bool = False):
        """Join from a genesis block (height 0) or onboard from a
        later config block by replicating the chain first."""
        cid, _config = config_from_block(join_block)
        if self._registrar.get_chain(cid) is not None:
            raise ParticipationError(f"channel {cid!r} exists")
        if as_follower and self._fetcher is None and \
                getattr(self._registrar, "_block_fetcher", None) is None:
            # fail loudly: a fetcher-less follower would sit at the
            # join height forever with no error anywhere
            raise ParticipationError(
                "this node has no replication source configured; "
                "follower channels cannot pull blocks")
        return self._registrar.join_channel(
            join_block, block_fetcher=self._fetcher,
            as_follower=as_follower)

    def remove(self, channel_id: str) -> None:
        self._registrar.remove_channel(channel_id)

    # -- HTTP wiring (the REST shape of restapi.go) ----------------------
    def handle(self, method: str, path: str, body: bytes):
        """(code, json-serializable) for
        {GET,POST,DELETE} /participation/v1/channels[/<id>]."""
        import base64
        import json as _json
        parts = [p for p in path.split("/") if p]
        # parts: ["participation", "v1", "channels", <id>?]
        if len(parts) < 3 or parts[0] != "participation" or \
                parts[1] != "v1" or parts[2] != "channels":
            return 404, {"error": "not found"}
        cid = parts[3] if len(parts) > 3 else None
        try:
            if method == "GET" and cid is None:
                return 200, {"channels": self.list_channels()}
            if method == "GET":
                return 200, self.channel_info(cid)
            if method == "POST" and cid is None:
                req = _json.loads(body or b"{}")
                block = m.Block.decode(
                    base64.b64decode(req["config_block"]))
                info = self.join(block,
                                 as_follower=bool(req.get("follower")))
                return 201, {"name": info.channel_id,
                             "height": info.store.height}
            if method == "DELETE" and cid is not None:
                self.remove(cid)
                return 204, None
        except ParticipationError as e:
            return 400, {"error": str(e)}
        except Exception as e:
            return 400, {"error": f"bad request: {e}"}
        return 405, {"error": "method not allowed"}
