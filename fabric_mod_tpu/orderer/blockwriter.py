"""Block creation + signing + append for the ordering service.

(reference: orderer/common/multichannel/blockwriter.go —
CreateNextBlock at :67, WriteBlock at :168, addBlockSignature at :191
— and the LAST_CONFIG tracking the deliver client depends on.)

The orderer's signature lives in block metadata[SIGNATURES] as a
Metadata message whose value carries the last-config index; the signed
bytes are value ‖ signature_header ‖ encoded block header, so any
tampering with the data hash chain or the metadata breaks the
signature.  Peers verify it against the channel's
/Channel/Orderer/BlockValidation policy before committing (the MCS
seam, peer/mcs.py).
"""
from __future__ import annotations

import threading
from typing import Optional, Sequence

from fabric_mod_tpu.ledger.blkstorage import BlockStore
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.concurrency.locks import RegisteredLock


def block_signed_data(block: m.Block, md_value: bytes,
                      sig_header: bytes) -> bytes:
    """The exact bytes an orderer signs over a block (and a peer
    verifies): metadata value ‖ signature header ‖ block header."""
    return md_value + sig_header + block.header.encode()


# generic block-metadata decoding lives in protoutil; kept as an
# alias here for the orderer-side callers
last_config_index = protoutil.block_last_config_index


class BlockWriter:
    """Creates, signs, and appends blocks for one channel."""

    def __init__(self, store: BlockStore, signer, channel_id: str):
        self._store = store
        self._signer = signer
        self.channel_id = channel_id
        self._lock = RegisteredLock("orderer.blockwriter._lock")
        self.height_changed = threading.Condition()
        # Recover last-config pointer from the tip (reference:
        # blockwriter newBlockWriter reads lastConfigBlockNum)
        self._last_config = 0
        h = store.height
        if h > 0:
            tip = store.get_block_by_number(h - 1)
            lc = last_config_index(tip)
            if lc is not None:
                self._last_config = lc

    # -- creation --------------------------------------------------------
    def create_next_block(self, envs: Sequence[m.Envelope]) -> m.Block:
        """(reference: blockwriter.go:67 CreateNextBlock)"""
        h = self._store.height
        prev = self._store.last_block_hash if h else b""
        return protoutil.new_block(h, prev, envs)

    # -- commit ----------------------------------------------------------
    def write_block(self, block: m.Block, is_config: bool = False) -> None:
        """Sign metadata and append (reference: blockwriter.go:168
        WriteBlock + :191 addBlockSignature).  Caller threads must not
        interleave create/write pairs; the consenter loop is the only
        writer (solo/raft both single-threaded), the lock is a guard."""
        with self._lock:
            if is_config:
                self._last_config = block.header.number
            md_value = m.LastConfig(index=self._last_config).encode()
            sigs = []
            if self._signer is not None:
                sig_header = protoutil.make_signature_header(
                    self._signer.serialize(), protoutil.new_nonce()).encode()
                signed = block_signed_data(block, md_value, sig_header)
                sigs.append(m.MetadataSignature(
                    signature_header=sig_header,
                    signature=self._signer.sign_message(signed)))
            meta = m.Metadata(value=md_value, signatures=sigs)
            md = block.metadata.metadata
            while len(md) <= m.BlockMetadataIndex.SIGNATURES:
                md.append(b"")
            md[m.BlockMetadataIndex.SIGNATURES] = meta.encode()
            self._store.add_block(block)
        with self.height_changed:
            self.height_changed.notify_all()

    @property
    def height(self) -> int:
        return self._store.height

    @property
    def last_config(self) -> int:
        return self._last_config
