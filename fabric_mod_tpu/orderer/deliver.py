"""Deliver: stream committed blocks to consumers.

(reference: common/deliver/deliver.go — Handle at :157, deliverBlocks
at :199 with SeekInfo semantics — serving the peer's deliver client,
blocksprovider.go.)

In-process this round: `DeliverService.blocks` is a generator with the
reference's seek semantics (start position, optional stop, block on
newest).  The gRPC streaming wrapper rides on top unchanged later.
"""
from __future__ import annotations

import threading
from typing import Iterator, Optional

from fabric_mod_tpu.orderer.registrar import ChainSupport
from fabric_mod_tpu.protos import messages as m


class DeliverService:
    def __init__(self, support: ChainSupport):
        self._support = support

    def blocks(self, start: int = 0, stop: Optional[int] = None,
               stop_event: Optional[threading.Event] = None,
               timeout_s: float = 30.0) -> Iterator[m.Block]:
        """Yield blocks [start, stop]; when the chain tip is reached,
        block until new blocks arrive (SeekInfo BLOCK_UNTIL_READY) or
        `stop_event` fires / `timeout_s` elapses without progress."""
        num = start
        store = self._support.store
        cond = self._support.writer.height_changed
        while stop is None or num <= stop:
            if stop_event is not None and stop_event.is_set():
                return
            blk = store.get_block_by_number(num)
            if blk is not None:
                yield blk
                num += 1
                continue
            with cond:
                if store.height > num:
                    continue              # raced a write; re-read
                if not cond.wait(timeout=timeout_s):
                    return                # idle timeout: end the stream
