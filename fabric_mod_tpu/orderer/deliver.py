"""Deliver: stream committed blocks to consumers.

(reference: common/deliver/deliver.go — Handle at :157, deliverBlocks
at :199 with SeekInfo semantics — serving the peer's deliver client,
blocksprovider.go.)

In-process this round: `DeliverService.blocks` is a generator with the
reference's seek semantics (start position, optional stop, block on
newest).  The gRPC streaming wrapper rides on top unchanged later.
"""
from __future__ import annotations

import threading
import time
from typing import Iterator, Optional

from fabric_mod_tpu import faults
from fabric_mod_tpu.concurrency import CancellationEvent
from fabric_mod_tpu.orderer.registrar import ChainSupport
from fabric_mod_tpu.protos import messages as m


class DeliverService:
    def __init__(self, support: ChainSupport):
        self._support = support

    def blocks(self, start: int = 0, stop: Optional[int] = None,
               stop_event: Optional[threading.Event] = None,
               timeout_s: float = 30.0) -> Iterator[m.Block]:
        """Yield blocks [start, stop]; when the chain tip is reached,
        block until new blocks arrive (SeekInfo BLOCK_UNTIL_READY) or
        `stop_event` fires / `timeout_s` elapses without progress."""
        num = start
        store = self._support.store
        cond = self._support.writer.height_changed
        while stop is None or num <= stop:
            if stop_event is not None and stop_event.is_set():
                return
            # chaos seam: a stream that dies mid-pull (the raised
            # fault reaches the consumer exactly like a transport
            # error would — DeliverClient types it as
            # DeliverDisconnected with the resume height)
            faults.point("deliver.stream")
            blk = store.get_block_by_number(num)
            if blk is not None:
                yield blk
                num += 1
                continue
            # a CancellationEvent can notify the writer's cond on
            # set(), so those streams park tickless until a commit,
            # cancel, or the idle deadline; a plain Event (legacy
            # callers) cannot reach into the cond, so it keeps the
            # 0.25 s slice that bounds stop() latency inside every
            # join budget (leak found by the FMT_RACECHECK
            # registered-thread sweep)
            unhook = None
            if isinstance(stop_event, CancellationEvent):
                def _wake() -> None:
                    with cond:
                        cond.notify_all()
                unhook = stop_event.on_set(_wake)
            try:
                with cond:
                    if store.height > num:
                        continue          # raced a write; re-read
                    deadline = time.monotonic() + timeout_s
                    while store.height <= num:
                        if stop_event is not None and stop_event.is_set():
                            return
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return        # idle timeout: end the stream
                        if unhook is not None:
                            cond.wait(timeout=remaining)
                        else:
                            cond.wait(timeout=min(0.25, remaining))
            finally:
                if unhook is not None:
                    unhook()
