"""Broker-based consenter: ordering via a shared append-only topic.

(reference: orderer/consensus/kafka — chain.go:1181: every orderer
posts envelopes to one partition and consumes the SAME offset-ordered
stream, so all nodes cut identical blocks; batch timeouts are made
deterministic with time-to-cut (TTC) messages — the first TTC naming
a block number wins, duplicates are ignored; the last consumed offset
rides in block metadata so restarts resume mid-stream without
re-cutting (LAST_OFFSET_PERSISTED).)

The broker here is the pluggable transport seam: an in-process
`Broker` with optional CRC-framed file persistence stands in for the
kafka cluster (same API shape a real broker client would adapt to).
Determinism comes from the stream, not the broker: any transport that
delivers the same messages in the same order to every consumer works.
"""
from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from fabric_mod_tpu.orderer.consensus import ChainHaltedError
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.concurrency.threads import RegisteredThread
from fabric_mod_tpu.concurrency.locks import RegisteredLock

_NORMAL, _CONFIG, _TTC = 0, 1, 2


class Broker:
    """Offset-ordered topics (reference: the kafka partition).  With
    `dir_path`, messages persist across restarts (CRC-framed; torn
    tails cropped)."""

    def __init__(self, dir_path: Optional[str] = None):
        self._dir = dir_path
        self._topics: Dict[str, List[bytes]] = {}
        self._files: Dict[str, object] = {}
        self._lock = RegisteredLock("orderer.broker._lock")
        self._cv = threading.Condition(self._lock)
        if dir_path:
            os.makedirs(dir_path, exist_ok=True)
            for name in sorted(os.listdir(dir_path)):
                if name.endswith(".topic"):
                    self._load(name[:-len(".topic")])

    def _load(self, topic: str) -> None:
        path = os.path.join(self._dir, topic + ".topic")
        msgs: List[bytes] = []
        raw = open(path, "rb").read()
        pos = good = 0
        while pos + 8 <= len(raw):
            ln, crc = struct.unpack_from("<II", raw, pos)
            end = pos + 8 + ln
            if end > len(raw) or zlib.crc32(raw[pos + 8:end]) != crc:
                break
            msgs.append(raw[pos + 8:end])
            good = pos = end
        if good < len(raw):
            with open(path, "r+b") as f:
                f.truncate(good)
        self._topics[topic] = msgs

    def append(self, topic: str, msg: bytes) -> int:
        """-> the assigned offset."""
        with self._cv:
            msgs = self._topics.setdefault(topic, [])
            if self._dir:
                f = self._files.get(topic)
                if f is None:
                    f = open(os.path.join(self._dir, topic + ".topic"),
                             "ab")
                    self._files[topic] = f
                f.write(struct.pack("<II", len(msg), zlib.crc32(msg))
                        + msg)
                f.flush()
                os.fsync(f.fileno())
            msgs.append(msg)
            self._cv.notify_all()
            return len(msgs) - 1

    def read(self, topic: str, from_offset: int,
             timeout_s: float = 0.2) -> List[Tuple[int, bytes]]:
        """Messages at offsets >= from_offset; blocks briefly when
        none are available (the consumer poll)."""
        with self._cv:
            msgs = self._topics.get(topic, [])
            if from_offset >= len(msgs):
                self._cv.wait(timeout_s)
                msgs = self._topics.get(topic, [])
            return [(i, msgs[i])
                    for i in range(from_offset, len(msgs))]

    def close(self) -> None:
        with self._lock:
            for f in self._files.values():
                f.close()
            self._files.clear()


def _encode(kind: int, payload: bytes, number: int = 0) -> bytes:
    return bytes([kind]) + struct.pack("<q", number) + payload


def _decode(raw: bytes) -> Tuple[int, int, bytes]:
    return raw[0], struct.unpack_from("<q", raw, 1)[0], raw[9:]


class BrokerChain:
    """Consenter over a Broker topic (reference: kafka chain.go:1181).

    All ordering decisions derive from the shared stream: size cuts
    from message counts, timeout cuts from the first TTC naming the
    next block number.  Every consumer builds identical blocks."""

    # the consenter-metadata slot (the reference's ORDERER index — its
    # kafka chain stores LAST_OFFSET_PERSISTED there; our raft chain
    # uses the same slot for its applied index, and a channel only
    # ever has one consenter)
    OFFSET_MD_SLOT = 3

    def __init__(self, broker: Broker, support,
                 topic: Optional[str] = None):
        self._broker = broker
        self._support = support
        self._topic = topic or support.channel_id
        self._halted = threading.Event()
        self._thread = RegisteredThread(
            target=self._run, name=f"broker-chain[{self._topic}]",
            structure="orderer.broker")
        self._timer_lock = RegisteredLock("orderer.broker._timer_lock")
        self._timer: Optional[threading.Timer] = None
        # resume: the offset recorded in the tip block's metadata is
        # the last offset INCLUDED in a block — everything after it
        # (messages left pending at the crash) is re-consumed
        self._consumed = 0
        store = support.store
        if store.height > 1:
            tip = store.get_block_by_number(store.height - 1)
            md = tip.metadata.metadata if tip.metadata else []
            # slot 4 fallback: chains written before the offset moved
            # to the consenter slot must still resume, not re-consume
            for slot in (self.OFFSET_MD_SLOT, 4):
                if len(md) > slot and md[slot] and len(md[slot]) == 8:
                    self._consumed = struct.unpack(
                        "<q", md[slot])[0] + 1
                    break
        # offset of the newest message sitting in the cutter's pending
        # batch (what a cut of the pending batch must be stamped with)
        self._pending_last = self._consumed - 1

    # -- consenter surface ------------------------------------------------
    def start(self) -> None:
        self._thread.start()

    def halt(self) -> None:
        self._halted.set()
        with self._timer_lock:
            if self._timer is not None:
                self._timer.cancel()
        self._thread.join(timeout=5)

    def wait_ready(self) -> None:
        if self._halted.is_set():
            raise ChainHaltedError("chain is halted")

    def order(self, env: m.Envelope, config_seq: int) -> None:
        self.wait_ready()
        self._broker.append(self._topic,
                            _encode(_NORMAL, env.encode(), config_seq))

    def configure(self, env: m.Envelope, config_seq: int) -> None:
        self.wait_ready()
        self._broker.append(self._topic,
                            _encode(_CONFIG, env.encode(), config_seq))

    # -- timeout -> TTC (reference: sendTimeToCut) ------------------------
    def _arm_timer(self, next_block: int) -> None:
        with self._timer_lock:
            if self._timer is not None or self._halted.is_set():
                return

            def fire():
                with self._timer_lock:
                    self._timer = None
                if not self._halted.is_set():
                    self._broker.append(self._topic,
                                        _encode(_TTC, b"", next_block))
            # fmtlint: allow[threads] -- one-shot batch-timeout Timer, cancelled under _timer_lock on halt; RegisteredThread has no delayed-start analog
            self._timer = threading.Timer(
                self._support.batch_timeout_s(), fire)
            self._timer.daemon = True
            self._timer.start()

    def _disarm_timer(self) -> None:
        with self._timer_lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None

    # -- the consume loop -------------------------------------------------
    def _write(self, batch, offset: int, is_config: bool = False,
               config_env: Optional[m.Envelope] = None) -> None:
        support = self._support
        block = support.writer.create_next_block(batch)
        md = block.metadata.metadata
        while len(md) <= self.OFFSET_MD_SLOT:
            md.append(b"")
        md[self.OFFSET_MD_SLOT] = struct.pack("<q", offset)
        if is_config:
            support.process_config(config_env, block)
        else:
            support.writer.write_block(block)

    def _run(self) -> None:
        support = self._support
        while not self._halted.is_set():
            msgs = self._broker.read(self._topic, self._consumed)
            for offset, raw in msgs:
                if self._halted.is_set():
                    return
                kind, number, payload = _decode(raw)
                if kind == _TTC:
                    # first TTC for the CURRENT next block cuts; stale
                    # duplicates (earlier numbers) are ignored.  The
                    # block is stamped with the last message INCLUDED
                    # (not the TTC's offset): a restart must re-consume
                    # anything that was still pending
                    if number == support.store.height:
                        batch = support.cutter.cut()
                        if batch:
                            self._disarm_timer()
                            self._write(batch, self._pending_last)
                    self._consumed = offset + 1
                    continue
                try:
                    env = m.Envelope.decode(payload)
                except Exception:
                    self._consumed = offset + 1
                    continue
                if kind == _CONFIG:
                    if number < support.sequence():
                        try:
                            env, _cfg, _seq = support.reprocess_config(env)
                        except Exception:
                            self._consumed = offset + 1
                            continue
                    pending = support.cutter.cut()
                    if pending:
                        self._disarm_timer()
                        self._write(pending, self._pending_last)
                    self._write([env], offset, is_config=True,
                                config_env=env)
                    self._consumed = offset + 1
                    continue
                if number < support.sequence():
                    try:
                        support.revalidate_normal(env)
                    except Exception:
                        self._consumed = offset + 1
                        continue
                batches, pending = support.cutter.ordered(env)
                for idx, batch in enumerate(batches):
                    self._disarm_timer()
                    # a batch contains THIS message only when it is the
                    # last one and nothing stayed pending; earlier
                    # batches end at the previous pending tail
                    contains_env = (idx == len(batches) - 1
                                    and not pending)
                    self._write(batch,
                                offset if contains_env
                                else self._pending_last)
                if pending:
                    self._pending_last = offset
                    self._arm_timer(support.store.height)
                self._consumed = offset + 1
