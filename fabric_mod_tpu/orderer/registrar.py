"""Multichannel registrar: per-channel chain resources + lifecycle.

(reference: orderer/common/multichannel/registrar.go — Initialize at
:155, BroadcastChannelSupport at :259, CreateChain at :340 — and
chainsupport.go:288's ChainSupport aggregation.)

A ChainSupport owns one channel's bundle (atomically swapped on config
commit), block cutter, block writer, ingress processor, and consenter.
The registrar maps channel ids to supports and bootstraps each from
its genesis (or tip config) block on open — the same
"ledger is the config store" recovery the reference does.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

from fabric_mod_tpu.channelconfig import Bundle, config_from_block
from fabric_mod_tpu.ledger.blkstorage import BlockStore
from fabric_mod_tpu.orderer.blockcutter import BlockCutter
from fabric_mod_tpu.orderer.blockwriter import BlockWriter, last_config_index
from fabric_mod_tpu.orderer.consensus import SoloChain
from fabric_mod_tpu.orderer.msgprocessor import (
    MsgRejectedError, StandardChannelProcessor)
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil


class RegistrarError(Exception):
    pass


class ChainSupport:
    """(reference: multichannel/chainsupport.go ChainSupport)"""

    def __init__(self, channel_id: str, store: BlockStore, bundle: Bundle,
                 signer, csp, verify_many=None, chain_factory=None):
        self.channel_id = channel_id
        self.store = store
        self._bundle = bundle
        self._bundle_lock = threading.Lock()
        self._csp = csp
        self.cutter = BlockCutter(bundle.batch_config())
        self.writer = BlockWriter(store, signer, channel_id)
        self.processor = StandardChannelProcessor(
            self.bundle, signer=signer, verify_many=verify_many)
        # consenter selection (reference: consenter registry keyed by
        # the channel's ConsensusType; solo is the default)
        if chain_factory is not None:
            self.chain = chain_factory(self)
        else:
            self.chain = SoloChain(self)

    # -- bundle access (atomic swap on config commit) --------------------
    def bundle(self) -> Bundle:
        with self._bundle_lock:
            return self._bundle

    def sequence(self) -> int:
        return self.bundle().sequence

    def batch_timeout_s(self) -> float:
        return self.bundle().orderer.batch_timeout_s

    # -- consenter callbacks ---------------------------------------------
    def process_config(self, config_env: m.Envelope,
                       block: m.Block) -> None:
        """Write a config block and swap the live bundle (reference:
        chainsupport WriteConfigBlock -> bundle update callback)."""
        _, new_config = config_from_block(block)
        new_bundle = Bundle(self.channel_id, new_config, self._csp)
        self.writer.write_block(block, is_config=True)
        with self._bundle_lock:
            self._bundle = new_bundle
        # batch parameters may have changed
        self.cutter.config = new_bundle.batch_config()

    def reprocess_config(self, env: m.Envelope) -> Tuple:
        wrapped, seq = self.processor.process_config_update_msg(env)
        return wrapped, True, seq

    def revalidate_normal(self, env: m.Envelope) -> None:
        self.processor.process_normal_msg(env)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self.chain.start()

    def halt(self) -> None:
        self.chain.halt()


class Registrar:
    """(reference: multichannel/registrar.go)"""

    def __init__(self, root_dir: str, signer, csp, verify_many=None,
                 chain_factory=None):
        self._root = root_dir
        self._signer = signer
        self._csp = csp
        self._verify_many = verify_many
        self._chain_factory = chain_factory
        self._chains: Dict[str, ChainSupport] = {}
        self._lock = threading.Lock()
        os.makedirs(root_dir, exist_ok=True)
        # Recover existing channels from disk (reference: Initialize)
        for name in sorted(os.listdir(root_dir)):
            path = os.path.join(root_dir, name)
            if os.path.isdir(path):
                self._open_channel(name, path)

    def _open_channel(self, channel_id: str, path: str) -> None:
        store = BlockStore(path)
        if store.height == 0:
            store.close()
            return
        # find the latest config block via the tip's last-config pointer
        tip = store.get_block_by_number(store.height - 1)
        lc = last_config_index(tip)
        cfg_block = store.get_block_by_number(lc or 0)
        cid, config = config_from_block(cfg_block)
        if cid != channel_id:
            raise RegistrarError(
                f"directory {channel_id!r} holds channel {cid!r}")
        bundle = Bundle(cid, config, self._csp)
        support = ChainSupport(cid, store, bundle, self._signer, self._csp,
                               self._verify_many,
                               chain_factory=self._chain_factory)
        self._chains[cid] = support
        support.start()

    # -- channel creation -------------------------------------------------
    def create_channel(self, genesis_block: m.Block) -> ChainSupport:
        """(reference: registrar.go:340 CreateChain — here from a
        pre-built genesis block, the configtxgen output)"""
        cid, config = config_from_block(genesis_block)
        with self._lock:
            if cid in self._chains:
                raise RegistrarError(f"channel {cid!r} exists")
            path = os.path.join(self._root, cid)
            store = BlockStore(path)
            if store.height == 0:
                store.add_block(genesis_block)
            bundle = Bundle(cid, config, self._csp)
            support = ChainSupport(cid, store, bundle, self._signer,
                                   self._csp, self._verify_many,
                                   chain_factory=self._chain_factory)
            self._chains[cid] = support
        support.start()
        return support

    def get_chain(self, channel_id: str) -> Optional[ChainSupport]:
        with self._lock:
            return self._chains.get(channel_id)

    def channel_ids(self):
        with self._lock:
            return sorted(self._chains)

    def broadcast_channel_support(self, env: m.Envelope
                                  ) -> Tuple[ChainSupport, bool]:
        """Route an incoming envelope: (support, is_config_update)
        (reference: registrar.go:259 BroadcastChannelSupport)."""
        ch = protoutil.envelope_channel_header(env)
        support = self.get_chain(ch.channel_id)
        if support is None:
            raise RegistrarError(f"unknown channel {ch.channel_id!r}")
        return support, ch.type == m.HeaderType.CONFIG_UPDATE

    def close(self) -> None:
        with self._lock:
            for support in self._chains.values():
                support.halt()
                support.store.close()
            self._chains.clear()
