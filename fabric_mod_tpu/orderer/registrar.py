"""Multichannel registrar: per-channel chain resources + lifecycle.

(reference: orderer/common/multichannel/registrar.go — Initialize at
:155, BroadcastChannelSupport at :259, CreateChain at :340 — and
chainsupport.go:288's ChainSupport aggregation.)

A ChainSupport owns one channel's bundle (atomically swapped on config
commit), block cutter, block writer, ingress processor, and consenter.
The registrar maps channel ids to supports and bootstraps each from
its genesis (or tip config) block on open — the same
"ledger is the config store" recovery the reference does.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

from fabric_mod_tpu.channelconfig import Bundle, config_from_block
from fabric_mod_tpu.ledger.blkstorage import BlockStore
from fabric_mod_tpu.orderer.blockcutter import BlockCutter
from fabric_mod_tpu.orderer.blockwriter import BlockWriter, last_config_index
from fabric_mod_tpu.orderer.consensus import SoloChain
from fabric_mod_tpu.orderer.msgprocessor import (
    MsgRejectedError, StandardChannelProcessor)
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.concurrency.locks import RegisteredLock


class RegistrarError(Exception):
    pass


class ChainSupport:
    """(reference: multichannel/chainsupport.go ChainSupport)"""

    def __init__(self, channel_id: str, store: BlockStore, bundle: Bundle,
                 signer, csp, verify_many=None, chain_factory=None):
        self.channel_id = channel_id
        self.store = store
        self._bundle = bundle
        self._bundle_lock = RegisteredLock("orderer.registrar._bundle_lock")
        self._csp = csp
        self.cutter = BlockCutter(bundle.batch_config())
        self.writer = BlockWriter(store, signer, channel_id)
        self.processor = StandardChannelProcessor(
            self.bundle, signer=signer, verify_many=verify_many)
        # consenter selection (reference: consenter registry keyed by
        # the channel's ConsensusType; solo is the default)
        if chain_factory is not None:
            self.chain = chain_factory(self)
        else:
            self.chain = SoloChain(self)

    # -- bundle access (atomic swap on config commit) --------------------
    def bundle(self) -> Bundle:
        with self._bundle_lock:
            return self._bundle

    def sequence(self) -> int:
        return self.bundle().sequence

    def batch_timeout_s(self) -> float:
        return self.bundle().orderer.batch_timeout_s

    # -- consenter callbacks ---------------------------------------------
    def process_config(self, config_env: m.Envelope,
                       block: m.Block) -> None:
        """Write a config block and swap the live bundle (reference:
        chainsupport WriteConfigBlock -> bundle update callback)."""
        _, new_config = config_from_block(block)
        new_bundle = Bundle(self.channel_id, new_config, self._csp)
        self.writer.write_block(block, is_config=True)
        with self._bundle_lock:
            self._bundle = new_bundle
        # batch parameters may have changed
        self.cutter.config = new_bundle.batch_config()

    def reprocess_config(self, env: m.Envelope) -> Tuple:
        wrapped, seq = self.processor.process_config_update_msg(env)
        return wrapped, True, seq

    def revalidate_normal(self, env: m.Envelope) -> None:
        self.processor.process_normal_msg(env)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self.chain.start()

    def halt(self) -> None:
        self.chain.halt()


class Registrar:
    """(reference: multichannel/registrar.go)"""

    def __init__(self, root_dir: str, signer, csp, verify_many=None,
                 chain_factory=None, block_fetcher=None,
                 consenters=None):
        """`block_fetcher`: callable(lo, hi) -> blocks, the cluster
        replication source used by follower channels and non-genesis
        joins (reference: the cluster block puller).  `consenters`:
        {consensus_type: factory(support) -> chain} — the consenter
        registry keyed by the channel's ConsensusType (reference:
        registrar.go's consenters map); `chain_factory` overrides it
        for every channel; with neither, channels run solo."""
        self._root = root_dir
        self._signer = signer
        self._csp = csp
        self._verify_many = verify_many
        self._chain_factory = chain_factory
        self._consenters = dict(consenters or {})
        self._block_fetcher = block_fetcher
        self._chains: Dict[str, ChainSupport] = {}
        # channel ids being joined/removed right now: reserved so a
        # concurrent join/remove of the same id cannot interleave
        self._busy: set = set()
        self._lock = RegisteredLock("orderer.registrar._lock")
        os.makedirs(root_dir, exist_ok=True)
        # Recover existing channels from disk (reference: Initialize).
        # Directories carrying a .joining marker died mid-onboarding:
        # their chains are incomplete and must NOT come up as active —
        # a re-join resumes the replication (onboarding.go's restart
        # stance).
        for name in sorted(os.listdir(root_dir)):
            path = os.path.join(root_dir, name)
            if os.path.isdir(path) and not os.path.exists(
                    os.path.join(path, ".joining")):
                self._open_channel(name, path)

    def _resolve_factory(self, bundle: Bundle):
        """Consenter selection by the channel's ConsensusType
        (reference: registrar.go consenters[consensusType]); an
        explicit chain_factory wins, an unregistered type runs solo."""
        if self._chain_factory is not None:
            return self._chain_factory
        return self._consenters.get(bundle.orderer.consensus_type)

    def _open_channel(self, channel_id: str, path: str) -> None:
        store = BlockStore(path)
        if store.height == 0:
            store.close()
            return
        # find the latest config block via the tip's last-config pointer
        tip = store.get_block_by_number(store.height - 1)
        lc = last_config_index(tip)
        cfg_block = store.get_block_by_number(lc or 0)
        cid, config = config_from_block(cfg_block)
        if cid != channel_id:
            raise RegistrarError(
                f"directory {channel_id!r} holds channel {cid!r}")
        bundle = Bundle(cid, config, self._csp)
        # follower channels stay followers across restarts (the
        # .follower marker) — a non-member must never come back up
        # ordering (reference: the follower chain registry)
        factory = self._resolve_factory(bundle)
        if os.path.exists(os.path.join(path, ".follower")):
            from fabric_mod_tpu.orderer.participation import FollowerChain

            def factory(support, fetch=self._block_fetcher):
                return FollowerChain(support, fetch)
        support = ChainSupport(cid, store, bundle, self._signer, self._csp,
                               self._verify_many,
                               chain_factory=factory)
        self._chains[cid] = support
        support.start()

    # -- channel creation -------------------------------------------------
    def create_channel(self, genesis_block: m.Block) -> ChainSupport:
        """(reference: registrar.go:340 CreateChain — here from a
        pre-built genesis block, the configtxgen output)"""
        cid, config = config_from_block(genesis_block)
        with self._lock:
            if cid in self._chains:
                raise RegistrarError(f"channel {cid!r} exists")
            path = os.path.join(self._root, cid)
            store = BlockStore(path)
            if store.height == 0:
                store.add_block(genesis_block)
            bundle = Bundle(cid, config, self._csp)
            support = ChainSupport(cid, store, bundle, self._signer,
                                   self._csp, self._verify_many,
                                   chain_factory=self._resolve_factory(
                                       bundle))
            self._chains[cid] = support
        support.start()
        return support

    # -- channel participation (reference: restapi.go:408 join/remove) ---
    def join_channel(self, join_block: m.Block, block_fetcher=None,
                     as_follower: bool = False) -> ChainSupport:
        """Join from a genesis block, or onboard from a later config
        block by replicating the chain first (anchored to the join
        block).  `as_follower` stores + follows without ordering
        (reference: follower/chain.go).  Replication runs OUTSIDE the
        registrar lock — a slow source must not stall the other
        channels' get_chain; the id is reserved instead."""
        import shutil
        from fabric_mod_tpu.orderer.participation import (
            FollowerChain, replicate_chain)
        cid, _config = config_from_block(join_block)
        fetch = block_fetcher or self._block_fetcher
        with self._lock:
            if cid in self._chains or cid in self._busy:
                raise RegistrarError(f"channel {cid!r} exists or is "
                                     "being joined/removed")
            self._busy.add(cid)
        store = None
        try:
            path = os.path.join(self._root, cid)
            marker = os.path.join(path, ".joining")
            if os.path.exists(marker):
                # a previous join died mid-replication: its partial
                # chain was never anchor-verified — wipe and restart
                shutil.rmtree(path, ignore_errors=True)
            os.makedirs(path, exist_ok=True)
            store = BlockStore(path)
            if as_follower:
                # BEFORE clearing .joining: a crash between the two
                # must never restart a requested follower as an
                # ordering member
                with open(os.path.join(path, ".follower"), "w"):
                    pass
            if join_block.header.number == 0:
                if store.height == 0:
                    store.add_block(join_block)
            else:
                with open(marker, "w"):
                    pass
                replicate_chain(store, join_block, fetch)
                os.remove(marker)
            # bundle from the latest config block now in the store
            tip = store.get_block_by_number(store.height - 1)
            lc = last_config_index(tip)
            cfg_block = store.get_block_by_number(lc or 0)
            _cid2, config = config_from_block(cfg_block)
            bundle = Bundle(cid, config, self._csp)
            if as_follower:
                def factory(support, f=fetch):
                    return FollowerChain(support, f)
            else:
                factory = self._resolve_factory(bundle)
            support = ChainSupport(cid, store, bundle, self._signer,
                                   self._csp, self._verify_many,
                                   chain_factory=factory)
            # start BEFORE publishing (still holding the _busy
            # reservation): a concurrent remove must never halt a
            # chain that was never started
            support.start()
            with self._lock:
                self._chains[cid] = support
        except Exception:
            if store is not None:
                store.close()
            raise
        finally:
            with self._lock:
                self._busy.discard(cid)
        return support

    def remove_channel(self, channel_id: str) -> None:
        """Halt + delete a channel's chain and storage (reference:
        restapi.go DELETE /channels/<id> → registrar RemoveChannel).
        The id stays reserved until the files are gone so a concurrent
        re-join cannot race the deletion."""
        import shutil
        with self._lock:
            support = self._chains.pop(channel_id, None)
            if support is None:
                raise RegistrarError(f"unknown channel {channel_id!r}")
            self._busy.add(channel_id)
        try:
            support.halt()
            support.store.close()
            shutil.rmtree(os.path.join(self._root, channel_id),
                          ignore_errors=True)
        finally:
            with self._lock:
                self._busy.discard(channel_id)

    def get_chain(self, channel_id: str) -> Optional[ChainSupport]:
        with self._lock:
            return self._chains.get(channel_id)

    def channel_ids(self):
        with self._lock:
            return sorted(self._chains)

    def broadcast_channel_support(self, env: m.Envelope
                                  ) -> Tuple[ChainSupport, bool]:
        """Route an incoming envelope: (support, is_config_update)
        (reference: registrar.go:259 BroadcastChannelSupport)."""
        ch = protoutil.envelope_channel_header(env)
        support = self.get_chain(ch.channel_id)
        if support is None:
            raise RegistrarError(f"unknown channel {ch.channel_id!r}")
        return support, ch.type == m.HeaderType.CONFIG_UPDATE

    def close(self) -> None:
        with self._lock:
            for support in self._chains.values():
                support.halt()
                support.store.close()
            self._chains.clear()
