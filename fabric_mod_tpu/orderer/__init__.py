"""The ordering service (reference: orderer/)."""
from fabric_mod_tpu.orderer.admission import (                           # noqa: F401
    AdmissionController, ResourceExhaustedError)
from fabric_mod_tpu.orderer.blockcutter import BatchConfig, BlockCutter  # noqa: F401
from fabric_mod_tpu.orderer.blockwriter import BlockWriter               # noqa: F401
from fabric_mod_tpu.orderer.broadcast import Broadcast, BroadcastError   # noqa: F401
from fabric_mod_tpu.orderer.consensus import SoloChain                   # noqa: F401
from fabric_mod_tpu.orderer.deliver import DeliverService                # noqa: F401
from fabric_mod_tpu.orderer.msgprocessor import (                        # noqa: F401
    MsgRejectedError, StandardChannelProcessor)
from fabric_mod_tpu.orderer.registrar import ChainSupport, Registrar     # noqa: F401
