"""Raft consensus for the ordering service.

(reference: orderer/consensus/etcdraft — the etcd/raft library driven
by chain.go:533's single-threaded FSM loop, WAL+snapshot storage in
storage.go, and leader-side block proposing at :791/:860.  This is an
original, compact Raft — same protocol rules, none of etcd's code:
randomized election timeouts, term/vote persistence, log matching,
leader commit rules (commit only entries of the current term by
counting replicas), follower log repair by decrementing next_index.)

Design decisions that mirror the reference's use of raft:
* The payload replicated through the log is a FULL serialized block
  (the leader cuts batches; followers never re-cut) — exactly
  etcdraft's "leader proposes block data" model, which makes apply
  deterministic across nodes regardless of local timers.
* Each node signs committed blocks with its own orderer identity;
  data/prev hashes are identical everywhere, metadata signatures are
  per-node (any of them satisfies the BlockValidation policy).
* Transport is a seam (`RaftTransport`): in-process delivery for
  tests, the gRPC cluster Step stream later — message schema is
  already wire-shaped dataclasses.

The node runs a single FSM thread (like chain.go:533): one queue
carries timer ticks, peer messages, and local proposals; all state
transitions happen on that thread.  Term/vote/log survive restarts
via a CRC-framed WAL (same framing as ledger/durable.py).
"""
from __future__ import annotations

import io
import os
import queue
import random
import struct
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple
from fabric_mod_tpu import faults
from fabric_mod_tpu.concurrency.threads import RegisteredThread
from fabric_mod_tpu.observability import tracing
from fabric_mod_tpu.observability.logging import get_logger
from fabric_mod_tpu.concurrency.locks import RegisteredLock

log = get_logger("orderer.raft")

# --- messages (wire-shaped; the gRPC cluster Step carries these) -----------


class RequestVote:
    __slots__ = ("term", "candidate", "last_log_index", "last_log_term")

    def __init__(self, term, candidate, last_log_index, last_log_term):
        self.term = term
        self.candidate = candidate
        self.last_log_index = last_log_index
        self.last_log_term = last_log_term


class VoteReply:
    __slots__ = ("term", "voter", "granted")

    def __init__(self, term, voter, granted):
        self.term = term
        self.voter = voter
        self.granted = granted


class AppendEntries:
    __slots__ = ("term", "leader", "prev_index", "prev_term", "entries",
                 "leader_commit")

    def __init__(self, term, leader, prev_index, prev_term, entries,
                 leader_commit):
        self.term = term
        self.leader = leader
        self.prev_index = prev_index
        self.prev_term = prev_term
        self.entries = entries          # [(term, bytes)]
        self.leader_commit = leader_commit


class AppendReply:
    __slots__ = ("term", "follower", "success", "match_index")

    def __init__(self, term, follower, success, match_index):
        self.term = term
        self.follower = follower
        self.success = success
        self.match_index = match_index


class InstallSnapshot:
    """Leader→lagging-follower state transfer when the entries the
    follower needs were compacted away (reference: etcdraft snapshot
    catch-up, chain.go:880 + storage.go:299 TakeSnapshot)."""

    __slots__ = ("term", "leader", "last_index", "last_term", "data")

    def __init__(self, term, leader, last_index, last_term, data):
        self.term = term
        self.leader = leader
        self.last_index = last_index   # last raft index the snapshot covers
        self.last_term = last_term
        self.data = data               # app-defined state pointer


class RaftTransport:
    """node_id -> deliver(msg).  In-process registry (the test fabric);
    a gRPC Step-stream adapter registers the same surface."""

    def __init__(self):
        self._handlers: Dict[str, Callable] = {}
        self._lock = RegisteredLock("orderer.raft._lock")
        self.partitioned: set = set()

    def register(self, node_id: str, handler: Callable) -> None:
        with self._lock:
            self._handlers[node_id] = handler

    def send(self, src: str, dst: str, msg) -> None:
        with self._lock:
            if src in self.partitioned or dst in self.partitioned:
                return
            handler = self._handlers.get(dst)
        if handler is not None:
            try:
                handler(src, msg)
            except Exception as e:
                log.debug("raft transport handler %s<-%s "
                          "failed: %r", dst, src, e)


# --- WAL -------------------------------------------------------------------

_HARDSTATE, _ENTRY, _SNAPSHOT = 0, 1, 2


class RaftWAL:
    """Append-only persistence of (term, voted_for) + log entries +
    snapshot markers (reference: etcd WAL via storage.go:244; same
    crash contract — torn tails cropped by CRC framing).

    A snapshot marker (snap_index, snap_term, app data) says "entries
    ≤ snap_index are folded into app state"; `compact` rewrites the
    file to a marker plus the retained suffix, bounding WAL size the
    way storage.go:299/gc does.  Like etcd, compaction keeps a margin
    of entries BEHIND snap_index (SnapshotCatchUpEntries) so slightly
    lagging followers are repaired by AppendEntries, not snapshots —
    hence the separate log base: entries[i] holds raft index
    base + i + 1, with base ≤ snap_index ≤ last_index.

    Group commit (FABRIC_MOD_TPU_WAL_GROUP_COMMIT): `append` writes
    the frame buffered and defers the fsync to the next `sync()`
    barrier — one physical fsync covers every entry appended since the
    last barrier (all frames share one handle).  The node places the
    barrier at every durability-before-ack point (before a follower's
    AppendReply, before the leader counts itself in the quorum), so
    the crash contract is unchanged: a torn/unsynced tail was never
    acked, CRC replay crops it, and AppendEntries repair refills it.
    Unarmed, `append` syncs inline — the pre-PR-16 fsync-per-entry
    behavior.  `sync_count` counts PHYSICAL fsyncs in both modes (the
    kill-harness asserts the N→O(1) collapse against it)."""

    def __init__(self, path: str):
        from fabric_mod_tpu.utils import knobs
        self._path = path
        self.term = 0
        self.voted_for: Optional[str] = None
        self.snap_index = 0
        self.snap_term = 0
        self.snap_data = b""
        self.base = 0            # index of the entry before entries[0]
        self.base_term = 0
        self.entries: List[Tuple[int, bytes]] = []
        self._group = bool(
            knobs.get_bool("FABRIC_MOD_TPU_WAL_GROUP_COMMIT"))
        self._dirty = False
        self.sync_count = 0
        if os.path.exists(path):
            self._replay()
        self._f = open(path, "ab")

    def _replay(self) -> None:
        raw = open(self._path, "rb").read()
        pos = 0
        good_end = 0
        while pos + 8 <= len(raw):
            ln, crc = struct.unpack_from("<II", raw, pos)
            end = pos + 8 + ln
            if end > len(raw):
                break
            payload = raw[pos + 8:end]
            if zlib.crc32(payload) != crc:
                break
            kind = payload[0]
            if kind == _HARDSTATE:
                (self.term,) = struct.unpack_from("<q", payload, 1)
                (vl,) = struct.unpack_from("<I", payload, 9)
                self.voted_for = (payload[13:13 + vl].decode()
                                  if vl else None)
            elif kind == _ENTRY:
                eterm, upto = struct.unpack_from("<qq", payload, 1)
                data = payload[17:]
                # upto = the index this entry lands at; truncate any
                # conflicting suffix (log repair happened before write)
                local = upto - self.base
                if local >= 1:
                    del self.entries[local - 1:]
                    self.entries.append((eterm, data))
            elif kind == _SNAPSHOT:
                (sidx, sterm, base,
                 bterm) = struct.unpack_from("<qqqq", payload, 1)
                self.snap_index = sidx
                self.snap_term = sterm
                self.base = base
                self.base_term = bterm
                self.snap_data = payload[33:]
                self.entries = []
            good_end = end
            pos = end
        if good_end < len(raw):
            with open(self._path, "r+b") as f:
                f.truncate(good_end)

    def _frame(self, payload: bytes) -> bytes:
        return struct.pack("<II", len(payload),
                           zlib.crc32(payload)) + payload

    # -- index helpers (1-based raft indices) ----------------------------
    @property
    def last_index(self) -> int:
        return self.base + len(self.entries)

    def term_at(self, index: int) -> int:
        """Term of `index`; only valid for base ≤ index ≤ last."""
        if index == self.base:
            return self.base_term
        return self.entries[index - self.base - 1][0]

    def entry(self, index: int) -> Tuple[int, bytes]:
        return self.entries[index - self.base - 1]

    def entries_from(self, index: int, limit: int) -> List[Tuple[int, bytes]]:
        s = index - self.base - 1
        return self.entries[s:s + limit]

    # -- writes -----------------------------------------------------------
    def sync(self) -> None:
        """The group-commit barrier: flush + ONE fsync makes every
        frame written since the last barrier durable.  A no-op when
        nothing is pending, so heartbeat-path callers cost nothing.
        In drop mode the `orderer.wal.sync` fault point swallows the
        physical fsync — the injected lost-durability window the
        torn-tail tests crash into."""
        if not self._dirty:
            return
        with tracing.span("wal.sync"):
            if faults.point("orderer.wal.sync"):
                return
            self._f.flush()
            os.fsync(self._f.fileno())
            self.sync_count += 1
            self._dirty = False

    def save_hardstate(self, term: int, voted_for: Optional[str]) -> None:
        self.term = term
        self.voted_for = voted_for
        v = (voted_for or "").encode()
        payload = (bytes([_HARDSTATE]) + struct.pack("<q", term)
                   + struct.pack("<I", len(v)) + v)
        self._f.write(self._frame(payload))
        # term/vote must be durable BEFORE any message acts on them
        # (§5.1 election safety) — never deferred, in either mode; the
        # one fsync also covers any entries buffered before it
        self._dirty = True
        self.sync()

    def append(self, index: int, term: int, data: bytes) -> None:
        """Write entry at 1-based `index`, truncating conflicts.
        Group mode defers the fsync to the caller's `sync()` barrier."""
        local = index - self.base
        if local < 1:
            return                         # already folded into snapshot
        del self.entries[local - 1:]
        self.entries.append((term, data))
        payload = (bytes([_ENTRY]) + struct.pack("<qq", term, index)
                   + data)
        self._f.write(self._frame(payload))
        # crash seam: an error-mode rule kills the orderer AFTER the
        # frame hit the (possibly still unsynced) file but BEFORE any
        # ack could be built on it — the torn-tail window a restarted
        # node's _replay() crops, then AppendEntries repair refills
        faults.point("orderer.wal.crash")
        self._dirty = True
        if not self._group:
            self.sync()

    def _rewrite(self, snap_index: int, snap_term: int, snap_data: bytes,
                 base: int, base_term: int,
                 keep: List[Tuple[int, bytes]]) -> None:
        """Atomically replace the file: hardstate + snapshot marker +
        retained entries (absolute indices base+1…)."""
        tmp = self._path + ".compact"
        with open(tmp, "wb") as f:
            v = (self.voted_for or "").encode()
            f.write(self._frame(bytes([_HARDSTATE])
                                + struct.pack("<q", self.term)
                                + struct.pack("<I", len(v)) + v))
            f.write(self._frame(bytes([_SNAPSHOT])
                                + struct.pack("<qqqq", snap_index,
                                              snap_term, base, base_term)
                                + snap_data))
            for i, (eterm, data) in enumerate(keep):
                f.write(self._frame(bytes([_ENTRY])
                                    + struct.pack("<qq", eterm,
                                                  base + i + 1)
                                    + data))
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self._path)
        self._f = open(self._path, "ab")
        self._dirty = False       # the rewrite fsynced everything kept
        self.snap_index = snap_index
        self.snap_term = snap_term
        self.snap_data = snap_data
        self.base = base
        self.base_term = base_term
        self.entries = keep

    def compact(self, upto: int, term: int, data: bytes,
                margin: int = 0) -> None:
        """Record a snapshot at `upto` (which must be applied) and drop
        entries ≤ upto - margin; the margin stays available for
        AppendEntries repair of slightly-lagging followers."""
        if upto <= self.snap_index:
            return
        new_base = max(self.base, upto - margin)
        keep = self.entries[new_base - self.base:]
        self._rewrite(upto, term, data,
                      new_base, self.term_at(new_base), keep)

    def install_snapshot(self, index: int, term: int, data: bytes) -> None:
        """Replace the entire log with a received snapshot."""
        self._rewrite(index, term, data, index, term, [])

    def close(self) -> None:
        self.sync()               # graceful stop loses nothing buffered
        self._f.close()


# --- the node --------------------------------------------------------------

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class RaftNode:
    """One replica.  `apply_cb(index, data)` fires exactly once per
    committed entry, in order, on the FSM thread."""

    def __init__(self, node_id: str, peers: List[str],
                 transport: RaftTransport, wal_path: str,
                 apply_cb: Callable[[int, bytes], None],
                 election_timeout: Tuple[float, float] = (0.15, 0.3),
                 heartbeat_s: float = 0.05,
                 rng: Optional[random.Random] = None,
                 snapshot_interval: Optional[int] = None,
                 snapshot_cb: Optional[Callable[[], bytes]] = None,
                 install_cb: Optional[Callable[[int, bytes], None]] = None,
                 clock=None):
        self.id = node_id
        self.peers = [p for p in peers if p != node_id]
        self._transport = transport
        self._wal = RaftWAL(wal_path)
        self._apply = apply_cb
        self._eto = election_timeout
        self._hb = heartbeat_s
        self._rng = rng or random.Random()
        # snapshotting (reference: SnapshotIntervalSize, storage.go:299):
        # every `snapshot_interval` applied entries, snapshot_cb() is
        # asked for an app-state pointer and the log is compacted up to
        # last_applied; install_cb(index, data) must restore/catch up
        # app state when a snapshot arrives from the leader.
        self._snap_every = snapshot_interval
        self._snap_margin = (min(self.SNAPSHOT_CATCHUP_ENTRIES,
                                 snapshot_interval // 2)
                             if snapshot_interval else 0)
        self._snapshot_cb = snapshot_cb
        self._install_cb = install_cb

        self.state = FOLLOWER
        self.member = True                 # False once reconfigured out
        self.leader_id: Optional[str] = None
        self.commit_index = self._wal.snap_index
        self.last_applied = self._wal.snap_index
        self._votes: set = set()
        self._next_index: Dict[str, int] = {}
        self._match_index: Dict[str, int] = {}
        self._snap_sent: Dict[str, float] = {}
        # optimistic pipelining (FABRIC_MOD_TPU_RAFT_PIPELINE = window
        # depth): _opt_next[p] tracks the first index NOT yet sent to
        # p (≥ the acked _next_index); the propose path pushes up to
        # depth × MAX_ENTRIES_PER_APPEND un-acked entries ahead of the
        # acks instead of one window per reply round-trip.  Replies
        # repair it: success advances it, failure resets it to the
        # repaired _next_index (the classic decrement/hint semantics
        # untouched).  Safe under any FIFO-per-sender transport — the
        # in-process RaftTransport delivers synchronously in order
        from fabric_mod_tpu.utils import knobs as _knobs
        self._pipeline = max(
            0, _knobs.get_int("FABRIC_MOD_TPU_RAFT_PIPELINE"))
        self._opt_next: Dict[str, int] = {}
        # bounded FSM queue (FABRIC_MOD_TPU_RAFT_QUEUE, 0 = unbounded):
        # a peer flooding Step messages can no longer grow host memory
        # without bound — overflow drops the MESSAGE (raft re-sends;
        # AppendEntries/vote traffic is idempotent-by-protocol) and
        # counts it, the same observability as the chain-level drops
        from fabric_mod_tpu.utils import knobs
        self._q: "queue.Queue" = queue.Queue(
            maxsize=max(0, knobs.get_int("FABRIC_MOD_TPU_RAFT_QUEUE")))
        self._stop = threading.Event()
        self._deadline = 0.0
        # pluggable time source: election/heartbeat deadlines are
        # compared against self._now(), so a ManualClock (utils/
        # fakeclock.py) makes timer behavior deterministic — the
        # kill-harness tests stop depending on wall-clock under CPU
        # load.  A subscribable clock wakes the FSM on advance so the
        # queue wait re-evaluates the (fake) deadline.
        if clock is None:
            self._now = time.monotonic
        else:
            self._now = clock.monotonic
            subscribe = getattr(clock, "subscribe", None)
            if subscribe is not None:
                # advisory wakeup: a full queue is by definition a
                # non-empty queue, so a dropped noop never strands the
                # FSM wait
                subscribe(lambda: self._put_advisory(("noop",)))
        # machine-checked single-threaded-FSM contract (the -race
        # analog, utils/racecheck.py): every state transition must run
        # on the FSM thread — a stray cross-thread call raises
        from fabric_mod_tpu.utils.racecheck import ThreadOwnership
        self._fsm_owner = ThreadOwnership(f"raft-fsm[{node_id}]")
        self._thread = RegisteredThread(
            target=self._run, name=f"raft-fsm[{node_id}]",
            structure="orderer.raft")
        transport.register(node_id, self._on_transport_msg)

    # -- queue admission --------------------------------------------------
    def _on_transport_msg(self, src: str, msg) -> None:
        try:
            self._q.put_nowait(("msg", src, msg))
        except queue.Full:
            # surface the drop (the old unbounded queue grew instead):
            # the protocol repairs — heartbeats re-send entries, votes
            # re-request on timeout
            from fabric_mod_tpu.orderer.admission import \
                chain_drop_counter
            chain_drop_counter().with_labels("raft_msg").add(1)

    def _put_advisory(self, item) -> None:
        """Wakeup-only items: dropping one on a full queue is safe —
        the queue being full already wakes the FSM."""
        try:
            self._q.put_nowait(item)
        except queue.Full:
            pass

    # -- public ----------------------------------------------------------
    def start(self) -> None:
        self._reset_election_timer()
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._put_advisory(("noop",))
        self._thread.join(timeout=5)
        self._wal.close()

    def propose(self, data: bytes) -> bool:
        """Leader-only; returns False when not the leader OR when the
        FSM queue is full (caller forwards to `leader_id` or requeues —
        reference: chain Submit :494).  The bounded-queue False is
        honest backpressure: the proposer re-offers instead of the old
        unbounded enqueue."""
        if self.state != LEADER:
            return False
        try:
            self._q.put_nowait(("propose", data))
        except queue.Full:
            from fabric_mod_tpu.orderer.admission import \
                chain_drop_counter
            chain_drop_counter().with_labels("raft_msg").add(1)
            return False
        return True

    def propose_many(self, datas: List[bytes]) -> bool:
        """Leader-only multi-entry proposal: every entry lands in the
        log in ONE FSM turn — one group-commit barrier, one
        replication broadcast — or none does (False on not-leader or
        full queue, same contract as `propose`)."""
        if self.state != LEADER:
            return False
        if not datas:
            return True
        try:
            self._q.put_nowait(("propose_many", list(datas)))
        except queue.Full:
            from fabric_mod_tpu.orderer.admission import \
                chain_drop_counter
            chain_drop_counter().with_labels("raft_msg").add(1)
            return False
        return True

    def update_peers(self, node_ids) -> None:
        """Reconfigure the member set (applied on the FSM thread).
        Every replica calls this when the SAME committed config entry
        applies, so membership switches at identical log points —
        apply-time reconfiguration, the reference's ConfChange-on-
        config-block model (etcdraft chain.go's raft.ApplyConfChange).
        Callers must change at most ONE member per config (quorum
        overlap; enforced by the chain layer).  A reconfig is never
        dropped: callers off the FSM thread use a blocking put (the
        FSM drains); the FSM thread itself (the apply path) must not
        block against its own consumer, so a full queue applies the
        reconfig synchronously instead."""
        if threading.current_thread() is self._thread:
            try:
                self._q.put_nowait(("reconfig", list(node_ids)))
            except queue.Full:
                self._on_reconfig(list(node_ids))
            return
        self._q.put(("reconfig", list(node_ids)))

    @property
    def last_index(self) -> int:
        return self._wal.last_index

    def _last_term(self) -> int:
        return self._wal.term_at(self._wal.last_index)

    # -- FSM loop (reference: chain.go:533 run) ---------------------------
    def _run(self) -> None:
        self._fsm_owner.claim()
        while not self._stop.is_set():
            timeout = max(0.0, self._deadline - self._now())
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:
                # the blocking wait above is REAL time; the deadline is
                # clock time.  Under a manual clock they diverge, so a
                # real-time expiry only fires the timer if clock time
                # agrees (frozen clock => frozen timers, by design)
                if self._now() >= self._deadline:
                    self._on_timer()
                continue
            kind = item[0]
            if kind == "msg":
                self._on_message(item[1], item[2])
            elif kind == "propose":
                self._on_propose(item[1])
            elif kind == "propose_many":
                self._on_propose_many(item[1])
            elif kind == "reconfig":
                self._on_reconfig(item[1])
            # manual clocks block the queue wait in REAL time while
            # deadlines live in FAKE time: re-check expiry on every
            # wakeup (noop items from clock.advance land here)
            if self._now() >= self._deadline and not self._stop.is_set():
                self._on_timer()

    def _on_reconfig(self, node_ids) -> None:
        self._fsm_owner.guard()
        self.member = self.id in node_ids
        self.peers = [p for p in node_ids if p != self.id]
        for gone in [p for p in self._next_index
                     if p not in self.peers]:
            self._next_index.pop(gone, None)
            self._match_index.pop(gone, None)
        if not self.member and self.state == LEADER:
            # a removed leader steps down; it keeps serving as a
            # non-voting observer until halted (reference: the raft
            # eviction path — chain.go:1335)
            self._step_down(self._wal.term)

    def _reset_election_timer(self) -> None:
        self._deadline = (self._now()
                          + self._rng.uniform(*self._eto))

    def _on_timer(self) -> None:
        self._fsm_owner.guard()
        if self.state == LEADER:
            self._broadcast_append()
            self._deadline = self._now() + self._hb
        elif self.member:
            self._start_election()
        else:
            self._reset_election_timer()   # observers never campaign

    # -- elections --------------------------------------------------------
    def _start_election(self) -> None:
        self.state = CANDIDATE
        self._wal.save_hardstate(self._wal.term + 1, self.id)
        self._votes = {self.id}
        self.leader_id = None
        self._reset_election_timer()
        msg = RequestVote(self._wal.term, self.id, self.last_index,
                          self._last_term())
        for p in self.peers:
            self._transport.send(self.id, p, msg)
        self._maybe_win()

    def _maybe_win(self) -> None:
        if self.state == CANDIDATE and \
                len(self._votes) * 2 > len(self.peers) + 1:
            self.state = LEADER
            self.leader_id = self.id
            self._next_index = {p: self.last_index + 1
                                for p in self.peers}
            self._match_index = {p: 0 for p in self.peers}
            self._opt_next = dict(self._next_index)
            # no-op barrier entry: lets the new leader commit prior-term
            # entries per the current-term counting rule
            self._append_local(b"")
            self._wal.sync()               # durable before self-quorum
            self._advance_commit()         # single-node quorum
            self._broadcast_append()
            self._deadline = self._now() + self._hb

    def _step_down(self, term: int) -> None:
        if term > self._wal.term:
            self._wal.save_hardstate(term, None)
        self.state = FOLLOWER
        self._votes = set()
        # a deposed leader must not keep advertising itself: consumers
        # (submit forwarding) would loop messages back to this node
        if self.leader_id == self.id:
            self.leader_id = None
        self._reset_election_timer()

    # -- log machinery ----------------------------------------------------
    def _append_local(self, data: bytes) -> int:
        idx = self.last_index + 1
        self._wal.append(idx, self._wal.term, data)
        return idx

    def _on_propose(self, data: bytes) -> None:
        self._fsm_owner.guard()
        if self.state != LEADER:
            return
        self._append_local(data)
        self._wal.sync()                   # durable before self-quorum
        self._advance_commit()             # single-node quorum
        self._broadcast_append(optimistic=True)

    def _on_propose_many(self, datas: List[bytes]) -> None:
        self._fsm_owner.guard()
        if self.state != LEADER:
            return
        for data in datas:
            self._append_local(data)
        self._wal.sync()                   # ONE barrier for the burst
        self._advance_commit()
        self._broadcast_append(optimistic=True)

    def _broadcast_append(self, optimistic: bool = False) -> None:
        for p in self.peers:
            if optimistic and self._pipeline > 0:
                self._pipeline_append(p)
            else:
                self._send_append(p)

    MAX_ENTRIES_PER_APPEND = 64            # reference: MaxInflightBlocks

    def _send_append(self, peer: str) -> None:
        nxt = self._next_index.get(peer, self.last_index + 1)
        if nxt <= self._wal.base:
            # the entries the follower needs were compacted: ship the
            # snapshot instead (reference: chain.go:880 catchUp).
            # Installation triggers an app-level block fetch, so do
            # not hammer a slow installer on every heartbeat.
            now = self._now()
            if now - self._snap_sent.get(peer, 0.0) >= 10 * self._hb:
                self._snap_sent[peer] = now
                self._transport.send(self.id, peer, InstallSnapshot(
                    self._wal.term, self.id, self._wal.snap_index,
                    self._wal.snap_term, self._wal.snap_data))
            return
        prev_index = nxt - 1
        prev_term = (self._wal.term_at(prev_index)
                     if (self._wal.base <= prev_index
                         <= self._wal.last_index) else 0)
        # cap the suffix: a lagging follower is repaired in bounded
        # chunks instead of O(K^2) full-suffix resends per heartbeat
        entries = self._wal.entries_from(nxt, self.MAX_ENTRIES_PER_APPEND)
        self._transport.send(self.id, peer, AppendEntries(
            self._wal.term, self.id, prev_index, prev_term,
            list(entries), self.commit_index))
        self._opt_next[peer] = max(self._opt_next.get(peer, 0),
                                   nxt + len(entries))

    def _pipeline_append(self, peer: str) -> None:
        """Windowed optimistic sends (FABRIC_MOD_TPU_RAFT_PIPELINE):
        push the un-sent suffix in MAX_ENTRIES_PER_APPEND chunks, up
        to `depth` windows beyond the acked `_next_index`, without
        waiting a reply round-trip per window.  A dropped window
        (injected at `orderer.raft.replicate`, or a real loss) is
        repaired by the heartbeat resend from `_next_index` plus the
        classic failure-reply backoff — the repair path is untouched."""
        nxt = self._next_index.get(peer, self.last_index + 1)
        if nxt <= self._wal.base:
            self._send_append(peer)        # snapshot catch-up path
            return
        opt = max(self._opt_next.get(peer, nxt), nxt)
        limit = min(self.last_index,
                    nxt - 1 + self._pipeline * self.MAX_ENTRIES_PER_APPEND)
        sent_any = False
        while opt <= limit:
            if not (self._wal.base <= opt - 1 <= self._wal.last_index):
                break                      # suffix compacted mid-flight
            entries = self._wal.entries_from(
                opt, min(self.MAX_ENTRIES_PER_APPEND, limit - opt + 1))
            if not entries:
                break
            with tracing.span("raft.replicate"):
                if faults.point("orderer.raft.replicate"):
                    return                 # injected window drop
                self._transport.send(self.id, peer, AppendEntries(
                    self._wal.term, self.id, opt - 1,
                    self._wal.term_at(opt - 1), list(entries),
                    self.commit_index))
            opt += len(entries)
            self._opt_next[peer] = opt
            sent_any = True
        if not sent_any:
            # nothing new in the window: still propagate term/commit
            # (the empty-append heartbeat the unpipelined path sends)
            prev = min(opt, self.last_index + 1) - 1
            if self._wal.base <= prev <= self._wal.last_index:
                self._transport.send(self.id, peer, AppendEntries(
                    self._wal.term, self.id, prev,
                    self._wal.term_at(prev), [], self.commit_index))

    # -- message handling --------------------------------------------------
    def _on_message(self, src: str, msg) -> None:
        self._fsm_owner.guard()
        if isinstance(msg, RequestVote):
            self._on_request_vote(msg)
        elif isinstance(msg, VoteReply):
            self._on_vote_reply(msg)
        elif isinstance(msg, AppendEntries):
            self._on_append(msg)
        elif isinstance(msg, AppendReply):
            self._on_append_reply(msg)
        elif isinstance(msg, InstallSnapshot):
            self._on_install_snapshot(msg)

    def _on_request_vote(self, msg: RequestVote) -> None:
        if msg.candidate not in self.peers:
            return                         # non-members cannot campaign
        if msg.term > self._wal.term:
            self._step_down(msg.term)
        granted = False
        if msg.term == self._wal.term and \
                self._wal.voted_for in (None, msg.candidate):
            # candidate's log must be at least as up-to-date (§5.4.1)
            up_to_date = (msg.last_log_term, msg.last_log_index) >= \
                (self._last_term(), self.last_index)
            if up_to_date:
                granted = True
                self._wal.save_hardstate(self._wal.term, msg.candidate)
                self._reset_election_timer()
        self._transport.send(self.id, msg.candidate, VoteReply(
            self._wal.term, self.id, granted))

    def _on_vote_reply(self, msg: VoteReply) -> None:
        if msg.term > self._wal.term:
            self._step_down(msg.term)
            return
        if self.state == CANDIDATE and msg.term == self._wal.term \
                and msg.granted:
            self._votes.add(msg.voter)
            self._maybe_win()

    def _on_append(self, msg: AppendEntries) -> None:
        if msg.term > self._wal.term or \
                (msg.term == self._wal.term and self.state != FOLLOWER):
            self._step_down(msg.term)
        if msg.term < self._wal.term:
            self._transport.send(self.id, msg.leader, AppendReply(
                self._wal.term, self.id, False, 0))
            return
        self.leader_id = msg.leader
        self._reset_election_timer()
        # log matching check (indices ≤ snap_index are committed by
        # definition — the snapshot only ever covers applied entries —
        # so matching is checked from max(prev, snap_index) up)
        snap = self._wal.snap_index
        if msg.prev_index > self.last_index:
            # reply our last index as a repair hint so the leader jumps
            # straight there instead of decrementing one per round-trip
            self._transport.send(self.id, msg.leader, AppendReply(
                self._wal.term, self.id, False, self.last_index))
            return
        if msg.prev_index > snap and msg.prev_index > 0:
            if self._wal.term_at(msg.prev_index) != msg.prev_term:
                self._transport.send(self.id, msg.leader, AppendReply(
                    self._wal.term, self.id, False, msg.prev_index - 1))
                return
        # append (truncating conflicts; entries folded into our
        # snapshot are skipped — they are already applied state)
        idx = msg.prev_index
        for eterm, data in msg.entries:
            idx += 1
            if idx <= snap:
                continue
            if idx <= self.last_index:
                if self._wal.term_at(idx) == eterm:
                    continue               # already have it
            self._wal.append(idx, eterm, data)
        # durability-before-ack: ONE barrier covers the whole message's
        # entries (group mode) before they count toward any quorum —
        # the success reply below is the ack the leader commits on
        self._wal.sync()
        if msg.leader_commit > self.commit_index:
            # §5.3: commit at most up to the last entry THIS message
            # matched/appended — the suffix beyond it is unverified
            # under reordered delivery
            last_new = msg.prev_index + len(msg.entries)
            self.commit_index = max(self.commit_index,
                                    min(msg.leader_commit, last_new))
            self._apply_committed()
        self._transport.send(self.id, msg.leader, AppendReply(
            self._wal.term, self.id, True, idx))

    def _on_append_reply(self, msg: AppendReply) -> None:
        if msg.term > self._wal.term:
            self._step_down(msg.term)
            return
        if self.state != LEADER or msg.term != self._wal.term:
            return
        if msg.success:
            self._match_index[msg.follower] = max(
                self._match_index.get(msg.follower, 0), msg.match_index)
            self._next_index[msg.follower] = \
                self._match_index[msg.follower] + 1
            self._opt_next[msg.follower] = max(
                self._opt_next.get(msg.follower, 0),
                self._next_index[msg.follower])
            self._advance_commit()
            if self._pipeline > 0 and \
                    self._opt_next[msg.follower] <= self.last_index:
                # an ack freed window room: keep the pipe full
                self._pipeline_append(msg.follower)
        else:
            # repair: back off, jumping straight to the follower's
            # hinted last index when it is further behind (§5.3);
            # every optimistic send past the mismatch is void — resend
            # from the repaired index
            cur = self._next_index.get(msg.follower, self.last_index + 1)
            self._next_index[msg.follower] = max(
                1, min(cur - 1, msg.match_index + 1))
            self._opt_next[msg.follower] = self._next_index[msg.follower]
            self._send_append(msg.follower)

    def _advance_commit(self) -> None:
        """Commit the highest index replicated on a majority whose
        entry is from the CURRENT term (§5.4.2)."""
        for n in range(self.last_index,
                       max(self.commit_index, self._wal.snap_index), -1):
            if self._wal.term_at(n) != self._wal.term:
                break
            count = 1 + sum(1 for p in self.peers
                            if self._match_index.get(p, 0) >= n)
            if count * 2 > len(self.peers) + 1:
                self.commit_index = n
                self._apply_committed()
                self._broadcast_append()   # propagate the commit index
                break

    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            nxt = self.last_applied + 1
            term, data = self._wal.entry(nxt)
            if data:                       # skip no-op barrier entries
                try:
                    self._apply(nxt, data)
                except Exception:
                    # do NOT advance past a failed apply: skipping a
                    # committed entry silently diverges this node's
                    # chain; stop and retry on the next commit signal
                    return
            self.last_applied = nxt
        self._maybe_compact()

    # entries retained BEHIND the snapshot point so a follower that
    # missed only a few messages is repaired by plain AppendEntries
    # instead of the full snapshot+fetch path (reference: etcd's
    # SnapshotCatchUpEntries)
    SNAPSHOT_CATCHUP_ENTRIES = 16

    def _maybe_compact(self) -> None:
        """Fold applied entries into a snapshot every `snapshot_interval`
        applies (reference: storage.go:299 TakeSnapshot + gc)."""
        if not self._snap_every or self._snapshot_cb is None:
            return
        if self.last_applied - self._wal.snap_index < self._snap_every:
            return
        try:
            data = self._snapshot_cb()
        except Exception:
            return                         # keep the log; retry later
        self._wal.compact(self.last_applied,
                          self._wal.term_at(self.last_applied), data,
                          margin=self._snap_margin)

    def _on_install_snapshot(self, msg: InstallSnapshot) -> None:
        if msg.term > self._wal.term:
            self._step_down(msg.term)
        if msg.term < self._wal.term:
            self._transport.send(self.id, msg.leader, AppendReply(
                self._wal.term, self.id, False, 0))
            return
        if self.state != FOLLOWER:
            self._step_down(msg.term)
        self.leader_id = msg.leader
        self._reset_election_timer()
        if msg.last_index <= self.commit_index:
            # nothing to install; tell the leader where we really are
            # so it resumes AppendEntries from there
            self._transport.send(self.id, msg.leader, AppendReply(
                self._wal.term, self.id, True, self.commit_index))
            return
        # the app must be able to reconstruct state up to last_index
        # (for the orderer: pull the missing blocks); refuse otherwise —
        # accepting would silently skip committed entries
        if self._install_cb is None:
            self._transport.send(self.id, msg.leader, AppendReply(
                self._wal.term, self.id, False, self.commit_index))
            return
        try:
            self._install_cb(msg.last_index, msg.data)
        except Exception:
            self._transport.send(self.id, msg.leader, AppendReply(
                self._wal.term, self.id, False, self.commit_index))
            return
        self._wal.install_snapshot(msg.last_index, msg.last_term, msg.data)
        self.commit_index = msg.last_index
        self.last_applied = msg.last_index
        self._transport.send(self.id, msg.leader, AppendReply(
            self._wal.term, self.id, True, msg.last_index))
