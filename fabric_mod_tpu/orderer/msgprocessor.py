"""Orderer ingress message processing.

(reference: orderer/common/msgprocessor — StandardChannel at
standardchannel.go:70 with its filter chain, SigFilter.Apply at
sigfilter.go:41, and the config-update path ProcessConfigUpdateMsg.)

The filters: reject empty envelopes, enforce the channel's
absolute_max_bytes, and require the channel Writers policy over the
envelope's signature — the policy engine's batch-first evaluators do
the verify (a single envelope rides the host path; gossip-storm-style
ingress floods batch through the same seam).
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

from fabric_mod_tpu.channelconfig import (
    ConfigTxError, extract_config_update, propose_config_update)
from fabric_mod_tpu.channelconfig.bundle import Bundle
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil


class MsgRejectedError(Exception):
    pass


CHANNEL_WRITERS = "/Channel/Writers"


class StandardChannelProcessor:
    """Per-channel ingress processor.  `bundle_fn` returns the CURRENT
    bundle (atomically swapped on config commit), so every message is
    judged under the config in force at processing time — the
    reference re-reads its config sequence the same way."""

    def __init__(self, bundle_fn: Callable[[], Bundle],
                 signer=None, verify_many=None):
        self._bundle = bundle_fn
        self._signer = signer          # orderer identity for CONFIG wraps
        self._verify_many = verify_many

    # -- classification (reference: registrar BroadcastChannelSupport) --
    @staticmethod
    def classify(env: m.Envelope) -> int:
        ch = protoutil.envelope_channel_header(env)
        return ch.type

    # -- filters ---------------------------------------------------------
    def _apply_filters(self, env: m.Envelope, bundle: Bundle) -> None:
        if not env.payload:
            raise MsgRejectedError("empty envelope")
        oc = bundle.orderer
        if oc is not None and len(env.encode()) > \
                oc.batch_size.absolute_max_bytes:
            raise MsgRejectedError("message exceeds absolute_max_bytes")
        pol = bundle.policy(CHANNEL_WRITERS)
        if pol is None:
            raise MsgRejectedError(f"no {CHANNEL_WRITERS} policy")
        sds = protoutil.envelope_as_signed_data(env)
        if not pol.evaluate_signed_data(sds, self._verify_many):
            raise MsgRejectedError("signature does not satisfy Writers")

    def process_normal_msg(self, env: m.Envelope) -> int:
        """Validate a normal tx for ordering; returns the config
        sequence it was validated under (reference:
        standardchannel.go ProcessNormalMsg)."""
        bundle = self._bundle()
        ch = protoutil.envelope_channel_header(env)
        if ch.channel_id != bundle.channel_id:
            raise MsgRejectedError(
                f"message for channel {ch.channel_id!r} on "
                f"{bundle.channel_id!r}")
        self._apply_filters(env, bundle)
        return bundle.sequence

    def process_config_update_msg(
            self, env: m.Envelope) -> Tuple[m.Envelope, int]:
        """CONFIG_UPDATE -> validated CONFIG envelope ready to order
        (reference: standardchannel.go ProcessConfigUpdateMsg:
        filters, ProposeConfigUpdate, wrap, re-filter)."""
        bundle = self._bundle()
        self._apply_filters(env, bundle)
        cue = extract_config_update(env)
        new_config = propose_config_update(bundle, cue, self._verify_many)
        cenv = m.ConfigEnvelope(config=new_config, last_update=env)
        ch = protoutil.make_channel_header(
            m.HeaderType.CONFIG, bundle.channel_id)
        if self._signer is not None:
            sh = protoutil.make_signature_header(
                self._signer.serialize(), protoutil.new_nonce())
            payload = protoutil.make_payload(ch, sh, cenv.encode())
            wrapped = protoutil.sign_envelope(payload, self._signer)
        else:
            sh = protoutil.make_signature_header(b"", protoutil.new_nonce())
            payload = protoutil.make_payload(ch, sh, cenv.encode())
            wrapped = m.Envelope(payload=payload.encode())
        return wrapped, bundle.sequence
