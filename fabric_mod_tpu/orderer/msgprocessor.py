"""Orderer ingress message processing.

(reference: orderer/common/msgprocessor — StandardChannel at
standardchannel.go:70 with its filter chain, SigFilter.Apply at
sigfilter.go:41, and the config-update path ProcessConfigUpdateMsg.)

The filters: reject empty envelopes, enforce the channel's
absolute_max_bytes, and require the channel Writers policy over the
envelope's signature — the policy engine's batch-first evaluators do
the verify (a single envelope rides the host path; gossip-storm-style
ingress floods batch through the same seam).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

from fabric_mod_tpu.channelconfig import (
    ConfigTxError, extract_config_update, propose_config_update)
from fabric_mod_tpu.channelconfig.bundle import Bundle
from fabric_mod_tpu.policy.cauthdsl import BatchCollector
from fabric_mod_tpu.policy.manager import batch_verifier
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil


class MsgRejectedError(Exception):
    pass


CHANNEL_WRITERS = "/Channel/Writers"


class StandardChannelProcessor:
    """Per-channel ingress processor.  `bundle_fn` returns the CURRENT
    bundle (atomically swapped on config commit), so every message is
    judged under the config in force at processing time — the
    reference re-reads its config sequence the same way."""

    def __init__(self, bundle_fn: Callable[[], Bundle],
                 signer=None, verify_many=None):
        self._bundle = bundle_fn
        self._signer = signer          # orderer identity for CONFIG wraps
        self._verify_many = verify_many

    # -- classification (reference: registrar BroadcastChannelSupport) --
    @staticmethod
    def classify(env: m.Envelope) -> int:
        ch = protoutil.envelope_channel_header(env)
        return ch.type

    # -- filters ---------------------------------------------------------
    def _apply_filters(self, env: m.Envelope, bundle: Bundle) -> None:
        if not env.payload:
            raise MsgRejectedError("empty envelope")
        oc = bundle.orderer
        if oc is not None and len(env.encode()) > \
                oc.batch_size.absolute_max_bytes:
            raise MsgRejectedError("message exceeds absolute_max_bytes")
        pol = bundle.policy(CHANNEL_WRITERS)
        if pol is None:
            raise MsgRejectedError(f"no {CHANNEL_WRITERS} policy")
        sds = protoutil.envelope_as_signed_data(env)
        if not pol.evaluate_signed_data(sds, self._verify_many):
            raise MsgRejectedError("signature does not satisfy Writers")

    def process_normal_msg(self, env: m.Envelope) -> int:
        """Validate a normal tx for ordering; returns the config
        sequence it was validated under (reference:
        standardchannel.go ProcessNormalMsg)."""
        bundle = self._bundle()
        ch = protoutil.envelope_channel_header(env)
        if ch.channel_id != bundle.channel_id:
            raise MsgRejectedError(
                f"message for channel {ch.channel_id!r} on "
                f"{bundle.channel_id!r}")
        self._apply_filters(env, bundle)
        return bundle.sequence

    def process_normal_msgs(
            self, envs: Sequence[m.Envelope]) -> List[object]:
        """Batched `process_normal_msg`: validate many normal txs
        under ONE bundle read, their Writers-policy signature checks
        staged into ONE `verify_many` dispatch (the staged broadcast
        drainer's seam).  Returns one verdict per envelope,
        positionally: the config sequence (int) on acceptance, the
        raising exception on rejection — a poisoned envelope costs
        its own slot, never its batch-mates'.  A failure of the batch
        dispatch ITSELF falls back to the per-envelope path so an
        infra fault cannot reject a whole cohort of clients."""
        bundle = self._bundle()
        results: List[object] = [None] * len(envs)
        pol = bundle.policy(CHANNEL_WRITERS)
        oc = bundle.orderer
        collector = BatchCollector()
        staged = []                          # (slot, PendingEval)
        for i, env in enumerate(envs):
            try:
                ch = protoutil.envelope_channel_header(env)
                if ch.channel_id != bundle.channel_id:
                    raise MsgRejectedError(
                        f"message for channel {ch.channel_id!r} on "
                        f"{bundle.channel_id!r}")
                if not env.payload:
                    raise MsgRejectedError("empty envelope")
                if oc is not None and len(env.encode()) > \
                        oc.batch_size.absolute_max_bytes:
                    raise MsgRejectedError(
                        "message exceeds absolute_max_bytes")
                if pol is None:
                    raise MsgRejectedError(
                        f"no {CHANNEL_WRITERS} policy")
                sds = protoutil.envelope_as_signed_data(env)
                staged.append((i, pol.prepare(sds, collector)))
            except Exception as e:  # noqa: BLE001 -- the exception IS
                results[i] = e      # this slot's typed verdict
        if staged:
            try:
                mask = batch_verifier(
                    pol, self._verify_many)(collector.items)
                verdicts = [(i, p.finish(mask)) for i, p in staged]
            except Exception:  # noqa: BLE001 -- batch-level infra
                # fault: re-judge each envelope alone so one poisoned
                # item cannot take down its whole cohort
                for i, _ in staged:
                    try:
                        results[i] = self.process_normal_msg(envs[i])
                    except Exception as e:  # noqa: BLE001 -- slot verdict
                        results[i] = e
            else:
                for i, ok in verdicts:
                    results[i] = bundle.sequence if ok else \
                        MsgRejectedError(
                            "signature does not satisfy Writers")
        return results

    def process_config_update_msg(
            self, env: m.Envelope) -> Tuple[m.Envelope, int]:
        """CONFIG_UPDATE -> validated CONFIG envelope ready to order
        (reference: standardchannel.go ProcessConfigUpdateMsg:
        filters, ProposeConfigUpdate, wrap, re-filter)."""
        bundle = self._bundle()
        self._apply_filters(env, bundle)
        cue = extract_config_update(env)
        new_config = propose_config_update(bundle, cue, self._verify_many)
        cenv = m.ConfigEnvelope(config=new_config, last_update=env)
        ch = protoutil.make_channel_header(
            m.HeaderType.CONFIG, bundle.channel_id)
        if self._signer is not None:
            sh = protoutil.make_signature_header(
                self._signer.serialize(), protoutil.new_nonce())
            payload = protoutil.make_payload(ch, sh, cenv.encode())
            wrapped = protoutil.sign_envelope(payload, self._signer)
        else:
            sh = protoutil.make_signature_header(b"", protoutil.new_nonce())
            payload = protoutil.make_payload(ch, sh, cenv.encode())
            wrapped = m.Envelope(payload=payload.encode())
        return wrapped, bundle.sequence
