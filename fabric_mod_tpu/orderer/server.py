"""The orderer's gRPC surface: AtomicBroadcast.Broadcast/Deliver.

(reference: orderer/common/server — NewServer at server.go:210
registering AtomicBroadcast over internal/pkg/comm's mTLS server;
broadcast.go:66 Handle and common/deliver/deliver.go:157 Handle are
the two stream loops.)

Wire contract: envelopes/seek-infos/responses are this framework's
deterministic encodings travelling as gRPC byte payloads
(comm/grpc_comm.py's generic handlers).
"""
from __future__ import annotations

import threading
from typing import Iterator, Optional

from fabric_mod_tpu.comm.grpc_comm import GRPCServer, MethodKind
from fabric_mod_tpu.concurrency import CancellationEvent
from fabric_mod_tpu.orderer.admission import ResourceExhaustedError
from fabric_mod_tpu.orderer.broadcast import Broadcast, BroadcastError
from fabric_mod_tpu.orderer.consensus import NotLeaderError
from fabric_mod_tpu.orderer.deliver import DeliverService
from fabric_mod_tpu.orderer.registrar import Registrar
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil

SERVICE = "orderer.AtomicBroadcast"


class OrdererServer:
    """Binds a Registrar to a gRPC listener."""

    def __init__(self, registrar: Registrar, address: str = "127.0.0.1:0",
                 server_cert_pem: Optional[bytes] = None,
                 server_key_pem: Optional[bytes] = None,
                 client_root_pem: Optional[bytes] = None):
        self._registrar = registrar
        self._broadcast = Broadcast(registrar)
        self._grpc = GRPCServer(address, server_cert_pem,
                                server_key_pem, client_root_pem)
        self.port = self._grpc.port
        self._grpc.register(SERVICE, "Broadcast",
                            MethodKind.STREAM_STREAM, self._handle_broadcast)
        self._grpc.register(SERVICE, "Deliver",
                            MethodKind.STREAM_STREAM, self._handle_deliver)

    def start(self) -> None:
        self._grpc.start()

    def stop(self, grace: float = 1.0) -> None:
        """`grace=0` aborts in-flight streams immediately (crash
        simulation in tests); the default drains them briefly."""
        self._grpc.stop(grace)

    # -- Broadcast stream (reference: broadcast.go:66) -------------------
    def _handle_broadcast(self, request_iter, context) -> Iterator[bytes]:
        # cross-process trace stitching: the broadcast client carries
        # its trace context as stream metadata (tracing.inject on the
        # GrpcBroadcaster side); every envelope handled on this stream
        # parents under it, so a procnet tx is ONE trace from client
        # submit through the orderer's admission + ordering
        from fabric_mod_tpu.observability import tracing
        parent = tracing.extract(context.invocation_metadata()) \
            if tracing.armed() else None
        for raw in request_iter:
            try:
                env = m.Envelope.decode(raw)
                with tracing.span("broadcast.handle", parent=parent,
                                  bytes=len(raw)):
                    self._broadcast.submit(env)
                resp = m.BroadcastResponse(status=m.Status.SUCCESS)
            except BroadcastError as e:
                resp = m.BroadcastResponse(
                    status=m.Status.BAD_REQUEST, info=str(e))
            except NotLeaderError as e:
                # leaderless past the retry budget: retryable, with
                # the best leader hint (reference: etcdraft Submit ->
                # SERVICE_UNAVAILABLE + redirect info)
                hint = (f"; try {e.leader_hint}"
                        if e.leader_hint else "")
                resp = m.BroadcastResponse(
                    status=m.Status.SERVICE_UNAVAILABLE,
                    info=f"no leader: retry{hint}")
            except ResourceExhaustedError as e:
                # admission shed: typed + retryable, carrying the
                # server's retry-after hint so remote clients back off
                # exactly that long (the grpcdeliver broadcast client
                # parses this field)
                resp = m.BroadcastResponse(
                    status=m.Status.RESOURCE_EXHAUSTED,
                    info=f"resource exhausted ({e.reason}): "
                         f"retry_after={e.retry_after_s:.3f}")
            except Exception as e:
                resp = m.BroadcastResponse(
                    status=m.Status.INTERNAL_SERVER_ERROR, info=str(e))
            yield resp.encode()

    # -- Deliver stream (reference: deliver.go:157-199) ------------------
    def _handle_deliver(self, request_iter, context) -> Iterator[bytes]:
        for raw in request_iter:
            try:
                env = m.Envelope.decode(raw)
                payload = protoutil.unmarshal_envelope_payload(env)
                ch = m.ChannelHeader.decode(payload.header.channel_header)
                seek = m.SeekInfo.decode(payload.data)
            except Exception:
                yield m.DeliverResponse(
                    status=m.Status.BAD_REQUEST).encode()
                return
            support = self._registrar.get_chain(ch.channel_id)
            if support is None:
                yield m.DeliverResponse(
                    status=m.Status.NOT_FOUND).encode()
                return
            svc = DeliverService(support)
            h = support.store.height
            start = protoutil.seek_number(seek.start, h, newest_tip=True)
            stop = protoutil.seek_number(seek.stop, h, newest_tip=False)
            # CancellationEvent: its set() hook notifies the writer's
            # condition, so a cancelled stream leaves a tickless tip
            # wait immediately (orderer/deliver.py)
            stop_event = CancellationEvent()
            cb = context.add_callback(stop_event.set)
            for block in svc.blocks(start, stop=stop,
                                    stop_event=stop_event,
                                    timeout_s=30.0):
                yield m.DeliverResponse(block=block).encode()
            yield m.DeliverResponse(status=m.Status.SUCCESS).encode()

def make_seek_envelope(channel_id: str, start: int,
                       stop: Optional[int] = None) -> m.Envelope:
    """Client-side SeekInfo envelope (reference: the deliver client's
    seekInfo construction in blocksprovider)."""
    stop_pos = (m.SeekPosition(specified=m.SeekSpecified(number=stop))
                if stop is not None else None)
    seek = m.SeekInfo(
        start=m.SeekPosition(specified=m.SeekSpecified(number=start)),
        stop=stop_pos,
        behavior=m.SeekBehavior.BLOCK_UNTIL_READY)
    ch = protoutil.make_channel_header(
        m.HeaderType.DELIVER_SEEK_INFO, channel_id)
    sh = protoutil.make_signature_header(b"", protoutil.new_nonce())
    payload = protoutil.make_payload(ch, sh, seek.encode())
    return m.Envelope(payload=payload.encode())
