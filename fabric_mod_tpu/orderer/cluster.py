"""Cluster communication: the raft transport over real gRPC.

(reference: orderer/common/cluster/comm.go — the orderer-to-orderer
`Cluster/Step` RPC carrying consensus messages and submit forwarding,
with per-destination send queues so one dead peer never stalls the
consensus thread (comm.go's buffered streams), and TLS-pinned
membership via the comm layer's mTLS.)

`GRPCRaftTransport` implements the RaftTransport seam (register/send)
that `RaftNode`/`RaftChain` already consume in-process: message
dataclasses are serialized as JSON (bytes base64'd — never pickle,
peers are remote), unary `Cluster/Step` calls deliver them, and a
bounded queue + sender thread per destination absorbs slow/dead
peers (drops on overflow; raft tolerates message loss).
"""
from __future__ import annotations

import base64
import json
import queue
import threading
from typing import Callable, Dict, Optional

from fabric_mod_tpu.comm.grpc_comm import (
    GRPCClient, GRPCServer, MethodKind)
from fabric_mod_tpu.orderer import raft
from fabric_mod_tpu.orderer import raftchain
from fabric_mod_tpu.concurrency.threads import RegisteredThread
from fabric_mod_tpu.observability.logging import get_logger
from fabric_mod_tpu.concurrency.locks import RegisteredLock

log = get_logger("orderer.cluster")


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def encode_msg(msg) -> bytes:
    """Raft/chain message -> wire JSON."""
    if isinstance(msg, raft.RequestVote):
        d = {"t": "rv", "term": msg.term, "candidate": msg.candidate,
             "lli": msg.last_log_index, "llt": msg.last_log_term}
    elif isinstance(msg, raft.VoteReply):
        d = {"t": "vr", "term": msg.term, "voter": msg.voter,
             "granted": msg.granted}
    elif isinstance(msg, raft.AppendEntries):
        d = {"t": "ae", "term": msg.term, "leader": msg.leader,
             "pi": msg.prev_index, "pt": msg.prev_term,
             "lc": msg.leader_commit,
             "entries": [[t, _b64(data)] for t, data in msg.entries]}
    elif isinstance(msg, raft.AppendReply):
        d = {"t": "ar", "term": msg.term, "follower": msg.follower,
             "success": msg.success, "mi": msg.match_index}
    elif isinstance(msg, raft.InstallSnapshot):
        d = {"t": "is", "term": msg.term, "leader": msg.leader,
             "li": msg.last_index, "lt": msg.last_term,
             "data": _b64(msg.data)}
    elif isinstance(msg, raftchain._Submit):
        d = {"t": "submit", "env": _b64(msg.env_bytes),
             "cfg": msg.is_config, "seq": msg.config_seq}
    else:
        raise TypeError(f"unknown cluster message {type(msg)!r}")
    return json.dumps(d).encode()


def decode_msg(raw: bytes):
    d = json.loads(raw)
    t = d["t"]
    if t == "rv":
        return raft.RequestVote(d["term"], d["candidate"], d["lli"],
                                d["llt"])
    if t == "vr":
        return raft.VoteReply(d["term"], d["voter"], d["granted"])
    if t == "ae":
        return raft.AppendEntries(
            d["term"], d["leader"], d["pi"], d["pt"],
            [(t_, _unb64(b)) for t_, b in d["entries"]], d["lc"])
    if t == "ar":
        return raft.AppendReply(d["term"], d["follower"], d["success"],
                                d["mi"])
    if t == "is":
        return raft.InstallSnapshot(d["term"], d["leader"], d["li"],
                                    d["lt"], _unb64(d["data"]))
    if t == "submit":
        return raftchain._Submit(_unb64(d["env"]), d["cfg"], d["seq"])
    raise ValueError(f"unknown cluster message type {t!r}")


class GRPCRaftTransport:
    """RaftTransport over gRPC (reference: cluster comm.go).

    `peers`: {base_node_id: "host:port"} including this node.  Targets
    named "<id>" or "<id>:chain" route to the peer owning <id>; local
    targets bypass the network.  TLS material (PEM bytes) makes both
    the server and the dials mutually authenticated."""

    STEP = ("Cluster", "Step")
    QUEUE_CAP = 256

    def __init__(self, node_id: str, peers: Dict[str, str],
                 listen_address: Optional[str] = None,
                 server_cert: Optional[bytes] = None,
                 server_key: Optional[bytes] = None,
                 client_ca: Optional[bytes] = None,
                 client_cert: Optional[bytes] = None,
                 client_key: Optional[bytes] = None):
        self.node_id = node_id
        self._peers = dict(peers)
        self._handlers: Dict[str, Callable] = {}
        self._lock = RegisteredLock("orderer.cluster._lock")
        self._stopped = threading.Event()
        self._client_tls = (client_ca, client_cert, client_key)
        # per-destination bounded queues + sender threads: a dead peer
        # blocks its own queue only, never the raft FSM thread
        self._queues: Dict[str, "queue.Queue"] = {}
        self._senders: Dict[str, threading.Thread] = {}
        self._clients: Dict[str, GRPCClient] = {}
        self.server = GRPCServer(
            listen_address or peers[node_id],
            server_cert_pem=server_cert, server_key_pem=server_key,
            client_root_pem=client_ca)
        self.server.register(*self.STEP, MethodKind.UNARY, self._on_step)

    def set_peer_address(self, node_id: str, address: str) -> None:
        """Fill in a peer's dial address after its server bound (test
        topologies bind port 0 first, then exchange real ports)."""
        with self._lock:
            self._peers[node_id] = address
            client = self._clients.pop(node_id, None)
        if client is not None:
            client.close()

    @property
    def listen_port(self) -> int:
        return self.server.port

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            queues = list(self._queues.values())
            clients = list(self._clients.values())
            self._clients.clear()
        for q in queues:
            try:
                q.put_nowait(None)
            except queue.Full:
                pass                       # sender polls _stopped too
        for client in clients:
            client.close()
        self.server.stop()

    # -- the RaftTransport surface ---------------------------------------
    def register(self, target: str, handler: Callable) -> None:
        with self._lock:
            self._handlers[target] = handler

    def send(self, src: str, dst: str, msg) -> None:
        if self._stopped.is_set():
            return                         # no new queues after stop
        base = dst.partition(":")[0]
        if base == self.node_id:
            self._deliver(src, dst, encode_msg(msg))
            return
        if base not in self._peers:
            return
        q = self._queue_for(base)
        try:
            q.put_nowait((src, dst, encode_msg(msg)))
        except queue.Full:
            pass                           # drop: raft re-sends

    # -- internals --------------------------------------------------------
    def _deliver(self, src: str, dst: str, raw: bytes) -> None:
        with self._lock:
            handler = self._handlers.get(dst)
        if handler is None:
            return
        try:
            handler(src, decode_msg(raw))
        except Exception as e:
            log.debug("cluster step handler for %s failed: %r",
                      dst, e)

    def _queue_for(self, base: str) -> "queue.Queue":
        with self._lock:
            q = self._queues.get(base)
            if q is None:
                q = queue.Queue(self.QUEUE_CAP)
                self._queues[base] = q
                t = RegisteredThread(target=self._sender,
                                     args=(base, q),
                                     name=f"cluster-sender[{base}]",
                                     structure="orderer.cluster")
                self._senders[base] = t
                t.start()
            return q

    def _sender(self, base: str, q: "queue.Queue") -> None:
        while not self._stopped.is_set():
            try:
                # bounded wait so a full queue at stop() (dropped
                # sentinel) still terminates promptly
                item = q.get(timeout=0.5)
            except queue.Empty:
                continue
            if item is None or self._stopped.is_set():
                return
            src, dst, raw = item
            try:
                client = self._client_for(base)
                client.unary(*self.STEP, json.dumps(
                    {"src": src, "dst": dst,
                     "msg": _b64(raw)}).encode(), timeout=2.0)
            except Exception:
                # dead peer: drop and forget the cached channel so the
                # next attempt re-dials
                with self._lock:
                    client = self._clients.pop(base, None)
                if client is not None:
                    client.close()

    def _client_for(self, base: str) -> GRPCClient:
        with self._lock:
            client = self._clients.get(base)
            if client is None:
                ca, cert, key = self._client_tls
                client = GRPCClient(self._peers[base],
                                    server_root_pem=ca,
                                    client_cert_pem=cert,
                                    client_key_pem=key)
                self._clients[base] = client
            return client

    def _on_step(self, request: bytes, context) -> bytes:
        try:
            d = json.loads(request)
            self._deliver(d["src"], d["dst"], _unb64(d["msg"]))
        except Exception as e:
            log.debug("malformed cluster step request: %r", e)
        return b""
