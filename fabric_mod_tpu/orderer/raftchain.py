"""The Raft-backed consenter: the consenter contract over RaftNode.

(reference: orderer/consensus/etcdraft/chain.go — Order/Configure at
:381/:387, Submit-forwarding to the leader at :494, the leader-side
blockcutter + batch timer inside run at :533, and block writing on
apply at :791/:964.)

Replicated payload = one CUT BATCH (flag byte + BlockData of envelope
bytes).  Every node builds the block at APPLY time from its own chain
tip — heights, prev hashes, and data hashes are identical everywhere
because apply order is identical; only the per-node metadata
signature differs.  Config batches carry exactly one envelope and
swap the bundle through the same ChainSupport.process_config path the
solo consenter uses.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import List, Optional, Tuple

from fabric_mod_tpu import faults
from fabric_mod_tpu.observability.logging import get_logger
from fabric_mod_tpu.orderer import admission
from fabric_mod_tpu.orderer.consensus import ChainHaltedError, NotLeaderError
from fabric_mod_tpu.orderer.raft import RaftNode, RaftTransport
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.concurrency.threads import RegisteredThread
from fabric_mod_tpu.concurrency.locks import RegisteredLock

_log = get_logger("orderer.raftchain")

_NORMAL, _CONFIG = 0, 1


class _Submit:
    """Envelope forwarded to the leader (reference: Submit :494)."""

    __slots__ = ("env_bytes", "is_config", "config_seq")

    def __init__(self, env_bytes: bytes, is_config: bool,
                 config_seq: int):
        self.env_bytes = env_bytes
        self.is_config = is_config
        self.config_seq = config_seq


def _encode_batch(envs: List[m.Envelope], kind: int) -> bytes:
    return bytes([kind]) + m.BlockData(
        data=[e.encode() for e in envs]).encode()


def _decode_batch(data: bytes) -> Tuple[int, List[m.Envelope]]:
    kind = data[0]
    bd = m.BlockData.decode(data[1:])
    return kind, [m.Envelope.decode(d) for d in bd.data]


class RaftChain:
    """Consenter with the SoloChain surface (order/configure/start/
    halt/wait_ready) plus leader awareness."""

    RAFT_INDEX_MD_SLOT = 3                 # block metadata slot

    def __init__(self, node_id: str, peer_ids: List[str],
                 transport: RaftTransport, wal_path: str, support,
                 election_timeout=(0.15, 0.3), heartbeat_s=0.05,
                 snapshot_interval: Optional[int] = None,
                 block_fetcher=None, clock=None, rng=None):
        """`snapshot_interval`: compact the raft log every N applied
        entries (reference: SnapshotIntervalSize).  `block_fetcher`:
        callable(from_height, to_height) -> list[Block] used by a
        lagging node to pull blocks it can no longer rebuild from
        compacted entries (reference: the cluster block puller,
        orderer/common/cluster/deliver.go:571).  The fetcher runs on
        the raft FSM thread, so it MUST bound its own time (connect +
        read deadlines); raising is safe — the leader re-offers the
        snapshot with backoff.  `clock`/`rng` pass through to RaftNode:
        a utils/fakeclock.ManualClock (plus per-node seeded rngs)
        makes ELECTION timing fully deterministic for tests — the
        batch timer below stays wall-clock (cutting a partial batch
        late is benign; spurious elections are not)."""
        self.node_id = node_id
        self._support = support
        self._transport = transport
        self._fetch_blocks = block_fetcher
        # the channel config's consenter set (ConsensusType.metadata)
        # is authoritative when present; the ctor list is the
        # bootstrap fallback (reference: consenters from ConfigMetadata)
        cfg_set = support.bundle().orderer.consenters()
        if cfg_set:
            peer_ids = list(cfg_set)
        self._raft = RaftNode(node_id, peer_ids, transport, wal_path,
                              self._apply, election_timeout, heartbeat_s,
                              rng=rng,
                              snapshot_interval=snapshot_interval,
                              snapshot_cb=self._snapshot_state,
                              install_cb=self._install_snapshot,
                              clock=clock)
        if cfg_set and node_id not in cfg_set:
            # configured out (or not yet in): run as observer — apply
            # committed entries, never campaign
            self._raft.member = False
        transport.register(f"{node_id}:chain", self._on_chain_msg)
        # FABRIC_MOD_TPU_SUBMIT_QUEUE bounds ingress with non-blocking
        # puts (typed shed); unset = the blocking 10k queue, unchanged
        cap = admission.submit_queue_cap()
        self._bounded = cap > 0
        self._q: "queue.Queue[Optional[_Submit]]" = queue.Queue(
            cap if self._bounded else 10_000)
        # already-ACKED submits that hit a full queue are PARKED, not
        # dropped — their clients got SUCCESS, so nobody would retry a
        # silent drop.  _parked is the run loop's own (single-thread);
        # _overflow absorbs forwarded submits arriving on transport
        # threads; both are bounded by _PARKED_CAP, and only a submit
        # past BOTH bounds is truly dropped (counted + logged).
        self._parked: List[_Submit] = []
        self._overflow: "deque[_Submit]" = deque()
        self._overflow_lock = RegisteredLock("orderer.raftchain._overflow_lock")
        self._halted = threading.Event()
        self._thread = RegisteredThread(
            target=self._run, name="raftchain-run",
            structure="orderer.raftchain")
        # Applied-index recovery: each block records the raft index of
        # the entry that produced it, so a restart replaying the WAL
        # skips entries already in the block store (otherwise every
        # restart would re-append the whole chain at new heights —
        # reference: etcdraft's lastBlock/appliedIndex in the
        # consenter metadata).
        self._applied_upto = self._tip_raft_index(support.store)

    # -- consenter surface ------------------------------------------------
    def start(self) -> None:
        self._raft.start()
        self._thread.start()

    def halt(self) -> None:
        if self._halted.is_set():
            return
        self._halted.set()
        try:
            # wake-up only (see SoloChain.halt): a blocking put on a
            # full bounded queue would deadlock against a run loop
            # that already exited on _halted
            self._q.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=5)
        self._raft.stop()

    def wait_ready(self) -> None:
        if self._halted.is_set():
            raise ChainHaltedError("chain is halted")

    @property
    def is_leader(self) -> bool:
        return self._raft.state == "leader"

    @property
    def leader_id(self) -> Optional[str]:
        return self._raft.leader_id

    def order(self, env: m.Envelope, config_seq: int) -> None:
        self._admission_check()
        self._enqueue_submit(_Submit(env.encode(), False, config_seq),
                             is_config=False)

    def configure(self, env: m.Envelope, config_seq: int) -> None:
        self._admission_check()
        self._check_membership_change(env)
        self._enqueue_submit(_Submit(env.encode(), True, config_seq),
                             is_config=True)

    def submit_queue_depth(self):
        """(qsize, maxsize) — the occupancy signal the overload gate
        watches."""
        return self._q.qsize(), self._q.maxsize

    def _enqueue_submit(self, sub: "_Submit", is_config: bool) -> None:
        """Bounded mode answers a full queue with the typed shed
        (clients retry after the hint) instead of blocking the
        broadcast handler; config submits keep the blocking put — the
        bounded queue drains, and the relief config must land.  The
        full-path re-check extends that to every PRIORITY envelope
        (lifecycle, orderer txs), mirroring SoloChain: "always
        admitted" holds at the queue too, and the decode+classify
        cost is paid only on the Full path."""
        if not self._bounded:
            self._q.put(sub)
            return
        if is_config:
            self._put_priority(sub)
            return
        try:
            self._q.put_nowait(sub)
        except queue.Full:
            try:
                env = m.Envelope.decode(sub.env_bytes)
            except Exception:
                env = None
            if env is not None and admission.is_priority(env):
                self._put_priority(sub)
                return
            raise admission.shed(
                "queue_full",
                f"submit queue full ({self._q.maxsize})",
                retry_after_s=min(5.0, self._support.batch_timeout_s()),
            ) from None

    def _put_priority(self, sub: "_Submit") -> None:
        """Bounded-mode blocking put for priority traffic in
        halted-aware slices (see SoloChain._put_priority): priority
        waits for drain, but never wedges a handler thread against a
        halted chain."""
        while True:
            if self._halted.is_set():
                raise ChainHaltedError("chain is halted")
            try:
                self._q.put(sub, timeout=0.25)
                return
            except queue.Full:
                continue

    def _admission_check(self) -> None:
        """Reject a submission this node can neither order nor forward
        with a TYPED, retryable error.  The old path enqueued during a
        leaderless window and the run loop silently dropped the
        envelope — an invisible loss the client could only discover by
        timing out on commit.  A follower with a live leader still
        accepts and forwards (reference: Submit :494); only the
        leaderless window (election in flight, or a deposed leader
        still listed as its own leader) rejects, carrying the best
        leader hint for the retry (reference: etcdraft's
        ErrNoLeader/SubmitResponse redirect)."""
        self.wait_ready()
        faults.point("orderer.raft.submit")
        if self.is_leader:
            return
        lead = self._raft.leader_id
        if lead is None or lead == self.node_id:
            raise NotLeaderError(
                f"consenter {self.node_id!r} has no raft leader to "
                f"forward to (election in progress)",
                leader_hint=None)

    def _check_membership_change(self, env: m.Envelope) -> None:
        """Reject consenter-set changes touching more than ONE member:
        single-server reconfiguration keeps old/new quorums overlapping
        (reference: etcdraft's one-change-per-config rule,
        consenter.go's CheckConfigMetadata)."""
        try:
            payload = protoutil.unmarshal_envelope_payload(env)
            cenv = m.ConfigEnvelope.decode(payload.data)
            if cenv.config is None:
                return
            from fabric_mod_tpu.channelconfig import Bundle
            new_bundle = Bundle(self._support.channel_id, cenv.config,
                                self._support._csp)
            new_set = set(new_bundle.orderer.consenters())
        except Exception:
            return                         # not a readable config: let
            #                                normal validation reject it
        if not new_set:
            return                         # channel doesn't track a set
        cur = set(self._current_consenters())
        if not cur:
            return
        if len(cur ^ new_set) > 1:
            raise ValueError(
                "consenter reconfiguration must add or remove at most "
                f"one member per config update (got {sorted(cur)} -> "
                f"{sorted(new_set)})")

    def _current_consenters(self):
        got = self._support.bundle().orderer.consenters()
        return got if got else tuple([self.node_id] + [
            p for p in self._raft.peers])

    # -- submit routing ----------------------------------------------------
    def _on_chain_msg(self, src: str, msg) -> None:
        if isinstance(msg, _Submit):
            try:
                self._q.put_nowait(msg)
            except queue.Full:
                # the FOLLOWER already acked this submit — park it for
                # the run loop to re-inject as the queue drains; only
                # overflow past the parked bound is a real drop, and
                # that one is counted + logged (a starved follower
                # must not be indistinguishable from a healthy idle
                # one)
                with self._overflow_lock:
                    if len(self._overflow) < self._PARKED_CAP:
                        self._overflow.append(msg)
                        return
                admission.chain_drop_counter().with_labels(
                    "forward").add(1)
                _log.debug(
                    "%s: dropped forwarded submit from %s "
                    "(queue full at %d, overflow full at %d)",
                    self.node_id, src, self._q.maxsize,
                    self._PARKED_CAP)

    # -- the leader loop (reference: chain.go:533 run) --------------------
    def _propose_batch(self, envs: List[m.Envelope], kind: int,
                       config_seq: int) -> None:
        """Propose; on leadership loss between check and propose,
        requeue the envelopes so they are forwarded to the new leader
        instead of vanishing (the cutter already released them).
        These submits were ACKED at admission, so a full queue PARKS
        the remainder (this runs on the run-loop thread, which owns
        _parked); only past the parked bound is anything dropped —
        counted + logged.

        propose() also returns False while STILL leader when the raft
        FSM queue is full: retry the encoded batch with a short
        hold-off instead of unwinding to envelopes — an immediate
        requeue would busy-spin the run loop through decode +
        revalidate + re-cut per attempt.  Blocking here is honest
        backpressure: the submit queue fills behind us and sheds
        typed."""
        data = _encode_batch(envs, kind)
        while not self._halted.is_set():
            if self._raft.propose(data):
                return
            if not self.is_leader:
                break                      # leadership lost: unwind
            time.sleep(0.005)              # FSM queue full: hold off
        self._requeue(envs, kind, config_seq)

    def _propose_normal_batches(self, batches: List[List[m.Envelope]],
                                config_seq: int) -> None:
        """Leader-side multi-batch proposal: with the raft pipeline
        armed (FABRIC_MOD_TPU_RAFT_PIPELINE), every batch this
        submission cut enters the raft log in ONE FSM turn via
        `propose_many` — one group-commit barrier, one replication
        broadcast — instead of one propose round per block.  Unarmed
        (or a single batch), one propose per batch: the prior
        behavior exactly."""
        from fabric_mod_tpu.utils import knobs
        if len(batches) > 1 and \
                knobs.get_int("FABRIC_MOD_TPU_RAFT_PIPELINE") > 0:
            datas = [_encode_batch(b, _NORMAL) for b in batches]
            while not self._halted.is_set():
                if self._raft.propose_many(datas):
                    return
                if not self.is_leader:
                    break                  # leadership lost: unwind ALL
                time.sleep(0.005)          # FSM queue full: hold off
            for batch in batches:
                self._requeue(batch, _NORMAL, config_seq)
            return
        for batch in batches:
            self._propose_batch(batch, _NORMAL, config_seq)

    def _requeue(self, envs: List[m.Envelope], kind: int,
                 config_seq: int) -> None:
        subs = [_Submit(env.encode(), kind == _CONFIG, config_seq)
                for env in envs]
        for i, sub in enumerate(subs):
            try:
                self._q.put_nowait(sub)
            except queue.Full:
                rest = subs[i:]
                space = max(0, self._PARKED_CAP - len(self._parked))
                self._parked.extend(rest[:space])
                dropped = len(rest) - space
                if dropped > 0:
                    admission.chain_drop_counter().with_labels(
                        "requeue").add(dropped)
                    _log.debug(
                        "%s: dropped %d of %d reproposed envelopes "
                        "on leadership loss (queue and parked both "
                        "full)", self.node_id, dropped, len(envs))
                break

    _PARKED_CAP = 10_000                   # mirrors the ingress queue

    def _run(self) -> None:
        support = self._support
        timer_deadline: Optional[float] = None
        was_leader = False
        # self._parked: submits ADMITTED (admission saw a live leader)
        # but caught by a leaderless window or a full queue after the
        # ack: parked, not dropped — the caller already got a
        # successful return, so nobody would retry a silent drop.
        # Flushed back through the queue the moment a route (us as
        # leader, or a known remote leader) exists; bounded like the
        # ingress queue.
        parked = self._parked
        while not self._halted.is_set():
            timeout = 0.05
            if timer_deadline is not None:
                timeout = max(0.0, min(timeout,
                                       timer_deadline - time.monotonic()))
            try:
                sub = self._q.get(timeout=timeout)
            except queue.Empty:
                sub = "tick"
            if sub is None:
                break
            # forwarded submits that found the queue full (transport
            # threads park them in _overflow): re-inject as slots free
            with self._overflow_lock:
                while self._overflow:
                    try:
                        self._q.put_nowait(self._overflow[0])
                    except queue.Full:
                        break
                    self._overflow.popleft()
            lead = self._raft.leader_id
            if parked and (self.is_leader or
                           (lead is not None and lead != self.node_id)):
                # a route exists again: re-inject parked submits for
                # normal processing (leader path orders them, the
                # follower path forwards them)
                while parked:
                    try:
                        self._q.put_nowait(parked[0])
                    except queue.Full:
                        break              # keep the rest parked
                    parked.pop(0)
            if not self.is_leader:
                if was_leader:
                    # leadership lost: discard the pending batch —
                    # clients resubmit via the new leader (reference:
                    # etcdraft discards the cutter on soft-state change)
                    support.cutter.cut()
                    was_leader = False
                timer_deadline = None
                # followers forward; never to ourselves (a deposed
                # leader still listed as leader would spin-loop)
                if isinstance(sub, _Submit):
                    if lead is not None and lead != self.node_id:
                        self._transport.send(
                            f"{self.node_id}:chain", f"{lead}:chain",
                            sub)
                    elif len(parked) < self._PARKED_CAP:
                        parked.append(sub)  # leaderless: hold, don't drop
                continue
            was_leader = True
            # -- leader path --
            if isinstance(sub, _Submit):
                try:
                    env = m.Envelope.decode(sub.env_bytes)
                except Exception:
                    continue
                if sub.is_config:
                    if sub.config_seq < support.sequence():
                        try:
                            env, _is_cfg, _seq = \
                                support.reprocess_config(env)
                        except Exception:
                            continue
                    pending = support.cutter.cut()
                    if pending:
                        self._propose_batch(pending, _NORMAL,
                                            sub.config_seq)
                        timer_deadline = None
                    self._propose_batch([env], _CONFIG, sub.config_seq)
                    continue
                if sub.config_seq < support.sequence():
                    try:
                        support.revalidate_normal(env)
                    except Exception:
                        continue
                batches, pending = support.cutter.ordered(env)
                if batches:
                    self._propose_normal_batches(batches, sub.config_seq)
                if batches:
                    timer_deadline = None
                if pending and timer_deadline is None:
                    timer_deadline = (time.monotonic()
                                      + support.batch_timeout_s())
            # timer expiry cuts the pending batch
            if timer_deadline is not None and \
                    time.monotonic() >= timer_deadline:
                timer_deadline = None
                batch = support.cutter.cut()
                if batch:
                    self._propose_batch(batch, _NORMAL, 0)

    # -- snapshots (reference: etcdraft snapshot catch-up) ----------------
    def _snapshot_state(self) -> bytes:
        """The app-state pointer carried by a raft snapshot: our block
        height.  The ledger IS the state (SURVEY §5.4) — a snapshot
        need only say how tall the chain is; a lagging node fetches
        the actual blocks."""
        return self._support.store.height.to_bytes(8, "big")

    def _install_snapshot(self, index: int, data: bytes) -> None:
        """Catch this node's chain up to the snapshot's height by
        pulling real blocks (reference: chain.go:880 catchUp via the
        cluster puller).  Raises when catch-up is impossible, which
        makes the raft layer refuse the snapshot."""
        target = int.from_bytes(data[:8], "big")
        support = self._support
        h = support.store.height
        if h < target:
            if self._fetch_blocks is None:
                raise RuntimeError("snapshot needs %d..%d but no block "
                                   "fetcher is configured" % (h, target))
            blocks = self._fetch_blocks(h, target)
            for block in blocks:
                self._append_fetched(block)
        if support.store.height < target:
            raise RuntimeError("catch-up fetched too few blocks")
        # fetched blocks may include config blocks that changed the
        # consenter set: raft membership must follow the bundle the
        # catch-up just installed (the WAL entries covering these
        # blocks are skipped by _applied_upto, so _apply's
        # update_peers would never fire for them)
        cfg_set = support.bundle().orderer.consenters()
        if cfg_set:
            # install_cb runs ON the FSM thread: apply synchronously
            # (enqueueing via update_peers would act one message late)
            self._raft._on_reconfig(list(cfg_set))
        # trust the raft index recorded in the fetched tip block (it
        # equals the snapshot index, but the block metadata is the
        # authoritative record) so WAL-replayed entries covering the
        # fetched blocks are skipped, not re-appended
        self._applied_upto = max(self._applied_upto, index,
                                 self._tip_raft_index(support.store))

    def _append_fetched(self, block: m.Block) -> None:
        """Append one pulled block, verifying the hash chain AND the
        orderer block signature against the channel's BlockValidation
        policy (reference: cluster.VerifyBlocks in the replication
        puller) — the fetch source is untrusted; config blocks go
        through process_config so the bundle follows."""
        from fabric_mod_tpu.peer.mcs import MessageCryptoService
        support = self._support
        store = support.store
        if block.header.number != store.height:
            raise RuntimeError("fetched block out of order")
        if store.height and \
                block.header.previous_hash != store.last_block_hash:
            raise RuntimeError("fetched block breaks the hash chain")
        MessageCryptoService(support.bundle).verify_block(
            support.channel_id, block)
        if self._is_config_block(block):
            envs = protoutil.get_envelopes(block)
            support.process_config(envs[0], block)
        else:
            support.writer.write_block(block)

    @classmethod
    def _tip_raft_index(cls, store) -> int:
        """Raft index recorded in the tip block's metadata (0 when the
        chain has no raft-written block yet)."""
        h = store.height
        if h > 1:
            tip = store.get_block_by_number(h - 1)
            md = tip.metadata.metadata if tip.metadata else []
            if len(md) > cls.RAFT_INDEX_MD_SLOT and \
                    md[cls.RAFT_INDEX_MD_SLOT]:
                return int.from_bytes(md[cls.RAFT_INDEX_MD_SLOT], "big")
        return 0

    @staticmethod
    def _is_config_block(block: m.Block) -> bool:
        try:
            envs = protoutil.get_envelopes(block)
            if len(envs) != 1:
                return False
            payload = protoutil.unmarshal_envelope_payload(envs[0])
            ch = m.ChannelHeader.decode(payload.header.channel_header)
            return ch.type == m.HeaderType.CONFIG
        except Exception:
            return False

    # -- apply (every node, in commit order) ------------------------------
    def _apply(self, index: int, data: bytes) -> None:
        """(reference: chain.go:964 apply -> writeBlock :791).  The
        raft index rides in the block's metadata so restarts skip
        entries already in the store (see __init__)."""
        if index <= self._applied_upto:
            return                         # WAL replay of a stored block
        kind, envs = _decode_batch(data)
        support = self._support
        block = support.writer.create_next_block(envs)
        md = block.metadata.metadata
        while len(md) <= self.RAFT_INDEX_MD_SLOT:
            md.append(b"")
        md[self.RAFT_INDEX_MD_SLOT] = index.to_bytes(8, "big")
        if kind == _CONFIG:
            if not self._config_still_valid(envs[0]):
                # deterministic skip on every replica: a config raced
                # by another config at the same sequence (or one whose
                # membership change became multi-member against the
                # NOW-current set) must not apply — the submission-time
                # checks ran against a stale bundle
                self._applied_upto = index
                return
            before = support.bundle().orderer.consenters()
            support.process_config(envs[0], block)
            after = support.bundle().orderer.consenters()
            if after and set(after) != set(before):
                # membership switches exactly when the config entry
                # applies — every replica reaches this at the same log
                # index (reference: ApplyConfChange on config commit)
                self._raft.update_peers(after)
        else:
            support.writer.write_block(block)
        self._applied_upto = index

    def _config_still_valid(self, env: m.Envelope) -> bool:
        """Apply-time re-validation (all replicas decide identically):
        the wrapped config must advance the sequence by exactly one,
        and its consenter change must still be single-member against
        the CURRENT set (two racing single-member updates validated
        against the same stale bundle would otherwise compose into the
        multi-member jump the guard forbids)."""
        try:
            payload = protoutil.unmarshal_envelope_payload(env)
            cenv = m.ConfigEnvelope.decode(payload.data)
            if cenv.config is None or \
                    cenv.config.sequence != self._support.sequence() + 1:
                return False
            self._check_membership_change(env)
            return True
        except Exception:
            return False
