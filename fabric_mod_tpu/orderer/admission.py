"""Admission control + backpressure for the orderer ingress.

(reference: orderer/common/broadcast's WaitReady flow control —
broadcast.go:166 blocks the stream until the consenter is ready — and
etcdraft's Submit path, which answers SERVICE_UNAVAILABLE instead of
wedging.  The reference degrades by ANSWERING the client; this module
generalizes that into typed shedding: a burst from many clients costs
`RESOURCE_EXHAUSTED + retry-after` answers, never a wedged node or a
silently-growing queue.)

Three cooperating mechanisms, all dark until a knob arms them
(`enabled()`), so an unconfigured deployment keeps the PR 6 behavior
bit-for-bit — blocking queue puts, no limiter, no gate:

* **Bounded submit queues** (`FABRIC_MOD_TPU_SUBMIT_QUEUE=N`): the
  consenter ingress queues (SoloChain/RaftChain) switch from blocking
  `put` to bounded non-blocking puts; a full queue answers the typed,
  retryable `ResourceExhaustedError` (reason="queue_full") instead of
  blocking the broadcast handler thread.  Config txs keep a blocking
  put — the queue is bounded, so they wait briefly rather than shed.

* **Per-client token buckets** (`FABRIC_MOD_TPU_INGRESS_RATE=R`,
  optionally `FABRIC_MOD_TPU_INGRESS_BURST=B`): each client identity
  (hash of the envelope's creator) draws from its own bucket of R
  tokens/s; an empty bucket sheds with reason="rate_limited" and a
  retry-after equal to the real token deficit.  The clock is
  injectable (ManualClock-testable, like utils/retry.Retrier).  The
  client table is bounded: least-recently-seen buckets are evicted, so
  millions of one-shot clients cannot grow host memory.

* **Overload gate** (watermarks over submit-queue occupancy + an EWMA
  of admission latency, `FABRIC_MOD_TPU_SHED_HIGH`/`_SHED_LOW`/
  `_SHED_LAT_S`): opens at the high watermark (or when the latency
  EWMA crosses the threshold), sheds NORMAL txs with
  reason="overloaded", and closes only back at the low watermark —
  hysteresis, so the gate doesn't flap at the boundary.  Config and
  lifecycle txs are ALWAYS admitted while the gate is open: an
  operator must be able to land the config change that relieves the
  overload (the reference's config-tx priority in the blockcutter).

Shed accounting rides /metrics (queue occupancy, sheds by reason,
throttled-client gauge, gate state, admission-latency histogram).  The
per-client throttle counts live on the bounded limiter table
(`AdmissionController.throttles_by_client()`), not as metric labels —
one label value per client identity would be unbounded exposition
cardinality under exactly the burst this module exists to survive.

Chaos: `faults.point("orderer.admission.overload")` in drop mode
forces the gate open for that pass (reason="forced"), so an FMT_FAULTS
plan can drive shedding without constructing a real overload.
"""
from __future__ import annotations

import functools
import hashlib
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from fabric_mod_tpu import faults
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.utils import knobs
from fabric_mod_tpu.concurrency.locks import RegisteredLock

# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------


def submit_queue_cap() -> int:
    """FABRIC_MOD_TPU_SUBMIT_QUEUE: consenter ingress queue bound with
    non-blocking puts; 0/unset keeps the blocking 10k-queue PR 6
    behavior."""
    return max(0, knobs.get_int("FABRIC_MOD_TPU_SUBMIT_QUEUE"))


def ingress_rate() -> float:
    """FABRIC_MOD_TPU_INGRESS_RATE: per-client sustained tokens/s; 0
    disables the limiter."""
    return max(0.0, knobs.get_float("FABRIC_MOD_TPU_INGRESS_RATE"))


def ingress_burst(rate: float) -> float:
    """FABRIC_MOD_TPU_INGRESS_BURST: bucket capacity (burst size);
    default 2x the rate, floor 1."""
    return max(1.0, knobs.get_float("FABRIC_MOD_TPU_INGRESS_BURST",
                              max(1.0, 2.0 * rate)))


def shed_watermarks() -> Tuple[float, float]:
    """FABRIC_MOD_TPU_SHED_HIGH / FABRIC_MOD_TPU_SHED_LOW: submit-queue
    occupancy fractions that open/close the overload gate."""
    high = min(1.0, max(0.0, knobs.get_float("FABRIC_MOD_TPU_SHED_HIGH")))
    low = min(high, max(0.0, knobs.get_float("FABRIC_MOD_TPU_SHED_LOW")))
    return high, low


def shed_latency_s() -> float:
    """FABRIC_MOD_TPU_SHED_LAT_S: admission-latency EWMA (seconds) that
    opens the gate even below the occupancy watermark; 0 disables the
    latency trigger."""
    return max(0.0, knobs.get_float("FABRIC_MOD_TPU_SHED_LAT_S"))


def enabled() -> bool:
    """Any admission knob armed?  False = the PR 6 ingress, untouched."""
    return (submit_queue_cap() > 0 or ingress_rate() > 0.0
            or shed_latency_s() > 0.0)


# ---------------------------------------------------------------------------
# metrics (get-or-create: chains/controllers instantiate many times)
# ---------------------------------------------------------------------------

_OCCUPANCY_OPTS = MetricOpts(
    "fabric", "orderer", "submit_queue_occupancy",
    help="Consenter submit-queue occupancy fraction (qsize/maxsize) "
         "observed at the last admission decision, per channel.",
    label_names=("channel",))
_SHEDS_OPTS = MetricOpts(
    "fabric", "orderer", "admission_sheds_total",
    help="Submissions shed by admission control, per reason "
         "(rate_limited | overloaded | queue_full | forced).",
    label_names=("reason",))
_THROTTLES_OPTS = MetricOpts(
    "fabric", "orderer", "admission_throttles_total",
    help="Per-client rate-limit rejections, totalled (the per-client "
         "split lives on the bounded limiter table, not labels).")
_THROTTLED_CLIENTS_OPTS = MetricOpts(
    "fabric", "orderer", "admission_throttled_clients",
    help="Distinct clients with at least one rate-limit rejection "
         "still resident in the (bounded) limiter table.")
_GATE_OPTS = MetricOpts(
    "fabric", "orderer", "overload_gate_open",
    help="1 while a channel's overload gate is shedding normal txs, "
         "else 0.",
    label_names=("channel",))
_LATENCY_OPTS = MetricOpts(
    "fabric", "orderer", "admission_latency_seconds",
    help="Broadcast admission latency: route + admit + processor + "
         "enqueue, per accepted submission.")
_CHAIN_DROPS_OPTS = MetricOpts(
    "fabric", "orderer", "chain_msgs_dropped_total",
    help="Chain-level messages dropped on a full queue, per path "
         "(forward = follower->leader submits, requeue = leadership-"
         "loss reproposals, raft_msg = raft FSM ingress).",
    label_names=("path",))


@functools.lru_cache(maxsize=None)
def _metrics():
    prov = default_provider()
    return {
        "occupancy": prov.gauge(_OCCUPANCY_OPTS),
        "sheds": prov.counter(_SHEDS_OPTS),
        "throttles": prov.counter(_THROTTLES_OPTS),
        "throttled_clients": prov.gauge(_THROTTLED_CLIENTS_OPTS),
        "gate": prov.gauge(_GATE_OPTS),
        "latency": prov.histogram(_LATENCY_OPTS),
    }


@functools.lru_cache(maxsize=None)
def chain_drop_counter():
    """Shared drop counter for chain/raft queue overflows (the
    satellite observability for what used to be silent `queue.Full`
    passes)."""
    return default_provider().counter(_CHAIN_DROPS_OPTS)


# ---------------------------------------------------------------------------
# the typed shed answer
# ---------------------------------------------------------------------------


class ResourceExhaustedError(Exception):
    """The ingress shed this submission: retryable by construction.

    `retry_after_s` is the server's hint for when a retry can succeed
    (the real token deficit for rate limits, a drain estimate for
    queue/overload sheds); the gRPC surface serializes it so remote
    clients back off exactly that long instead of guessing.  `reason`
    is the shed class the metrics count: "rate_limited", "overloaded",
    "queue_full", or "forced" (chaos)."""

    def __init__(self, msg: str, reason: str = "overloaded",
                 retry_after_s: float = 0.25):
        super().__init__(msg)
        self.reason = reason
        self.retry_after_s = retry_after_s


def shed(reason: str, msg: str,
         retry_after_s: float = 0.25) -> ResourceExhaustedError:
    """Count one shed and build the typed answer (callers raise it).
    Centralized so every shed — controller or chain queue — lands in
    the same counter (and, FMT_TRACE armed, the flight recorder's
    event tape: overload sheds show up next to the block timelines
    they interleaved with)."""
    _metrics()["sheds"].with_labels(reason).add(1)
    from fabric_mod_tpu.observability import tracing
    tracing.note_event("admission_shed", reason)
    return ResourceExhaustedError(msg, reason=reason,
                                  retry_after_s=retry_after_s)


# ---------------------------------------------------------------------------
# token bucket + per-client limiter
# ---------------------------------------------------------------------------


class TokenBucket:
    """Classic token bucket with an injectable clock.  Not
    thread-safe; the limiter serializes access."""

    __slots__ = ("rate", "burst", "tokens", "stamp", "throttles")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.stamp = now
        self.throttles = 0

    def try_take(self, now: float) -> float:
        """Take one token; returns 0.0 on success, else the seconds
        until a token accrues (the retry-after hint)."""
        if now > self.stamp:
            self.tokens = min(self.burst,
                              self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        self.throttles += 1
        return (1.0 - self.tokens) / self.rate if self.rate > 0 else 1.0


class ClientRateLimiter:
    """client key -> TokenBucket, bounded: least-recently-seen buckets
    are evicted at `max_clients` (an evicted client restarts with a
    full bucket — biased toward admitting, never toward wedging).

    The client key is the UNAUTHENTICATED creator (admission runs
    before the signature check, on purpose — shedding must be cheap),
    so a flood of forged, ever-fresh creators must not mint a fresh
    full bucket per envelope.  First-seen clients therefore ALSO draw
    from one shared "newcomers" bucket, sized `NEWCOMER_SCALE` x the
    per-client rate: invisible in normal operation, but a sybil burst
    drains it and gets rate_limited typed — and legitimately-new
    clients degrade the same bounded way while the burst lasts."""

    NEWCOMER_SCALE = 64

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock=None, max_clients: int = 4096):
        self.rate = rate
        self.burst = burst if burst is not None else ingress_burst(rate)
        self._clock = clock or time
        self._max = max(1, max_clients)
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()
        self._lock = RegisteredLock("orderer.admission.ClientRateLimiter._lock")
        self._throttled = 0                # buckets with throttles > 0
        newcomer_rate = rate * self.NEWCOMER_SCALE
        self._newcomers = TokenBucket(
            newcomer_rate, max(self.burst, 2.0 * newcomer_rate),
            self._clock.monotonic())

    def admit(self, client: str) -> float:
        """0.0 = admitted; >0 = shed, retry after that many seconds."""
        now = self._clock.monotonic()
        with self._lock:
            bucket = self._buckets.get(client)
            if bucket is None:
                wait = self._newcomers.try_take(now)
                if wait > 0.0:
                    # forged-creator (or genuine thundering-herd)
                    # pressure: refuse to mint the bucket at all
                    _metrics()["throttles"].add(1)
                    return wait
                bucket = TokenBucket(self.rate, self.burst, now)
                self._buckets[client] = bucket
                while len(self._buckets) > self._max:
                    _key, gone = self._buckets.popitem(last=False)
                    if gone.throttles:
                        self._throttled -= 1
            else:
                self._buckets.move_to_end(client)
            wait = bucket.try_take(now)
            if wait > 0.0:
                if bucket.throttles == 1:
                    self._throttled += 1
                _metrics()["throttles"].add(1)
                _metrics()["throttled_clients"].set(self._throttled)
            return wait

    def throttles_by_client(self) -> Dict[str, int]:
        with self._lock:
            return {c: b.throttles for c, b in self._buckets.items()
                    if b.throttles}


# ---------------------------------------------------------------------------
# overload gate: occupancy watermarks + latency EWMA, with hysteresis
# ---------------------------------------------------------------------------


class OverloadGate:
    """Opens at `high` occupancy (or latency EWMA >= `lat_high_s`),
    closes at `low` — the hysteresis band keeps the gate from flapping
    when occupancy hovers at one watermark.  While open, NORMAL txs
    shed; config/lifecycle txs pass (the controller enforces that).

    The EWMA DECAYS over wall time (half-life `HALF_LIVES *
    lat_high_s`), not only on accepted samples: an open gate sheds the
    very traffic whose latencies would otherwise update the EWMA, so a
    sample-driven-only EWMA would latch a latency-opened gate open
    forever once the stall that caused it had passed.  The clock is
    injectable (ManualClock-testable)."""

    HALF_LIVES = 4.0                       # decay half-life factor

    def __init__(self, high: float = 0.9, low: float = 0.6,
                 lat_high_s: float = 0.0, ewma_alpha: float = 0.2,
                 clock=None, channel: str = ""):
        if low > high:
            raise ValueError("low watermark above high")
        self.high = high
        self.low = low
        self.lat_high_s = lat_high_s
        self.channel = channel
        self._alpha = ewma_alpha
        self._clock = clock or time
        self._ewma = 0.0
        self._stamp = self._clock.monotonic()
        self._open = False
        self._lock = RegisteredLock("orderer.admission.OverloadGate._lock")

    @property
    def is_open(self) -> bool:
        return self._open

    @property
    def latency_ewma_s(self) -> float:
        with self._lock:
            self._decay()
            return self._ewma

    def _decay(self) -> None:
        """Wall-time decay (caller holds the lock): exponential with a
        half-life tied to the latency threshold, so a stall's imprint
        fades within a few thresholds even when every sample is being
        shed."""
        now = self._clock.monotonic()
        dt = now - self._stamp
        self._stamp = now
        if dt <= 0.0 or self._ewma == 0.0:
            return
        half = (self.HALF_LIVES * self.lat_high_s
                if self.lat_high_s > 0.0 else 1.0)
        self._ewma *= 2.0 ** (-dt / half)

    def note_latency(self, seconds: float) -> None:
        with self._lock:
            self._decay()
            self._ewma += self._alpha * (seconds - self._ewma)

    def observe(self, occupancy: float) -> bool:
        """Feed one occupancy sample; returns the (possibly updated)
        gate state."""
        with self._lock:
            self._decay()
            lat_hot = (self.lat_high_s > 0.0
                       and self._ewma >= self.lat_high_s)
            if not self._open:
                if occupancy >= self.high or lat_hot:
                    self._open = True
            else:
                # close only when BOTH pressure signals have receded:
                # occupancy back under the low watermark and (if the
                # latency trigger is armed) the EWMA halved
                if occupancy <= self.low and (
                        self.lat_high_s <= 0.0
                        or self._ewma <= self.lat_high_s / 2.0):
                    self._open = False
            _metrics()["gate"].with_labels(self.channel).set(
                1.0 if self._open else 0.0)
            return self._open

    def retry_after_s(self) -> float:
        """Shed hint while open: a few EWMA latencies (the queue needs
        roughly that long to drain below the band), bounded sane."""
        ewma = self.latency_ewma_s
        return max(0.1, min(5.0, 8.0 * ewma)) if ewma else 0.25


# ---------------------------------------------------------------------------
# the controller Broadcast.submit consults
# ---------------------------------------------------------------------------


class AdmissionController:
    """Per-process admission policy: rate limiter + per-CHANNEL
    overload gates + the metrics that make shedding observable.

    `admit()` runs BEFORE the processor's signature work — the whole
    point is to answer an overload cheaply, not after paying the
    expensive part.  Priority traffic (config updates, orderer txs,
    lifecycle invocations) bypasses both mechanisms.

    The limiter is process-wide (one client = one bucket no matter
    which channel it floods); gate state is per channel — a hot
    channel's full queue must shed ITS traffic, not an idle
    neighbor's, and an idle channel's 0.0 samples must not defeat the
    hot channel's hysteresis."""

    def __init__(self, limiter: Optional[ClientRateLimiter] = None,
                 gate: Optional[OverloadGate] = None, clock=None):
        """`gate` is the default channel's gate AND the template whose
        watermark/latency parameters every per-channel gate copies;
        None disables the gate mechanism."""
        self._limiter = limiter
        self._clock = clock or time
        self._template = gate
        self._gates: Dict[str, OverloadGate] = {}
        self._gates_lock = RegisteredLock("orderer.admission._gates_lock")
        if gate is not None:
            self._gates[gate.channel] = gate

    @classmethod
    def from_env(cls, clock=None) -> Optional["AdmissionController"]:
        """The knob-built controller, or None when every knob is unset
        (the caller then skips admission entirely — PR 6 behavior)."""
        if not enabled():
            return None
        limiter = None
        rate = ingress_rate()
        if rate > 0.0:
            limiter = ClientRateLimiter(rate, clock=clock)
        high, low = shed_watermarks()
        gate = OverloadGate(high, low, lat_high_s=shed_latency_s(),
                            clock=clock)
        return cls(limiter=limiter, gate=gate, clock=clock)

    @property
    def gate(self) -> Optional[OverloadGate]:
        """The default ("") channel's gate (tests drive this one)."""
        return self._gates.get("") if self._template is not None \
            else None

    def gate_for(self, channel: str) -> Optional[OverloadGate]:
        if self._template is None:
            return None
        with self._gates_lock:
            got = self._gates.get(channel)
            if got is None:
                tpl = self._template
                got = OverloadGate(tpl.high, tpl.low,
                                   lat_high_s=tpl.lat_high_s,
                                   ewma_alpha=tpl._alpha,
                                   clock=self._clock, channel=channel)
                self._gates[channel] = got
            return got

    def throttles_by_client(self) -> Dict[str, int]:
        return (self._limiter.throttles_by_client()
                if self._limiter is not None else {})

    @property
    def has_limiter(self) -> bool:
        """False lets callers skip the client-key hash entirely."""
        return self._limiter is not None

    # -- the decision -----------------------------------------------------
    def admit(self, client: str, priority: bool, occupancy: float,
              channel: str = "") -> None:
        """Raise the typed shed answer, or return (admitted).
        `occupancy` is `channel`'s consenter submit-queue fraction as
        read by the caller (0.0 when the chain doesn't expose one)."""
        _metrics()["occupancy"].with_labels(channel).set(occupancy)
        forced = faults.point("orderer.admission.overload")
        gate = self.gate_for(channel)
        gate_open = gate.observe(occupancy) if gate is not None \
            else False
        if priority:
            return                         # config/lifecycle: always in
        if forced:
            raise shed("forced", "admission gate forced open (chaos)",
                       retry_after_s=0.25)
        if gate_open:
            assert gate is not None
            raise shed(
                "overloaded",
                f"channel {channel!r} overloaded "
                f"(queue {occupancy:.0%} full)",
                retry_after_s=gate.retry_after_s())
        if self._limiter is not None:
            wait = self._limiter.admit(client)
            if wait > 0.0:
                raise shed(
                    "rate_limited",
                    f"client {client} over {self._limiter.rate:g} tx/s",
                    retry_after_s=wait)

    def note_latency(self, seconds: float, channel: str = "") -> None:
        """Feed one ACCEPTED submission's admission latency (route +
        admit + processor + enqueue) into the histogram and the
        channel gate's EWMA trigger."""
        _metrics()["latency"].observe(seconds)
        gate = self.gate_for(channel)
        if gate is not None:
            gate.note_latency(seconds)


# ---------------------------------------------------------------------------
# envelope classification helpers (cheap: header-only parsing)
# ---------------------------------------------------------------------------


def classify(env, is_config_update: bool = False,
             need_client: bool = True) -> Tuple[str, bool]:
    """One-pass (client_key, priority) classification — the envelope
    payload is decoded ONCE; `need_client=False` (no limiter armed)
    skips the signature-header decode + hash entirely.  Shedding must
    cost a header parse, so this is the hot path's only parse.

    client_key: short hash of the signature-header creator (cert
    bytes) — one cert = one bucket no matter how many connections it
    opens.  Unparseable envelopes share the "" bucket: they will be
    rejected by the processor anyway, and a shared bucket stops a
    garbage flood from minting unlimited fresh buckets.

    priority: anything that isn't a plain endorser transaction
    (config updates, orderer txs), plus endorser txs whose channel-
    header extension names the _lifecycle namespace — traffic the
    gate/limiter must never shed."""
    from fabric_mod_tpu.protos import messages as m
    try:
        payload = m.Payload.decode(env.payload)
        ch = m.ChannelHeader.decode(payload.header.channel_header)
    except Exception:
        return "", is_config_update
    client = ""
    if need_client:
        try:
            sh = m.SignatureHeader.decode(
                payload.header.signature_header)
            if sh.creator:
                client = hashlib.sha256(
                    sh.creator).hexdigest()[:16]
        except Exception:  # fmtlint: allow[swallowed-exceptions] -- malformed signature header: classify as the shared anonymous client; the processor rejects the envelope with a typed error later
            pass
    priority = is_config_update or \
        ch.type != m.HeaderType.ENDORSER_TRANSACTION
    if not priority and ch.extension:
        try:
            ext = m.ChaincodeHeaderExtension.decode(ch.extension)
            priority = (ext.chaincode_id is not None
                        and ext.chaincode_id.name == "_lifecycle")
        except Exception:  # fmtlint: allow[swallowed-exceptions] -- malformed extension: not priority traffic; the processor surfaces the real decode error
            pass
    return client, priority


def client_key(env) -> str:
    """classify()'s client half (kept for callers that only need the
    bucket key)."""
    return classify(env)[0]


def is_priority(env, is_config_update: bool = False) -> bool:
    """classify()'s priority half (also the bounded queues' full-path
    re-check: a lifecycle tx on a full queue must block like a config
    tx, never shed)."""
    return classify(env, is_config_update, need_client=False)[1]


def chain_occupancy(chain) -> float:
    """Submit-queue occupancy fraction of a consenter, 0.0 when the
    chain doesn't expose `submit_queue_depth()`."""
    depth_fn = getattr(chain, "submit_queue_depth", None)
    if depth_fn is None:
        return 0.0
    try:
        qsize, maxsize = depth_fn()
    except Exception:
        return 0.0
    return (qsize / maxsize) if maxsize else 0.0
