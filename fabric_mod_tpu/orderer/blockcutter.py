"""Block cutting: batch envelopes by count/bytes (+ caller timeout).

(reference: orderer/common/blockcutter/blockcutter.go — `Ordered` at
:69 with its three cut conditions, `Cut` at :127.  The batch timeout
lives in the consenter loop, not here, exactly like the reference
where the chain's main loop owns the timer.)
"""
from __future__ import annotations

import dataclasses
from typing import List, Tuple

from fabric_mod_tpu.protos import messages as m


@dataclasses.dataclass(frozen=True)
class BatchConfig:
    """(reference: channelconfig BatchSize/BatchTimeout values)"""
    max_message_count: int = 500
    absolute_max_bytes: int = 10 * 1024 * 1024
    preferred_max_bytes: int = 2 * 1024 * 1024
    batch_timeout_s: float = 2.0


class BlockCutter:
    def __init__(self, config: BatchConfig):
        self.config = config
        self._pending: List[m.Envelope] = []
        self._pending_bytes = 0

    @property
    def pending(self) -> bool:
        return bool(self._pending)

    def ordered(self, env: m.Envelope
                ) -> Tuple[List[List[m.Envelope]], bool]:
        """Enqueue one message; returns (batches_to_cut, pending_left).

        Cut conditions (reference blockcutter.go:69-125):
          1. an oversized message (> preferred_max_bytes) cuts the
             pending batch and then rides alone;
          2. a message that would overflow preferred_max_bytes cuts
             the pending batch first;
          3. reaching max_message_count cuts immediately.
        """
        size = len(env.encode())
        batches: List[List[m.Envelope]] = []

        if size > self.config.preferred_max_bytes:
            if self._pending:
                batches.append(self.cut())
            batches.append([env])
            return batches, False

        if self._pending_bytes + size > self.config.preferred_max_bytes \
                and self._pending:
            batches.append(self.cut())

        self._pending.append(env)
        self._pending_bytes += size
        if len(self._pending) >= self.config.max_message_count:
            batches.append(self.cut())
        return batches, bool(self._pending)

    def cut(self) -> List[m.Envelope]:
        batch, self._pending = self._pending, []
        self._pending_bytes = 0
        return batch
