"""Staged broadcast ingress: coalesce concurrent submitters' verifies.

The commit path batches (PR 3 commitpipe, PR 12 fused policy); ingress
still pays one Writers-policy evaluation — one `verify_many` dispatch —
per `Broadcast.submit()` call.  Under a many-client storm those
dispatches are the orderer's cap long before raft or the cutter are.

This module is the ingress analogue of the commit pipeline's batching
discipline: concurrent submitters deposit their normal-tx envelopes
into a per-channel staging lane and block on a tagged verdict slot; a
single drainer thread per lane coalesces everything waiting (up to the
`FABRIC_MOD_TPU_STAGED_BROADCAST` batch bound), runs the whole cohort
through `StandardChannelProcessor.process_normal_msgs` — ONE bundle
read, ONE `verify_many` dispatch through the same batch-verifier seam
the commit path uses — and fans the typed per-envelope verdicts back.
Each submitter then continues on its OWN thread: `chain.order`, the
NotLeaderError retrier, and admission's `note_latency` all stay
per-envelope, so a mid-batch leadership loss retries/sheds each staged
envelope individually and the overload gate's EWMA keeps seeing true
submit-to-verdict latencies (not one per-batch sample).

Config txs never enter a lane — they keep the blocking
`process_config_update_msg` path, and their sequence semantics are
unchanged: a config commit bumps the bundle, and any staged normal tx
validated under the older sequence is re-validated by the cutter/chain
exactly as in the unstaged path.

Fault injection: `faults.point("orderer.broadcast.stage")` fires per
drain; a triggered rule (drop OR error mode) downgrades that cohort to
the per-envelope classic path — an ingress-engine fault costs
amortization, never a lost or mis-verdicted transaction.  The drain
runs under the `broadcast.stage` span.
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, List

from fabric_mod_tpu import faults
from fabric_mod_tpu.concurrency.locks import RegisteredLock
from fabric_mod_tpu.concurrency.queues import GuardedQueue
from fabric_mod_tpu.concurrency.threads import RegisteredThread
from fabric_mod_tpu.observability import tracing
from fabric_mod_tpu.utils import knobs


def staged_batch() -> int:
    """FABRIC_MOD_TPU_STAGED_BROADCAST: max envelopes one drain
    coalesces into a single batched verify; 0/unset disables."""
    return max(0, knobs.get_int("FABRIC_MOD_TPU_STAGED_BROADCAST"))


class _Pending:
    """One deposited submission: its envelope + the verdict slot the
    submitter blocks on."""

    __slots__ = ("env", "processor", "_done", "_seq", "_err")

    def __init__(self, env, processor):
        self.env = env
        self.processor = processor
        self._done = threading.Event()
        self._seq = None                 # config sequence on acceptance
        self._err = None                 # typed exception on rejection

    def resolve(self, verdict) -> None:
        if isinstance(verdict, BaseException):
            self._err = verdict
        else:
            self._seq = verdict
        self._done.set()

    def wait(self) -> int:
        self._done.wait()
        if self._err is not None:
            raise self._err
        return self._seq


class _Lane:
    """One channel's staging lane: a bounded deposit queue drained by
    one coalescing worker thread."""

    def __init__(self, channel_id: str, max_batch: int):
        self._max = max(1, max_batch)
        self._q = GuardedQueue(
            max(64, 2 * self._max),
            name=f"broadcast.stage.{channel_id}")
        self._thread = RegisteredThread(
            target=self._run, name=f"broadcast-stage-{channel_id}",
            structure="stagedbroadcast")
        self._thread.start()

    def deposit(self, pending: _Pending) -> None:
        self._q.put(pending)             # bounded: deposits backpressure

    def close(self) -> None:
        self._q.put(None)
        self._thread.join(timeout=10)
        # a deposit that raced past the sentinel still resolves typed
        # (the drainer released the consumer side on exit): close can
        # never leave a submitter blocked forever
        while True:
            try:
                p = self._q.get_nowait()
            except queue.Empty:
                return
            if p is not None:
                p.resolve(RuntimeError("staged ingress closed"))

    # -- drainer ----------------------------------------------------------
    def _run(self) -> None:
        try:
            while True:
                head = self._q.get()
                closing = head is None
                batch: List[_Pending] = [] if closing else [head]
                while len(batch) < self._max:
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is None:
                        closing = True   # keep draining: a deposit
                        continue         # racing close still resolves
                    batch.append(nxt)
                if batch:
                    self._flush(batch)
                if closing:
                    return
        finally:
            self._q.release_consumer()

    def _flush(self, batch: List[_Pending]) -> None:
        try:
            with tracing.span("broadcast.stage"):
                if faults.point("orderer.broadcast.stage"):
                    raise RuntimeError("injected stage fault")
                verdicts = batch[0].processor.process_normal_msgs(
                    [p.env for p in batch])
        except Exception:  # noqa: BLE001 -- engine fault (injected or
            # real): downgrade THIS cohort to the classic per-envelope
            # path so a staging fault never loses a submission
            for p in batch:
                try:
                    p.resolve(p.processor.process_normal_msg(p.env))
                except Exception as e:  # noqa: BLE001 -- slot verdict
                    p.resolve(e)
            return
        for p, v in zip(batch, verdicts):
            p.resolve(v)


class StagedIngress:
    """The per-channel lane registry behind `Broadcast.submit`."""

    def __init__(self, max_batch: int):
        self._max = max_batch
        self._mu = RegisteredLock("stagedbroadcast.lanes")
        self._lanes: Dict[str, _Lane] = {}
        self._closed = False

    def submit(self, channel_id: str, processor, env) -> int:
        """Deposit one normal tx and block until its verdict: returns
        the config sequence it validated under, or raises the typed
        per-envelope rejection."""
        pending = _Pending(env, processor)
        self._lane(channel_id).deposit(pending)
        return pending.wait()

    def _lane(self, channel_id: str) -> _Lane:
        with self._mu:
            if self._closed:
                raise RuntimeError("staged ingress closed")
            lane = self._lanes.get(channel_id)
            if lane is None:
                lane = _Lane(channel_id, self._max)
                self._lanes[channel_id] = lane
            return lane

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            self._closed = True
            lanes = list(self._lanes.values())
            self._lanes.clear()
        for lane in lanes:
            lane.close()
