"""Consenters: the ordering loop that turns envelopes into blocks.

(reference: orderer/consensus/solo/consensus.go:183 — the single
goroutine select loop over normal/config messages and the batch
timer — and the consenter contract in orderer/consensus/consensus.go.)

`SoloChain` is the dev/single-node consenter: one worker thread drains
an ingress queue, feeds the block cutter, owns the batch timer, and
drives the block writer.  Config envelopes cut the pending batch and
ride alone in their own block, after which the chain support swaps the
channel bundle — identical ordering semantics to the reference's solo,
with the queue standing in for the Go channel select.
"""
from __future__ import annotations

import queue
import threading
from typing import Optional

from fabric_mod_tpu.protos import messages as m


class ChainHaltedError(Exception):
    pass


class NotLeaderError(Exception):
    """This consenter cannot accept the submission right now: it is
    not the leader and has no live leader to forward to (a leaderless
    election window, or a deposed leader mid-step-down).

    `leader_hint` is the consenter id of the best-known leader (None
    when unknown) — the reference's Submit redirect carries the same
    hint (orderer/common/cluster: SubmitResponse.Info).  Retryable by
    construction: Broadcast.submit retries it on a backoff schedule,
    and the gRPC surface maps it to SERVICE_UNAVAILABLE so remote
    clients do the same."""

    def __init__(self, msg: str, leader_hint=None):
        super().__init__(msg)
        self.leader_hint = leader_hint


class _Msg:
    __slots__ = ("env", "is_config", "config_seq")

    def __init__(self, env: m.Envelope, is_config: bool, config_seq: int):
        self.env = env
        self.is_config = is_config
        self.config_seq = config_seq


class SoloChain:
    """Single-node consenter (reference: solo/consensus.go:183).

    `support` provides: cutter (BlockCutter), writer (BlockWriter),
    batch_timeout_s(), process_config(env) -> applies the config and
    returns None, and reprocess hooks when config_seq went stale.
    """

    def __init__(self, support):
        self._support = support
        self._q: "queue.Queue[Optional[_Msg]]" = queue.Queue(maxsize=10_000)
        self._halted = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    # -- consenter API (reference: consensus.go Order/Configure) ---------
    def start(self) -> None:
        self._thread.start()

    def halt(self) -> None:
        if self._halted.is_set():
            return
        self._halted.set()
        self._q.put(None)                 # wake the loop
        self._thread.join(timeout=10)

    def wait_ready(self) -> None:
        """Backpressure point (reference: WaitReady) — solo accepts
        whenever the queue has room; Queue.put blocks if full."""
        if self._halted.is_set():
            raise ChainHaltedError("chain is halted")

    def order(self, env: m.Envelope, config_seq: int) -> None:
        self.wait_ready()
        self._q.put(_Msg(env, False, config_seq))

    def configure(self, env: m.Envelope, config_seq: int) -> None:
        self.wait_ready()
        self._q.put(_Msg(env, True, config_seq))

    # -- the ordering loop ----------------------------------------------
    def _run(self) -> None:
        support = self._support
        timer_deadline: Optional[float] = None
        import time
        while not self._halted.is_set():
            timeout = None
            if timer_deadline is not None:
                timeout = max(0.0, timer_deadline - time.monotonic())
            try:
                msg = self._q.get(timeout=timeout)
            except queue.Empty:
                # batch timer fired (reference: case <-timer)
                timer_deadline = None
                batch = support.cutter.cut()
                if batch:
                    block = support.writer.create_next_block(batch)
                    support.writer.write_block(block)
                continue
            if msg is None:
                break
            if msg.is_config:
                # config messages cut pending and ride alone
                # (reference: solo consensus.go config branch)
                if msg.config_seq < support.sequence():
                    # stale validation: reprocess under current config
                    try:
                        msg = _Msg(*support.reprocess_config(msg.env))
                    except Exception:
                        continue          # rejected under new config
                batch = support.cutter.cut()
                if batch:
                    block = support.writer.create_next_block(batch)
                    support.writer.write_block(block)
                    timer_deadline = None
                block = support.writer.create_next_block([msg.env])
                support.process_config(msg.env, block)
                continue
            if msg.config_seq < support.sequence():
                try:
                    support.revalidate_normal(msg.env)
                except Exception:
                    continue              # rejected under new config
            batches, pending = support.cutter.ordered(msg.env)
            for batch in batches:
                block = support.writer.create_next_block(batch)
                support.writer.write_block(block)
            if batches:
                timer_deadline = None
            if pending and timer_deadline is None:
                timer_deadline = (time.monotonic()
                                  + support.batch_timeout_s())
        # drain-free halt: pending messages are dropped like the
        # reference's Halt (clients resubmit after failover)
