"""Consenters: the ordering loop that turns envelopes into blocks.

(reference: orderer/consensus/solo/consensus.go:183 — the single
goroutine select loop over normal/config messages and the batch
timer — and the consenter contract in orderer/consensus/consensus.go.)

`SoloChain` is the dev/single-node consenter: one worker thread drains
an ingress queue, feeds the block cutter, owns the batch timer, and
drives the block writer.  Config envelopes cut the pending batch and
ride alone in their own block, after which the chain support swaps the
channel bundle — identical ordering semantics to the reference's solo,
with the queue standing in for the Go channel select.
"""
from __future__ import annotations

import queue
import threading
from typing import Optional

from fabric_mod_tpu.orderer import admission
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.concurrency.threads import RegisteredThread


class ChainHaltedError(Exception):
    pass


class NotLeaderError(Exception):
    """This consenter cannot accept the submission right now: it is
    not the leader and has no live leader to forward to (a leaderless
    election window, or a deposed leader mid-step-down).

    `leader_hint` is the consenter id of the best-known leader (None
    when unknown) — the reference's Submit redirect carries the same
    hint (orderer/common/cluster: SubmitResponse.Info).  Retryable by
    construction: Broadcast.submit retries it on a backoff schedule,
    and the gRPC surface maps it to SERVICE_UNAVAILABLE so remote
    clients do the same."""

    def __init__(self, msg: str, leader_hint=None):
        super().__init__(msg)
        self.leader_hint = leader_hint


class _Msg:
    __slots__ = ("env", "is_config", "config_seq")

    def __init__(self, env: m.Envelope, is_config: bool, config_seq: int):
        self.env = env
        self.is_config = is_config
        self.config_seq = config_seq


class SoloChain:
    """Single-node consenter (reference: solo/consensus.go:183).

    `support` provides: cutter (BlockCutter), writer (BlockWriter),
    batch_timeout_s(), process_config(env) -> applies the config and
    returns None, and reprocess hooks when config_seq went stale.
    """

    def __init__(self, support):
        self._support = support
        # FABRIC_MOD_TPU_SUBMIT_QUEUE bounds the ingress queue with
        # NON-blocking puts (typed shed on full); unset keeps the
        # blocking 10k queue — the pre-admission behavior, byte for
        # byte (the differential test pins this)
        cap = admission.submit_queue_cap()
        self._bounded = cap > 0
        self._q: "queue.Queue[Optional[_Msg]]" = queue.Queue(
            maxsize=cap if self._bounded else 10_000)
        self._halted = threading.Event()
        self._thread = RegisteredThread(target=self._run,
                                        name="solo-chain",
                                        structure="orderer.consensus")

    # -- consenter API (reference: consensus.go Order/Configure) ---------
    def start(self) -> None:
        self._thread.start()

    def halt(self) -> None:
        if self._halted.is_set():
            return
        self._halted.set()
        try:
            # wake-up only: get() blocks solely on an EMPTY queue, so
            # the sentinel is needed exactly when put_nowait succeeds;
            # a blocking put on a FULL bounded queue would deadlock
            # against a run loop that already exited on _halted
            self._q.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=10)

    def wait_ready(self) -> None:
        """Backpressure point (reference: WaitReady) — solo accepts
        whenever the queue has room; Queue.put blocks if full."""
        if self._halted.is_set():
            raise ChainHaltedError("chain is halted")

    def order(self, env: m.Envelope, config_seq: int) -> None:
        self.wait_ready()
        self._enqueue(_Msg(env, False, config_seq), is_config=False)

    def configure(self, env: m.Envelope, config_seq: int) -> None:
        self.wait_ready()
        self._enqueue(_Msg(env, True, config_seq), is_config=True)

    def submit_queue_depth(self):
        """(qsize, maxsize) — the occupancy signal the overload gate
        watches."""
        return self._q.qsize(), self._q.maxsize

    def _enqueue(self, msg: _Msg, is_config: bool) -> None:
        """Bounded mode sheds a full queue typed instead of blocking
        the broadcast handler; config txs keep the blocking put (the
        queue is bounded, so they wait for drain rather than shed —
        an operator's relief config must always get through).  The
        full-path re-check extends that to every PRIORITY envelope
        (lifecycle, orderer txs): "always admitted" must hold at the
        queue too, not only at the gate — the classify parse runs
        only on the Full path, never on the fast path."""
        if not self._bounded:
            self._q.put(msg)
            return
        if is_config:
            self._put_priority(msg)
            return
        try:
            self._q.put_nowait(msg)
        except queue.Full:
            if admission.is_priority(msg.env):
                self._put_priority(msg)
                return
            raise admission.shed(
                "queue_full",
                f"submit queue full ({self._q.maxsize})",
                retry_after_s=min(5.0, self._support.batch_timeout_s()),
            ) from None

    def _put_priority(self, msg: _Msg) -> None:
        """Bounded-mode blocking put for priority traffic, in slices
        that re-check _halted: priority waits for drain rather than
        shed, but a halted chain must answer typed instead of wedging
        the broadcast handler thread forever."""
        while True:
            if self._halted.is_set():
                raise ChainHaltedError("chain is halted")
            try:
                self._q.put(msg, timeout=0.25)
                return
            except queue.Full:
                continue

    # -- the ordering loop ----------------------------------------------
    def _run(self) -> None:
        support = self._support
        timer_deadline: Optional[float] = None
        import time
        while not self._halted.is_set():
            timeout = None
            if timer_deadline is not None:
                timeout = max(0.0, timer_deadline - time.monotonic())
            try:
                msg = self._q.get(timeout=timeout)
            except queue.Empty:
                # batch timer fired (reference: case <-timer)
                timer_deadline = None
                batch = support.cutter.cut()
                if batch:
                    block = support.writer.create_next_block(batch)
                    support.writer.write_block(block)
                continue
            if msg is None:
                break
            if msg.is_config:
                # config messages cut pending and ride alone
                # (reference: solo consensus.go config branch)
                if msg.config_seq < support.sequence():
                    # stale validation: reprocess under current config
                    try:
                        msg = _Msg(*support.reprocess_config(msg.env))
                    except Exception:
                        continue          # rejected under new config
                batch = support.cutter.cut()
                if batch:
                    block = support.writer.create_next_block(batch)
                    support.writer.write_block(block)
                    timer_deadline = None
                block = support.writer.create_next_block([msg.env])
                support.process_config(msg.env, block)
                continue
            if msg.config_seq < support.sequence():
                try:
                    support.revalidate_normal(msg.env)
                except Exception:
                    continue              # rejected under new config
            batches, pending = support.cutter.ordered(msg.env)
            for batch in batches:
                block = support.writer.create_next_block(batch)
                support.writer.write_block(block)
            if batches:
                timer_deadline = None
            if pending and timer_deadline is None:
                timer_deadline = (time.monotonic()
                                  + support.batch_timeout_s())
        # drain-free halt: pending messages are dropped like the
        # reference's Halt (clients resubmit after failover)
