"""Docs cross-checks + the generated knob table.

The README "Knob registry" table is GENERATED from utils/knobs.py
(``python -m fabric_mod_tpu.analysis --knob-table``) between the
``<!-- fmtlint:knob-table -->`` markers; :func:`check_readme` fails
the lint run when either direction drifts — a declared knob missing
from the README, or a knob-shaped name in the README that no registry
entry backs.
"""
from __future__ import annotations

import re
from typing import List

from fabric_mod_tpu.analysis.engine import REPO_DIR, Finding
from fabric_mod_tpu.utils import knobs

TABLE_BEGIN = "<!-- fmtlint:knob-table -->"
TABLE_END = "<!-- /fmtlint:knob-table -->"

# tokens in prose/tables; trailing [A-Z0-9] so "FMT_SOAK_*" yields the
# checkable prefix "FMT_SOAK" rather than "FMT_SOAK_"
_TOKEN_RE = re.compile(r"(?:FABRIC_MOD_TPU|FMT)(?:_[A-Z0-9]+)*")


def knob_table_markdown() -> str:
    rows = ["| knob | type | default | doc |",
            "|---|---|---|---|"]
    for k in knobs.knob_table():
        default = "unset" if k.default is None else f"`{k.default}`"
        rows.append(f"| `{k.name}` | {k.type} | {default} | {k.doc} |")
    return "\n".join(rows)


def render_readme_section() -> str:
    return f"{TABLE_BEGIN}\n{knob_table_markdown()}\n{TABLE_END}"


def check_readme(readme_text: str = None) -> List[Finding]:
    path = REPO_DIR / "README.md"
    if readme_text is None:
        if not path.exists():
            return []
        readme_text = path.read_text()
    declared = set(knobs.declared())
    findings: List[Finding] = []
    tokens = set(_TOKEN_RE.findall(readme_text))
    for tok in sorted(tokens):
        if tok in declared:
            continue
        # a prefix form like FMT_SOAK (from "FMT_SOAK_*") is fine when
        # declared knobs live under it
        if any(d.startswith(tok + "_") for d in declared):
            continue
        findings.append(Finding(
            "README.md", 1, "knobs",
            f"README names knob-shaped {tok!r} that no "
            f"utils/knobs.py entry declares"))
    for name in sorted(declared - tokens):
        findings.append(Finding(
            "README.md", 1, "knobs",
            f"declared knob {name!r} is missing from the README "
            f"(regenerate: python -m fabric_mod_tpu.analysis "
            f"--knob-table)"))
    if TABLE_BEGIN in readme_text:
        inner = readme_text.split(TABLE_BEGIN, 1)[1]
        if TABLE_END not in inner:
            findings.append(Finding(
                "README.md", 1, "knobs",
                f"unterminated {TABLE_BEGIN} section"))
        elif inner.split(TABLE_END, 1)[0].strip() != \
                knob_table_markdown().strip():
            findings.append(Finding(
                "README.md", 1, "knobs",
                "generated knob table is stale — regenerate with "
                "python -m fabric_mod_tpu.analysis --knob-table"))
    return findings
