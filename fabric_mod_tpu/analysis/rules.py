"""The fmtlint rule catalog.

Each rule is the static mirror of a runtime discipline this framework
already enforces dynamically — the rule text names the sanctioned
primitive, so a finding is an instruction, not a style opinion.

Scoping convention: rules apply to the whole package unless noted.
``concurrency/`` is exempt from the thread/lock rules (it IS the
sanctioned layer), ``faults/`` from the fault-point rule, and the
tracing module from the span rule, for the same reason.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from fabric_mod_tpu.analysis.engine import (KNOB_RE, Finding, ModuleInfo,
                                            ProjectContext)


def _aliases(tree: ast.AST) -> Dict[str, Set[str]]:
    """module name -> local alias set, plus from-imported names under
    the pseudo-module key "from:<module>"."""
    out: Dict[str, Set[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out.setdefault(a.name, set()).add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out.setdefault(f"from:{node.module}", set()).add(
                    a.asname or a.name)
    return out


def _is_module_attr(node: ast.expr, modnames: Set[str],
                    attr: str) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == attr
            and isinstance(node.value, ast.Name)
            and node.value.id in modnames)


def _str_const(node: ast.expr):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class Rule:
    name: str = ""
    doc: str = ""

    def check(self, mod: ModuleInfo,
              ctx: ProjectContext) -> Iterable[Finding]:
        raise NotImplementedError

    def _f(self, mod: ModuleInfo, node: ast.AST, msg: str) -> Finding:
        return Finding(mod.relpath, getattr(node, "lineno", 1),
                       self.name, msg)


class KnobRule(Rule):
    name = "knobs"
    doc = ("every FABRIC_MOD_TPU_*/FMT_* access goes through the typed "
           "utils/knobs.py registry: raw os.environ reads of a knob, "
           "env_int/env_float calls outside utils/, and undeclared "
           "knob-name literals are errors")

    EXEMPT = {"utils/env.py", "utils/knobs.py"}
    RAW_HELPERS = {"env_int", "env_float", "_env_int", "_env_float"}

    def check(self, mod, ctx):
        if mod.pkgpath in self.EXEMPT:
            return
        from fabric_mod_tpu.utils import knobs
        al = _aliases(mod.tree)
        os_names = al.get("os", set())
        environ_names = al.get("from:os", set()) & {"environ"}
        getenv_names = al.get("from:os", set()) & {"getenv"}
        helper_names = (al.get("from:fabric_mod_tpu.utils.env", set())
                        | self.RAW_HELPERS)

        def is_environ(node: ast.expr) -> bool:
            return (_is_module_attr(node, os_names, "environ")
                    or (isinstance(node, ast.Name)
                        and node.id in environ_names))

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                if ((isinstance(fn, ast.Attribute)
                        and fn.attr in ("get", "pop", "setdefault")
                        and is_environ(fn.value))
                        or _is_module_attr(fn, os_names, "getenv")
                        or (isinstance(fn, ast.Name)
                            and fn.id in getenv_names)) and node.args:
                    key = _str_const(node.args[0])
                    if key is not None and KNOB_RE.match(key):
                        yield self._f(
                            mod, node,
                            f"raw os.environ read of knob {key!r} — "
                            f"use fabric_mod_tpu.utils.knobs.get_*")
                elif (isinstance(fn, ast.Name)
                        and fn.id in helper_names
                        and fn.id.lstrip("_").startswith("env_")):
                    yield self._f(
                        mod, node,
                        f"{fn.id}() outside utils/ — knob parsing goes "
                        f"through utils/knobs.py (get_int/get_float)")
                elif (isinstance(fn, ast.Attribute)
                        and fn.attr in ("env_int", "env_float")):
                    yield self._f(
                        mod, node,
                        f"{fn.attr}() outside utils/ — knob parsing goes "
                        f"through utils/knobs.py (get_int/get_float)")
            elif isinstance(node, ast.Subscript) and \
                    is_environ(node.value):
                key = _str_const(node.slice)
                if key is not None and KNOB_RE.match(key):
                    yield self._f(
                        mod, node,
                        f"raw os.environ[{key!r}] — use "
                        f"fabric_mod_tpu.utils.knobs")
            elif isinstance(node, ast.Constant):
                val = node.value
                if (isinstance(val, str) and KNOB_RE.match(val)
                        and not knobs.is_declared(val)):
                    yield self._f(
                        mod, node,
                        f"undeclared knob {val!r}: declare it in "
                        f"utils/knobs.py (name/type/default/doc)")


class FaultPointRule(Rule):
    name = "fault-points"
    doc = ("faults.point(...) takes a string LITERAL declared in "
           "faults/points.py — enables arm-time validation of "
           "FMT_FAULTS plans; declared-but-unused points are flagged "
           "on whole-package runs")

    def check(self, mod, ctx):
        if mod.pkgpath.startswith("faults/"):
            return
        from fabric_mod_tpu.faults import points
        al = _aliases(mod.tree)
        faults_names = (al.get("fabric_mod_tpu.faults", set())
                        | (al.get("from:fabric_mod_tpu", set())
                           & {"faults"}) | {"faults"})
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not _is_module_attr(node.func, faults_names, "point"):
                continue
            name = _str_const(node.args[0]) if node.args else None
            if name is None:
                yield self._f(
                    mod, node,
                    "faults.point() with a non-literal name defeats "
                    "the registry — pass a declared literal")
                continue
            ctx.fault_points_used.add(name)
            if not points.is_declared(name):
                yield self._f(
                    mod, node,
                    f"fault point {name!r} not declared in "
                    f"faults/points.py")


class SpanNameRule(Rule):
    name = "span-names"
    doc = ("tracing.span(...) takes a string LITERAL declared in "
           "observability/spannames.py — span names key the timeline "
           "sub-stages, metrics, and the Perfetto export; "
           "declared-but-unused names are flagged on whole-package "
           "runs")

    EXEMPT = {"observability/tracing.py", "observability/spannames.py"}

    def check(self, mod, ctx):
        if mod.pkgpath in self.EXEMPT:
            return
        from fabric_mod_tpu.observability import spannames
        al = _aliases(mod.tree)
        tracing_names = (al.get("fabric_mod_tpu.observability.tracing",
                                set())
                         | (al.get("from:fabric_mod_tpu.observability",
                                   set()) & {"tracing"}) | {"tracing"})
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not _is_module_attr(node.func, tracing_names, "span"):
                continue
            name = _str_const(node.args[0]) if node.args else None
            if name is None:
                yield self._f(
                    mod, node,
                    "tracing.span() with a non-literal name falls out "
                    "of every timeline/metric view — pass a declared "
                    "literal")
                continue
            ctx.span_names_used.add(name)
            if not spannames.is_declared(name):
                yield self._f(
                    mod, node,
                    f"span name {name!r} not declared in "
                    f"observability/spannames.py")


class ThreadRule(Rule):
    name = "threads"
    doc = ("no bare threading.Thread/Timer in production code — use "
           "concurrency.RegisteredThread so the leak-checked teardown "
           "sweep sees every worker")

    def check(self, mod, ctx):
        if mod.pkgpath.startswith("concurrency/"):
            return
        al = _aliases(mod.tree)
        thr = al.get("threading", set())
        from_thr = al.get("from:threading", set())
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            bare = None
            for cls in ("Thread", "Timer"):
                if _is_module_attr(fn, thr, cls) or (
                        isinstance(fn, ast.Name) and fn.id == cls
                        and cls in from_thr):
                    bare = cls
            if bare:
                yield self._f(
                    mod, node,
                    f"bare threading.{bare} — use "
                    f"concurrency.RegisteredThread (leak-checked, "
                    f"named, swept at teardown)")


class LockRule(Rule):
    name = "locks"
    doc = ("no bare threading.Lock()/RLock() in production code — use "
           "concurrency.OrderedLock (ranked hierarchy) or "
           "RegisteredLock (dynamic cycle detection), or pragma with "
           "the reason ordering cannot apply")

    def check(self, mod, ctx):
        if mod.pkgpath.startswith("concurrency/"):
            return
        al = _aliases(mod.tree)
        thr = al.get("threading", set())
        from_thr = al.get("from:threading", set())
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            for cls in ("Lock", "RLock"):
                if _is_module_attr(fn, thr, cls) or (
                        isinstance(fn, ast.Name) and fn.id == cls
                        and cls in from_thr):
                    yield self._f(
                        mod, node,
                        f"bare threading.{cls}() — use "
                        f"concurrency.OrderedLock/RegisteredLock so "
                        f"lock-order cycles are caught at acquire "
                        f"time")


class ClockRule(Rule):
    name = "clocks"
    doc = ("no time.time()/time.sleep() calls inside subsystems that "
           "already have injectable clocks (retry, admission, "
           "tracing, discovery, deliver failover, soak, fakeclock) — "
           "route through the injected clock.  time.monotonic() is "
           "exempt: measuring a real duration is not scheduling")

    SCOPED = {"utils/retry.py", "utils/fakeclock.py",
              "orderer/admission.py", "observability/tracing.py",
              "gossip/discovery.py", "peer/blocksprovider.py"}
    SCOPED_PREFIXES = ("soak/",)

    def _in_scope(self, pkgpath: str) -> bool:
        return (pkgpath in self.SCOPED
                or pkgpath.startswith(self.SCOPED_PREFIXES))

    def check(self, mod, ctx):
        if not self._in_scope(mod.pkgpath):
            return
        al = _aliases(mod.tree)
        time_names = al.get("time", set())
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            for fn_name in ("time", "sleep"):
                if _is_module_attr(node.func, time_names, fn_name):
                    yield self._f(
                        mod, node,
                        f"time.{fn_name}() in a clocked subsystem — "
                        f"use the injectable clock (or pragma why "
                        f"real OS time is required here)")


class SwallowRule(Rule):
    name = "swallowed-exceptions"
    doc = ("`except Exception: pass` (or bare except) with no "
           "log/metric/re-raise swallows failures invisibly — log it, "
           "count it, or pragma why silence is the contract")

    _BROAD = {"Exception", "BaseException"}

    def check(self, mod, ctx):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or (
                isinstance(node.type, ast.Name)
                and node.type.id in self._BROAD)
            if not broad:
                continue
            if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
                yield self._f(
                    mod, node,
                    "swallowed exception: except "
                    f"{'Exception' if node.type is not None else ''}"
                    ": pass with no log/metric/re-raise")


class JaxHotPathRule(Rule):
    name = "jax-hot-path"
    doc = ("host syncs (.item(), np.asarray/np.array of a "
           "freshly-computed value, jax.device_get, "
           "block_until_ready) flagged inside the device-dispatch "
           "files (bccsp/tpu.py, ops/*, parallel/*) — a sync inside "
           "the dispatch path serializes the pipeline; pragma the "
           "sanctioned resolve seams")

    SCOPED = {"bccsp/tpu.py"}
    SCOPED_PREFIXES = ("ops/", "parallel/")

    def _in_scope(self, pkgpath: str) -> bool:
        return (pkgpath in self.SCOPED
                or pkgpath.startswith(self.SCOPED_PREFIXES))

    def check(self, mod, ctx):
        if not self._in_scope(mod.pkgpath):
            return
        al = _aliases(mod.tree)
        np_names = (al.get("numpy", set()) | {"np"})
        jax_names = al.get("jax", set())
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "item" \
                    and not node.args:
                yield self._f(
                    mod, node,
                    ".item() is a device->host sync — keep verdicts "
                    "on device or pragma the resolve seam")
            elif (isinstance(fn, ast.Attribute)
                    and fn.attr in ("asarray", "array")
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in np_names
                    and node.args
                    and isinstance(node.args[0], ast.Call)):
                yield self._f(
                    mod, node,
                    f"np.{fn.attr}(<call>) syncs a freshly-computed "
                    f"device value to host — pragma if this is a "
                    f"sanctioned resolve/fallback seam")
            elif _is_module_attr(fn, jax_names, "device_get"):
                yield self._f(
                    mod, node,
                    "jax.device_get is a host sync — pragma the "
                    "sanctioned resolve seam")
            elif isinstance(fn, ast.Attribute) and \
                    fn.attr == "block_until_ready":
                yield self._f(
                    mod, node,
                    "block_until_ready() stalls dispatch — pragma if "
                    "this is a bench/trace seam")


ALL_RULES: List[Rule] = [
    KnobRule(), FaultPointRule(), SpanNameRule(), ThreadRule(),
    LockRule(), ClockRule(), SwallowRule(), JaxHotPathRule(),
]


class PragmaRuleDoc(Rule):
    """Placeholder for --list-rules: pragma findings are emitted by the
    engine's pragma parser, not an AST visitor."""
    name = "pragma"
    doc = ("fmtlint pragmas must be well-formed: "
           "'fmtlint: allow[rule] -- reason' (as a comment) with a "
           "known rule name and a non-empty reason")

    def check(self, mod, ctx):
        return ()


LISTED_RULES: List[Rule] = ALL_RULES + [PragmaRuleDoc()]


def project_checks(ctx: ProjectContext) -> List[Finding]:
    """Whole-tree cross-checks: a registry entry nothing references is
    dead documentation — drift in the other direction."""
    from fabric_mod_tpu.faults import points
    from fabric_mod_tpu.observability import spannames
    findings: List[Finding] = []
    for name in sorted(points.DECLARED_POINTS - ctx.fault_points_used):
        findings.append(Finding(
            "fabric_mod_tpu/faults/points.py", 1, "fault-points",
            f"declared fault point {name!r} has no faults.point() "
            f"seam in production code"))
    for name in sorted(spannames.DECLARED_SPANS - ctx.span_names_used):
        findings.append(Finding(
            "fabric_mod_tpu/observability/spannames.py", 1,
            "span-names",
            f"declared span {name!r} has no tracing.span() call in "
            f"production code"))
    return findings
