"""fmtlint: project-native static analysis over the package tree.

``python -m fabric_mod_tpu.analysis`` lints the whole package (also
run as a tier-1 test); ``--list-rules`` documents every rule and the
pragma syntax.  See engine.py for the pragma grammar and rules.py for
the catalog.
"""
from fabric_mod_tpu.analysis.engine import (Finding, ModuleInfo,
                                            RunResult, load_module,
                                            run)
from fabric_mod_tpu.analysis.rules import ALL_RULES, LISTED_RULES

__all__ = ["Finding", "ModuleInfo", "RunResult", "load_module", "run",
           "ALL_RULES", "LISTED_RULES"]
