"""fmtlint: the AST rule engine.

(reference: the role ``go vet`` + custom analyzers play in the Go
stack — project-specific invariants enforced at compile time.  Our
runtime disciplines (FMT_RACECHECK guards, fault seams, spans, the
knob registry, injectable clocks) each have a *dynamic* half already;
this engine is the *static* half: the discipline is checked on every
change, over the whole tree, without a reviewer re-deriving it.)

A run parses every production module once, hands the tree to each
registered rule, collects :class:`Finding` objects, and filters them
through per-line pragmas::

    some_violating_line()   # fmtlint: allow[locks] -- why it's OK here

The pragma REQUIRES a reason (`` -- text``); a reasonless or
unknown-rule pragma is itself a finding (rule ``pragma``), so
suppressions stay reviewable.  A pragma may sit on the violating line
or on the line directly above it (for lines that would overflow).

Rules are checked per module; rules that need whole-tree knowledge
(declared-but-unused fault points / span names, the README knob-table
drift check) run as *project checks* after the per-module pass, when
the run covers the whole package.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

PKG_DIR = Path(__file__).resolve().parent.parent        # fabric_mod_tpu/
REPO_DIR = PKG_DIR.parent

# one pragma grammar (as a comment): fmtlint: allow[rules...] -- reason
PRAGMA_RE = re.compile(
    r"#\s*fmtlint:\s*allow\[([^\]]*)\]\s*(?:--\s*(\S.*))?")
_PRAGMA_MARK = re.compile(r"#\s*fmtlint\b")

KNOB_RE = re.compile(r"^(?:FABRIC_MOD_TPU|FMT)_[A-Z0-9_]+$")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str                  # repo-relative
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass
class ModuleInfo:
    """One parsed production module plus its pragma map."""
    path: Path
    relpath: str               # repo-relative, posix separators
    pkgpath: str               # relative to fabric_mod_tpu/, posix
    tree: ast.AST
    lines: List[str]
    # line -> set of rule names allowed there ("*" = all)
    pragmas: Dict[int, Set[str]]
    pragma_findings: List[Finding]


def _parse_pragmas(relpath: str, lines: Sequence[str],
                   known_rules: Set[str]
                   ) -> Tuple[Dict[int, Set[str]], List[Finding]]:
    pragmas: Dict[int, Set[str]] = {}
    findings: List[Finding] = []
    for lineno, text in enumerate(lines, start=1):
        if not _PRAGMA_MARK.search(text):
            continue
        m = PRAGMA_RE.search(text)
        if m is None:
            findings.append(Finding(
                relpath, lineno, "pragma",
                "malformed fmtlint pragma: expected a comment "
                "'fmtlint: allow[<rule>] -- <reason>'"))
            continue
        rules_raw, reason = m.group(1), m.group(2)
        names = {r.strip() for r in rules_raw.split(",") if r.strip()}
        if not names:
            findings.append(Finding(
                relpath, lineno, "pragma",
                "fmtlint pragma allows no rules"))
            continue
        unknown = sorted(n for n in names if n not in known_rules)
        if unknown:
            findings.append(Finding(
                relpath, lineno, "pragma",
                f"fmtlint pragma names unknown rule(s) {unknown} "
                f"(see --list-rules)"))
        if not reason:
            findings.append(Finding(
                relpath, lineno, "pragma",
                "fmtlint pragma without a reason: append "
                "'-- <why this is sanctioned here>'"))
            continue
        # a pragma covers its own line and, when it stands alone on a
        # comment line, the line below it
        pragmas.setdefault(lineno, set()).update(names)
        if text.lstrip().startswith("#"):
            pragmas.setdefault(lineno + 1, set()).update(names)
    return pragmas, findings


def load_module(path: Path, known_rules: Set[str]) -> ModuleInfo:
    src = path.read_text()
    try:
        rel = path.resolve().relative_to(REPO_DIR).as_posix()
    except ValueError:
        rel = str(path)
    try:
        pkg = path.resolve().relative_to(PKG_DIR).as_posix()
    except ValueError:
        pkg = rel
    lines = src.splitlines()
    pragmas, pragma_findings = _parse_pragmas(rel, lines, known_rules)
    return ModuleInfo(path=path, relpath=rel, pkgpath=pkg,
                      tree=ast.parse(src, filename=str(path)),
                      lines=lines, pragmas=pragmas,
                      pragma_findings=pragma_findings)


class ProjectContext:
    """Cross-module accumulator the rules feed during the per-module
    pass; the project checks read it afterwards."""

    def __init__(self, full_package: bool):
        self.full_package = full_package
        self.fault_points_used: Set[str] = set()
        self.span_names_used: Set[str] = set()


def discover(root: Path) -> List[Path]:
    """Production modules under `root` (tests and bench live outside
    the package and are intentionally out of scope — synthetic knob
    names, fault points, and raw threads are legitimate there)."""
    return sorted(p for p in root.rglob("*.py") if "__pycache__" not in p.parts)


@dataclasses.dataclass
class RunResult:
    findings: List[Finding]
    suppressed: int
    files: int

    @property
    def ok(self) -> bool:
        return not self.findings


def check_module(mod: ModuleInfo, active: Sequence,
                 ctx: ProjectContext
                 ) -> Tuple[List[Finding], int]:
    """Run `active` rules over one parsed module and filter through
    its pragmas.  Returns (findings, suppressed-count).  This is the
    exact per-module path :func:`run` takes — the fixture tests in
    tests/test_analysis.py call it directly so suppressed fixtures
    exercise the same pragma filter as the tree gate."""
    raw: List[Finding] = list(mod.pragma_findings)
    for rule in active:
        raw.extend(rule.check(mod, ctx))
    findings: List[Finding] = []
    suppressed = 0
    for f in raw:
        allowed = mod.pragmas.get(f.line, ())
        if f.rule != "pragma" and (f.rule in allowed or "*" in allowed):
            suppressed += 1
        else:
            findings.append(f)
    return findings, suppressed


def run(paths: Optional[Sequence[Path]] = None,
        rules: Optional[Sequence] = None,
        docs_check: bool = True) -> RunResult:
    """Lint `paths` (default: the whole package).  Project checks and
    the README drift check only run on whole-package runs — partial
    runs cannot judge declared-but-unused registries."""
    from fabric_mod_tpu.analysis.rules import ALL_RULES, project_checks
    active = list(rules) if rules is not None else list(ALL_RULES)
    known = {r.name for r in ALL_RULES} | {"pragma"}
    full = paths is None
    files = discover(PKG_DIR) if full else [Path(p) for p in paths]

    ctx = ProjectContext(full_package=full)
    findings: List[Finding] = []
    suppressed = 0
    for path in files:
        mod = load_module(path, known)
        mod_findings, mod_suppressed = check_module(mod, active, ctx)
        findings.extend(mod_findings)
        suppressed += mod_suppressed
    if full:
        findings.extend(project_checks(ctx))
        if docs_check:
            from fabric_mod_tpu.analysis.docs import check_readme
            findings.extend(check_readme())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return RunResult(findings=findings, suppressed=suppressed,
                     files=len(files))
