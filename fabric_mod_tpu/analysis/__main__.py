"""CLI: ``python -m fabric_mod_tpu.analysis``.

Exit 0 = clean tree, 1 = findings, 2 = usage error.  The whole-package
run (no paths) additionally runs the project checks (unused registry
entries) and the README knob-table drift check — exactly what the
tier-1 gate in tests/test_analysis.py asserts.
"""
from __future__ import annotations

import argparse
import sys

from fabric_mod_tpu.analysis.engine import run
from fabric_mod_tpu.analysis.rules import LISTED_RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fabric_mod_tpu.analysis",
        description="fmtlint: project-native static analysis — the "
                    "repo's runtime disciplines as compile-time gates")
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: the whole package, "
                         "plus registry + README cross-checks)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every rule + the pragma syntax and exit")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the README knob table generated from "
                         "utils/knobs.py and exit")
    ap.add_argument("--no-docs-check", action="store_true",
                    help="skip the README drift check on whole-package "
                         "runs")
    args = ap.parse_args(argv)

    if args.list_rules:
        print("fmtlint rules (suppress per line with a comment "
              "'fmtlint: allow[<rule>] -- <reason>'):\n")
        for rule in LISTED_RULES:
            print(f"  {rule.name}")
            for line in rule.doc.splitlines():
                print(f"      {line}")
        return 0
    if args.knob_table:
        from fabric_mod_tpu.analysis.docs import render_readme_section
        print(render_readme_section())
        return 0

    result = run(paths=args.paths or None,
                 docs_check=not args.no_docs_check)
    for f in result.findings:
        print(f.render())
    print(f"fmtlint: {len(result.findings)} finding(s), "
          f"{result.suppressed} suppressed by pragma, "
          f"{result.files} file(s)", file=sys.stderr)
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
