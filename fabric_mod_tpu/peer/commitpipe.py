"""Pipelined block-commit engine: overlap stage(N+1) with finish+commit(N).

(reference: the serial StoreBlock composition of
gossip/state/state.go:817 — validate -> MVCC -> commit, one block at a
time — restructured the way FastFabric (Gorenflo et al., 2019) and
StreamChain (Istvan et al., 2018) pipeline Fabric's commit path.)

The validator already split the block hot path into `stage` (host
unpack + policy compilation + device batch DISPATCH, no await) and
`finish` (await verdicts + sequential flag resolution) —
peer/txvalidator.py.  This module runs that seam as a bounded
pipeline over an in-order block stream:

  caller       submit(block)   -> bounded in-queue (backpressure)
  stage loop   stage(N+1): host unpack + device dispatch, CONCURRENT
               with ...
  commit loop  finish(N): await verdicts, resolve flags; then
               kvledger.commit_block(N): MVCC + block store + state

`depth` bounds how many blocks may be staged-but-uncommitted at once;
depth=1 is bit-identical to the synchronous Committer (stage(N+1)
cannot start until commit(N) finished).  Whenever a staged block sets
`StagedBlock.needs_barrier` (config txs, VALIDATION_PARAMETER writes,
lifecycle-namespace writes — state that pass-1 staging READS), the
stage loop drains to a strict barrier: the next block stages only
after the barrier block's commit lands, so staged reads never race
committed state.  Everything else about verdict order is already
safe ahead-of-commit: duplicate-txid and key-level override
resolution run in `finish`, strictly in block order.

Knob: FABRIC_MOD_TPU_COMMIT_PIPELINE=<depth> (0/unset: disabled, the
synchronous path everywhere; >=1: consumers route commits through a
shared PipelinedCommitter of that depth).  The deliver client always
pipelines (its double buffer predates this engine) and uses the knob
only to override its default depth of 2.

Every stage is instrumented (MetricsProvider -> opsserver /metrics):
  fabric_commitpipe_stage_seconds    host unpack + dispatch per block
  fabric_commitpipe_await_seconds    device-verdict wait per block
  fabric_commitpipe_commit_seconds   MVCC + ledger commit per block
                                     (the ledger's own histograms
                                     split mvcc/store/state within)
  fabric_commitpipe_occupancy        staged-but-uncommitted blocks
  fabric_commitpipe_barriers_total   barrier drains taken
  fabric_commitpipe_blocks_total     blocks committed via a pipeline
"""
from __future__ import annotations

import functools
import itertools
import threading
import time
from typing import Callable, List, Optional

from fabric_mod_tpu import faults
from fabric_mod_tpu.concurrency import (GuardedQueue, OwnedState,
                                        RegisteredLock,
                                        RegisteredThread, assert_joined)
from fabric_mod_tpu.observability import tracing
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.observability.opsserver import default_health
from fabric_mod_tpu.utils import knobs
from fabric_mod_tpu.observability.logging import get_logger

log = get_logger("peer.commitpipe")

_STAGE_OPTS = MetricOpts(
    "fabric", "commitpipe", "stage_seconds",
    help="Host unpack + policy compilation + device dispatch time per "
         "block (the pipeline's front stage).")
_AWAIT_OPTS = MetricOpts(
    "fabric", "commitpipe", "await_seconds",
    help="Device-verdict wait per block (overlapped with the next "
         "block's staging when depth > 1).")
_COMMIT_OPTS = MetricOpts(
    "fabric", "commitpipe", "commit_seconds",
    help="Flag resolution + MVCC + ledger commit time per block.")
_OCCUPANCY_OPTS = MetricOpts(
    "fabric", "commitpipe", "occupancy",
    help="Blocks staged but not yet committed (pipeline fill; bounded "
         "by the configured depth).  Labeled per consumer: multiple "
         "live engines (a deliver client's private pipe + a channel's "
         "shared one) must not overwrite each other's fill level.",
    label_names=("consumer",))
_BARRIER_OPTS = MetricOpts(
    "fabric", "commitpipe", "barriers_total",
    help="Barrier drains: blocks whose config/VALIDATION_PARAMETER/"
         "lifecycle writes forced the next stage to wait for commit.")
_BLOCKS_OPTS = MetricOpts(
    "fabric", "commitpipe", "blocks_total",
    help="Blocks committed through a pipelined committer.")


@functools.lru_cache(maxsize=None)
def _metrics():
    prov = default_provider()
    return (prov.histogram(_STAGE_OPTS),
            prov.histogram(_AWAIT_OPTS),
            prov.histogram(_COMMIT_OPTS),
            prov.gauge(_OCCUPANCY_OPTS),
            prov.counter(_BARRIER_OPTS),
            prov.counter(_BLOCKS_OPTS))


# per-instance health-registry key suffix (consumer labels repeat)
_pipe_seq = itertools.count()


def pipeline_depth(default: int = 0) -> int:
    """The FABRIC_MOD_TPU_COMMIT_PIPELINE knob: pipeline depth, 0 (or
    unset/garbage) = disabled, i.e. the synchronous commit path."""
    return max(0, knobs.get_int("FABRIC_MOD_TPU_COMMIT_PIPELINE",
                                default))


class ValidatorCommitTarget:
    """The minimal channel-shaped commit target: one TxValidator bound
    to one ledger.  PipelinedCommitter only needs `stage_block`,
    `commit_staged` and `.ledger` — peer.Channel provides them in
    production; this adapter serves the bench and tests where no
    channel config machinery exists."""

    def __init__(self, validator, ledger):
        self.validator = validator
        self.ledger = ledger

    def stage_block(self, block):
        return self.validator.stage(block)

    def commit_staged(self, staged) -> List[int]:
        flags = staged.validator.finish(staged)
        return self.ledger.commit_block(
            staged.block, flags,
            rwsets=getattr(staged, "rwsets", None))


class PipelinedCommitter:
    """Bounded commit pipeline over an in-order block stream.

    `submit(block)` enqueues for staging and returns (backpressure via
    the bounded in-queue); blocks commit strictly in submission order
    on the commit loop.  `store_block` is the synchronous facade (used
    by the drop-in Committer seam): submit + wait for that block's
    commit, returning its final flags.  Threads start lazily on first
    submit and are daemons; `close()` drains and joins them.
    """

    def __init__(self, channel, depth: Optional[int] = None,
                 in_queue: int = 8,
                 on_commit: Optional[Callable] = None,
                 on_error: Optional[Callable] = None,
                 consumer: str = "adhoc"):
        """`channel`: stage_block/commit_staged/.ledger (peer.Channel
        or ValidatorCommitTarget).  `depth`: max staged-but-uncommitted
        blocks (None -> the env knob, floor 1).  `on_commit(block,
        flags)` fires after each commit, `on_error(exc)` once on the
        first failure.  `consumer` labels the occupancy gauge (keep
        the set small: "deliver", "channel", "adhoc")."""
        if depth is None:
            depth = pipeline_depth(2)
        self._channel = channel
        self.depth = max(1, depth)
        # in-queue: many producers (submit callers + close sentinel),
        # one consumer (the stage loop); staged queue: strict SPSC
        # stage -> commit.  Ownership is machine-checked under
        # FMT_RACECHECK.
        self._in_q: "GuardedQueue" = GuardedQueue(
            max(1, in_queue), name=f"commitpipe-in[{consumer}]")
        self._staged_q: "GuardedQueue" = GuardedQueue(
            name=f"commitpipe-staged[{consumer}]", single_producer=True)
        self._on_commit = on_commit
        self._on_error = on_error
        # one condition variable guards all pipeline state: inflight
        # count (the depth bound), committed height (barrier + flush
        # waits), the sticky first error.  Registry-fed lock: the cv
        # nests inside the submit lock and around the ledger's ranked
        # OrderedLock — inversions are cycles the registry reports.
        self._cv = threading.Condition(
            RegisteredLock(f"commitpipe-cv[{consumer}]"))
        self._inflight = 0
        self._height = channel.ledger.height
        self._barrier_height: Optional[int] = None
        self._last_submitted: Optional[int] = None
        self._err: Optional[Exception] = None
        self._closed = False
        self._started = False
        self._start_lock = RegisteredLock(
            f"commitpipe-start[{consumer}]")
        # serializes producers through the in-queue put: without it,
        # two overlapping store_block callers could update
        # _last_submitted in order yet enqueue out of order
        self._submit_lock = RegisteredLock(
            f"commitpipe-submit[{consumer}]")
        self._threads: List[threading.Thread] = []
        # cumulative per-stage wall seconds (the e2e bench reads these
        # off the deliver client to show the verify/commit overlap).
        # Single-writer contract made machine-checked: the stage loop
        # owns stage timing, the commit loop owns await/commit timing;
        # reads (bench, deliver client) stay open.
        self._stage_state = OwnedState(
            f"commitpipe-stage[{consumer}]", secs=0.0)
        self._commit_state = OwnedState(
            f"commitpipe-commit[{consumer}]", await_secs=0.0,
            commit_secs=0.0)
        (self._m_stage, self._m_await, self._m_commit,
         occupancy, self._m_barriers, self._m_blocks) = _metrics()
        self._m_occupancy = occupancy.with_labels(consumer)
        self._consumer = consumer
        # real health: a poisoned (sticky-error, not yet discarded)
        # pipeline flips /healthz — the registry existed since the ops
        # server landed, this is the first commit-path registrant.
        # Keyed per INSTANCE (consumer labels repeat: every channel's
        # engine is consumer="channel" — a shared key would let the
        # newest registration mask another channel's poisoned pipe);
        # close() unregisters, so the registry tracks live pipes only.
        self._health_key = f"commitpipe[{consumer}#{next(_pipe_seq)}]"
        default_health().register(self._health_key, self._health_check)

    def _health_check(self) -> None:
        if self._err is not None and not self._closed:
            raise RuntimeError(
                f"commit pipeline [{self._consumer}] poisoned: "
                f"{self._err!r}")

    # -- timing surface (kept: bench/deliver-client read these) -----------
    @property
    def stage_secs(self) -> float:
        return self._stage_state.secs

    @property
    def await_secs(self) -> float:
        return self._commit_state.await_secs

    @property
    def commit_secs(self) -> float:
        return self._commit_state.commit_secs

    # -- lifecycle -------------------------------------------------------
    def _ensure_started(self) -> None:
        with self._start_lock:
            if self._started:
                return
            self._started = True
            for name, fn in (("commitpipe-stage", self._stage_loop),
                             ("commitpipe-commit", self._commit_loop)):
                t = RegisteredThread(target=fn, name=name,
                                     structure="PipelinedCommitter")
                t.start()
                self._threads.append(t)

    @property
    def error(self) -> Optional[Exception]:
        return self._err

    def _fail(self, e: Exception) -> None:
        with self._cv:
            if self._err is None:
                self._err = e
            self._cv.notify_all()
        if self._on_error is not None:
            try:
                self._on_error(e)
            except Exception as cb_err:
                log.debug("on_error callback raised: %r", cb_err)

    # -- producer side ---------------------------------------------------
    def submit(self, block) -> None:
        """Enqueue one block for pipelined commit.  Blocks only on the
        bounded in-queue (or a pending error).  Blocks MUST arrive in
        block-number order; a misordered submit (stale redelivery, or
        a racing producer's block arriving early) is rejected HERE
        with the ledger's own error type, to the offending caller
        only — never admitted to poison the shared pipeline with a
        commit-time out-of-order failure that would hit an unrelated
        later caller (the sync path's per-caller arbitration)."""
        with self._submit_lock:
            with self._cv:
                if self._err is not None:
                    raise self._err
                if self._closed:
                    # checked BEFORE starting workers: a closed
                    # never-started pipe must not spawn threads that
                    # nothing will ever send the shutdown sentinel to
                    raise RuntimeError("commit pipeline is closed")
                num = block.header.number
                # ledger-aware base: the chain may have advanced past
                # this pipe's construction snapshot (e.g. a deliver
                # client built early, gossip commits landing before
                # run()) — such in-order streams are not misordered
                base = max(self._height, self._channel.ledger.height)
                expected = (base if self._last_submitted is None
                            else max(base, self._last_submitted + 1))
                if num != expected:
                    from fabric_mod_tpu.ledger.kvledger import (
                        LedgerError)
                    raise LedgerError(
                        f"submit out of order: block {num}, pipeline "
                        f"expects {expected}")
                self._last_submitted = num
            self._ensure_started()
            self._in_q.put(block)

    def store_block(self, block) -> List[int]:
        """Synchronous facade: submit + wait for THIS block's commit;
        returns its final flags.  Pipelining still happens across
        concurrent/overlapping callers."""
        from fabric_mod_tpu.protos import protoutil
        num = block.header.number
        self.submit(block)
        self.wait_height(num + 1)
        return list(protoutil.block_txflags(block))

    def wait_height(self, height: int,
                    timeout_s: Optional[float] = None) -> bool:
        """Block until `height` blocks are committed (or the pipeline
        failed, re-raising its error)."""
        deadline = None if timeout_s is None else \
            time.monotonic() + timeout_s
        with self._cv:
            while self._height < height and self._err is None:
                left = None if deadline is None else \
                    deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._cv.wait(timeout=left if left is not None else 0.5)
            if self._height >= height:
                # truthfully report a reached height even if a LATER
                # block's failure set the sticky error meanwhile — the
                # waiter's own block is durably committed
                return True
            raise self._err

    def flush(self, timeout_s: Optional[float] = None) -> bool:
        """Wait until every submitted block is committed."""
        with self._cv:
            last = self._last_submitted
        if last is None:
            if self._err is not None:
                raise self._err
            return True
        return self.wait_height(last + 1, timeout_s)

    def close(self, timeout_s: Optional[float] = None) -> None:
        """Drain submitted work and join the workers.  The default
        (None) joins until drained — close() must not return with
        commits silently in flight (a cold XLA compile can hold the
        tail block for minutes); pass a bound only where abandoning
        the workers is acceptable (e.g. discarding a pipe that
        already failed).  A pending pipeline error stays readable on
        `.error` (callers that need to re-raise do so — the deliver
        client does)."""
        # taking the submit lock excludes a producer mid-submit, so
        # "started" is stable when read and the sentinel can't race a
        # block into a closed pipe
        with self._submit_lock:
            with self._cv:
                if self._closed:
                    return
                self._closed = True
            started = self._started
        # a closed (drained or discarded) engine leaves the health
        # registry: its sticky error was surfaced to its callers, and
        # keeping the entry would pin the whole channel/ledger graph
        # in the process-global registry forever
        default_health().unregister(self._health_key)
        if not started:
            return
        self._in_q.put(None)
        # leak-checked join: with FMT_RACECHECK armed, workers that
        # outlive the drain raise instead of parking as daemons.  The
        # commit loop may legally call close() via on_error/on_commit
        # callbacks — assert_joined skips the current thread.
        assert_joined(self._threads, owner="PipelinedCommitter",
                      timeout=timeout_s)

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def consumer(self) -> str:
        """The occupancy-gauge label this engine reports under.  The
        shard router labels per slice ("shard0", "shard1", ...), so
        /metrics shows each slice's pipeline fill separately — the
        placement-balance view next to the router's channels gauge."""
        return self._consumer

    # -- stage loop: host unpack + device dispatch -----------------------
    def _stage_loop(self) -> None:
        try:
            while True:
                block = self._in_q.get()
                if block is None:
                    return
                with self._cv:
                    # depth bound + barrier drain share the wait: stage
                    # only when a slot is free AND no barrier block is
                    # still committing
                    while self._err is None and (
                            self._inflight >= self.depth
                            or (self._barrier_height is not None
                                and self._height < self._barrier_height)):
                        self._cv.wait(timeout=0.5)
                    if self._err is not None:
                        continue           # drain mode (below)
                    self._inflight += 1
                    self._m_occupancy.set(self._inflight)
                t0 = time.perf_counter()
                # chaos seam: an engine crash while staging (the
                # sticky-error drain below is the recovery contract
                # under test — a poisoned pipe must fail its callers
                # and be rebuildable from the committed height)
                faults.point("commitpipe.stage")
                # one flight-recorder timeline per block: the stage
                # side's sub-spans (unpack, device_dispatch) land
                # here; StagedBlock carries it across the handoff and
                # the commit loop resumes it (None when FMT_TRACE is
                # unset — zero objects, zero writes)
                tl = tracing.start_timeline(self._consumer,
                                            block.header.number)
                with tracing.timeline_scope(tl):
                    staged = self._channel.stage_block(block)
                if tl is not None:
                    staged.trace_timeline = tl
                dt = time.perf_counter() - t0
                self._stage_state.secs += dt
                self._m_stage.observe(dt)
                if staged.needs_barrier:
                    with self._cv:
                        self._barrier_height = block.header.number + 1
                    self._m_barriers.add(1)
                self._staged_q.put(staged)
        except Exception as e:
            self._fail(e)
            # keep draining so a bounded-queue producer never deadlocks
            while self._in_q.get() is not None:
                pass
        finally:
            self._staged_q.put(None)

    # -- commit loop: await verdicts, resolve, MVCC + commit -------------
    def _commit_loop(self) -> None:
        while True:
            staged = self._staged_q.get()
            if staged is None:
                return
            tl = getattr(staged, "trace_timeline", None)
            try:
                # chaos seam: a crash between verdict await and ledger
                # write — the worst spot: the block is staged, its
                # device batch resolved, and NOTHING may have reached
                # the ledger (crash-resume must re-commit it exactly
                # once from the durable height)
                faults.point("commitpipe.commit")
                with tracing.timeline_scope(tl):
                    t0 = time.perf_counter()
                    staged.resolve_mask()  # the device-verdict wait
                    dt = time.perf_counter() - t0
                    self._commit_state.await_secs += dt
                    self._m_await.observe(dt)
                    t0 = time.perf_counter()
                    flags = self._channel.commit_staged(staged)
                    dt = time.perf_counter() - t0
                    self._commit_state.commit_secs += dt
                    self._m_commit.observe(dt)
            except Exception as e:
                self._fail(e)
                while self._staged_q.get() is not None:
                    pass
                return
            finally:
                tracing.finish_timeline(tl)
            with self._cv:
                self._inflight -= 1
                self._m_occupancy.set(self._inflight)
                self._height = staged.block.header.number + 1
                self._cv.notify_all()
            self._m_blocks.add(1)
            if self._on_commit is not None:
                try:
                    self._on_commit(staged.block, flags)
                except Exception as e:     # fan-out is advisory
                    log.debug("on_commit fan-out for block %d "
                              "raised: %r",
                              staged.block.header.number, e)
