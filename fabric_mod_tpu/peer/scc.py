"""System chaincodes: QSCC (ledger queries) + CSCC (channel config).

(reference: core/scc — qscc/query.go:228's
GetChainInfo/GetBlockByNumber/GetBlockByTxID/GetTransactionByID and
cscc/configure.go:305's GetConfigBlock/GetChannelConfig — in-process
chaincodes dispatched through the same registry as user contracts.)

Read-only: they run against the committed ledger through the stub's
channel binding, produce no writes, and their proposal responses are
not meant to be ordered (clients query, they don't submit).
"""
from __future__ import annotations

import json

from fabric_mod_tpu.peer.chaincode import ChaincodeError, ChaincodeStub
from fabric_mod_tpu.protos import protoutil


class QsccContract:
    """(reference: core/scc/qscc/query.go)"""

    def __init__(self, ledger):
        self._ledger = ledger

    def invoke(self, stub: ChaincodeStub) -> bytes:
        if not stub.args:
            raise ChaincodeError("no args")
        op = stub.args[0].decode()
        if op == "GetChainInfo":
            h = self._ledger.height
            tip = (self._ledger.get_block_by_number(h - 1)
                   if h else None)
            info = {
                "height": h,
                "currentBlockHash":
                    protoutil.block_header_hash(tip.header).hex()
                    if tip else "",
                "previousBlockHash":
                    tip.header.previous_hash.hex() if tip else "",
            }
            return json.dumps(info, sort_keys=True).encode()
        if op == "GetBlockByNumber":
            num = int(stub.args[1].decode())
            blk = self._ledger.get_block_by_number(num)
            if blk is None:
                raise ChaincodeError(f"block {num} not found")
            return blk.encode()
        if op == "GetBlockByTxID":
            blk = self._ledger.blockstore.get_block_by_txid(
                stub.args[1].decode())
            if blk is None:
                raise ChaincodeError("tx not found")
            return blk.encode()
        if op == "GetTransactionByID":
            pt = self._ledger.get_transaction_by_id(
                stub.args[1].decode())
            if pt is None:
                raise ChaincodeError("tx not found")
            return pt.encode()
        raise ChaincodeError(f"unknown qscc op {op!r}")


class CsccContract:
    """(reference: core/scc/cscc/configure.go)"""

    def __init__(self, channel):
        self._channel = channel

    def invoke(self, stub: ChaincodeStub) -> bytes:
        if not stub.args:
            raise ChaincodeError("no args")
        op = stub.args[0].decode()
        if op == "GetChannelConfig":
            return self._channel.bundle().config.encode()
        if op == "GetConfigBlock":
            ledger = self._channel.ledger
            # walk back from the tip's last-config pointer
            h = ledger.height
            if h == 0:
                raise ChaincodeError("empty chain")
            tip = ledger.get_block_by_number(h - 1)
            lc = protoutil.block_last_config_index(tip)
            blk = ledger.get_block_by_number(lc or 0)
            if blk is None:
                raise ChaincodeError("config block pruned")
            return blk.encode()
        if op == "GetChannels":
            return json.dumps(
                [self._channel.channel_id]).encode()
        raise ChaincodeError(f"unknown cscc op {op!r}")


def build_default_registry(channel, ledger):
    """The standard per-peer chaincode registry: user contract +
    system chaincodes + the lifecycle ceremony wired to the channel's
    application orgs (reference: the SCC registrations of
    internal/peer/node/start.go).  Shared by the e2e network and the
    real peer process so their wiring can never drift."""
    from fabric_mod_tpu.peer.chaincode import (
        ChaincodeRegistry, KvContract)
    from fabric_mod_tpu.peer.lifecycle import (
        LIFECYCLE_NS, LifecycleContract)

    registry = ChaincodeRegistry()
    registry.register("mycc", KvContract())
    registry.register(LIFECYCLE_NS, LifecycleContract(
        channel_orgs=lambda: list(
            channel.bundle().application.org_mspids)))
    registry.register("qscc", QsccContract(ledger))
    registry.register("cscc", CsccContract(channel))
    return registry
