"""Block validation: the north-star hot path, one device batch per block.

(reference: core/committer/txvalidator/v20/validator.go:182-267
`TxValidator.Validate` + `ValidateTx` at :300-455,
core/common/validation/msgvalidation.go:248 `ValidateTransaction`,
the plugin dispatcher at plugindispatcher/dispatcher.go:102, the
default VSCC at handlers/validation/builtin/v20/validation_logic.go:185,
and the endorsement signature-set construction at
statebased/validator_keylevel.go:245-258.)

Where the reference fans out one goroutine per transaction behind a
semaphore and verifies each ECDSA signature as it reaches it, this
validator makes the data flow explicit and device-shaped:

  pass 1 (host)   unpack every tx; syntactic checks; creator identity
                  validation; stage creator signature + every
                  endorsement signature of every tx into ONE
                  BatchCollector (the policy engine's two-phase
                  prepare handles dedup/principal logic)
  pass 2 (device) verifier.verify_many(collector.items) — a single
                  jitted dispatch for the whole block
  pass 3 (host)   resolve creator verdicts, finish each endorsement-
                  policy decision against the mask, mark duplicate
                  tx ids, write the txflags bitmap

MVCC and commit stay in the ledger (kvledger.commit_block).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from fabric_mod_tpu.policy import ApplicationPolicyEvaluator, BatchCollector
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.protos.protoutil import SignedData

V = m.TxValidationCode


class ValidationInfoProvider:
    """Resolves a chaincode namespace to its validation plugin and
    endorsement policy — the lifecycle's job in the reference
    (plugindispatcher dispatcher.go:102 + lifecycle ValidationInfo).
    A static map with a default stands in until the lifecycle SCC
    lands; the seam is the same.
    """

    def __init__(self, default_policy: bytes,
                 per_namespace: Optional[Dict[str, bytes]] = None):
        self._default = default_policy
        self._per_ns = dict(per_namespace or {})

    def validation_info(self, ns: str) -> Tuple[str, bytes]:
        return "vscc", self._per_ns.get(ns, self._default)

    def set_policy(self, ns: str, policy_bytes: bytes) -> None:
        self._per_ns[ns] = policy_bytes


class _TxWork:
    """Per-tx staging between the host pass and the device verdict."""

    __slots__ = ("flag", "txid", "creator_slot", "pendings", "is_config")

    def __init__(self):
        self.flag = V.NOT_VALIDATED
        self.txid = ""
        self.creator_slot = None          # (batch_idx | None, host_ok)
        self.pendings = []                # endorsement PendingEvals
        self.is_config = False


class TxValidator:
    """(reference: txvalidator/v20/validator.go TxValidator)"""

    def __init__(self, channel_id: str, msp_mgr,
                 policy_eval: ApplicationPolicyEvaluator,
                 verifier,
                 vinfo: ValidationInfoProvider,
                 tx_id_exists: Optional[Callable[[str], bool]] = None):
        self.channel_id = channel_id
        self._msp_mgr = msp_mgr
        self._policy_eval = policy_eval
        self._verifier = verifier
        self._vinfo = vinfo
        self._tx_id_exists = tx_id_exists or (lambda _txid: False)

    # -- pass 1: host unpack + staging -----------------------------------
    def _stage_tx(self, env: m.Envelope, work: _TxWork,
                  collector: BatchCollector) -> None:
        """Syntactic validation + creator/endorsement staging for one
        tx.  Sets work.flag on terminal failure, else leaves VALID
        pending the device verdicts.
        (reference: msgvalidation.go:248 ValidateTransaction)"""
        if not env.payload:
            work.flag = V.NIL_ENVELOPE
            return
        try:
            payload = protoutil.unmarshal_envelope_payload(env)
            ch = m.ChannelHeader.decode(payload.header.channel_header)
            sh = m.SignatureHeader.decode(payload.header.signature_header)
        except Exception:
            work.flag = V.BAD_PAYLOAD
            return
        if not ch.channel_id or ch.channel_id != self.channel_id:
            work.flag = V.BAD_CHANNEL_HEADER
            return
        work.txid = ch.tx_id

        # creator signature (reference: msgvalidation.go:26
        # checkSignatureFromCreator — Validate() then Verify())
        if not sh.creator or not env.signature:
            work.flag = V.BAD_CREATOR_SIGNATURE
            return
        try:
            creator = self._msp_mgr.deserialize_identity(sh.creator)
            self._msp_mgr.validate(creator)
        except Exception:
            work.flag = V.BAD_CREATOR_SIGNATURE
            return
        item = creator.verify_item(env.payload, env.signature)
        if item is not None:
            work.creator_slot = (collector.add(item), False)
        else:
            work.creator_slot = (
                None, creator.verify(env.payload, env.signature))

        if ch.type == m.HeaderType.CONFIG:
            work.is_config = True
            return                        # config txs skip endorsement
        if ch.type != m.HeaderType.ENDORSER_TRANSACTION:
            work.flag = V.UNKNOWN_TX_TYPE
            return

        # tx id binding (reference: utils.CheckTxID in msgvalidation)
        expected = protoutil.compute_tx_id(sh.nonce, sh.creator)
        if ch.tx_id != expected:
            work.flag = V.BAD_PROPOSAL_TXID
            return
        if self._tx_id_exists(ch.tx_id):
            work.flag = V.DUPLICATE_TXID
            return

        # endorsement policy per action (reference: VSCC v20
        # validation_logic.go:185 + validator_keylevel.go:245-258:
        # data = proposal-response-payload ‖ endorser-identity)
        try:
            tx = protoutil.extract_endorser_tx(payload)
            if not tx.actions:
                work.flag = V.NIL_TXACTION
                return
            for action in tx.actions:
                cca, prp_bytes, endorsements = \
                    protoutil.tx_rwset_and_endorsements(action)
                if not endorsements:
                    work.flag = V.ENDORSEMENT_POLICY_FAILURE
                    return
                ns = (cca.chaincode_id.name
                      if cca.chaincode_id is not None else "")
                _plugin, policy_bytes = self._vinfo.validation_info(ns)
                sds = [SignedData(data=prp_bytes + e.endorser,
                                  identity=e.endorser,
                                  signature=e.signature)
                       for e in endorsements]
                work.pendings.append(
                    self._policy_eval.prepare(policy_bytes, sds, collector))
        except Exception:
            work.flag = V.INVALID_ENDORSER_TRANSACTION
            return

    # -- the three passes -------------------------------------------------
    def validate(self, block: m.Block) -> List[int]:
        """Validate every tx of `block`; ONE device dispatch total.
        Writes the txflags bitmap into the block metadata and returns
        the flags (reference: validator.go:182-267)."""
        works: List[_TxWork] = []
        collector = BatchCollector()
        for data in block.data.data:
            work = _TxWork()
            works.append(work)
            try:
                env = m.Envelope.decode(data)
            except Exception:
                work.flag = V.BAD_PAYLOAD
                continue
            self._stage_tx(env, work, collector)

        # pass 2: the device batch
        mask = self._verifier.verify_many(collector.items)

        # pass 3: verdicts
        flags: List[int] = []
        for work in works:
            flags.append(self._finish_tx(work, mask))
        self._mark_in_block_duplicates(works, flags)
        protoutil.set_block_txflags(block, bytes(flags))
        return flags

    def _finish_tx(self, work: _TxWork, mask) -> int:
        if work.flag != V.NOT_VALIDATED:
            return work.flag
        bidx, host_ok = work.creator_slot
        creator_ok = bool(mask[bidx]) if bidx is not None else host_ok
        if not creator_ok:
            return V.BAD_CREATOR_SIGNATURE
        if work.is_config:
            return V.VALID
        for pending in work.pendings:
            if not pending.finish(mask):
                return V.ENDORSEMENT_POLICY_FAILURE
        return V.VALID

    @staticmethod
    def _mark_in_block_duplicates(works: Sequence[_TxWork],
                                  flags: List[int]) -> None:
        """First occurrence of a tx id wins
        (reference: validator.go:281 markTXIdDuplicates)."""
        seen = set()
        for i, work in enumerate(works):
            if flags[i] != V.VALID or not work.txid:
                continue
            if work.txid in seen:
                flags[i] = V.DUPLICATE_TXID
            else:
                seen.add(work.txid)


class Committer:
    """Validate + MVCC + commit, the peer's StoreBlock composition
    (reference: gossip/state/state.go:817 commitBlock ->
    coordinator StoreBlock -> validator -> kvledger CommitLegacy)."""

    def __init__(self, validator: TxValidator, ledger):
        self.validator = validator
        self.ledger = ledger

    def store_block(self, block: m.Block) -> List[int]:
        flags = self.validator.validate(block)
        return self.ledger.commit_block(block, flags)
