"""Block validation: the north-star hot path, one device batch per block.

(reference: core/committer/txvalidator/v20/validator.go:182-267
`TxValidator.Validate` + `ValidateTx` at :300-455,
core/common/validation/msgvalidation.go:248 `ValidateTransaction`,
the plugin dispatcher at plugindispatcher/dispatcher.go:102, the
default VSCC at handlers/validation/builtin/v20/validation_logic.go:185,
and the endorsement signature-set construction at
statebased/validator_keylevel.go:245-258.)

Where the reference fans out one goroutine per transaction behind a
semaphore and verifies each ECDSA signature as it reaches it, this
validator makes the data flow explicit and device-shaped:

  pass 1 (host)   unpack every tx; syntactic checks; creator identity
                  validation; stage creator signature + every
                  endorsement signature of every tx into ONE
                  BatchCollector (the policy engine's two-phase
                  prepare handles dedup/principal logic)
  pass 2 (device) verifier.verify_many(collector.items) — a single
                  jitted dispatch for the whole block
  pass 3 (host)   resolve creator verdicts, finish each endorsement-
                  policy decision against the mask, mark duplicate
                  tx ids, write the txflags bitmap

MVCC and commit stay in the ledger (kvledger.commit_block).
"""
from __future__ import annotations

import functools

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from fabric_mod_tpu.observability import tracing
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.policy import ApplicationPolicyEvaluator, BatchCollector
from fabric_mod_tpu.policy import tensorpolicy
from fabric_mod_tpu.protos import batchdecode
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.protos.protoutil import SignedData

V = m.TxValidationCode

_STAGED_ITEMS_OPTS = MetricOpts(
    "fabric", "validator", "staged_verify_items",
    help="Unique verify items staged per block (the device batch size).")
_DEDUP_SAVED_OPTS = MetricOpts(
    "fabric", "validator", "dedup_saved_items",
    help="Verify requests answered by within-block dedup instead of a "
         "device lane (meta-policies and key-level candidates re-stage "
         "identical signature sets).")
_RAW_ITEMS_OPTS = MetricOpts(
    "fabric", "validator", "staged_raw_message_items",
    help="Staged items carrying raw messages instead of host digests "
         "(FABRIC_MOD_TPU_FUSED_HASH: e = H(m) computed on device in "
         "the same program as the verify).")
_BODY_FALLBACK_OPTS = MetricOpts(
    "fabric", "validator", "body_decode_fallbacks",
    help="Endorser-tx bodies the columnar batch decoder could not "
         "prove clean — staged through the generic per-tx decode "
         "instead (identical outcome, serial speed).")


@functools.lru_cache(maxsize=None)
def _stage_metrics():
    prov = default_provider()
    return (prov.histogram(_STAGED_ITEMS_OPTS,
                           buckets=(1, 8, 64, 256, 512, 1024, 2048)),
            prov.counter(_DEDUP_SAVED_OPTS),
            prov.counter(_RAW_ITEMS_OPTS),
            prov.counter(_BODY_FALLBACK_OPTS))


class ValidationInfoProvider:
    """Resolves a chaincode namespace to its validation plugin and
    endorsement policy — the lifecycle's job in the reference
    (plugindispatcher dispatcher.go:102 + lifecycle ValidationInfo).
    A static map with a default stands in until the lifecycle SCC
    lands; the seam is the same.
    """

    def __init__(self, default_policy: bytes,
                 per_namespace: Optional[Dict[str, bytes]] = None):
        self._default = default_policy
        self._per_ns = dict(per_namespace or {})

    def validation_info(self, ns: str) -> Tuple[str, bytes]:
        return "vscc", self._per_ns.get(ns, self._default)

    def set_policy(self, ns: str, policy_bytes: bytes) -> None:
        self._per_ns[ns] = policy_bytes


VALIDATION_PARAMETER = "VALIDATION_PARAMETER"


class _KeyEval:
    """One written key's endorsement-policy resolution candidates.

    (reference: statebased/validator_keylevel.go:243-271 — a key with a
    VALIDATION_PARAMETER metadata override validates against it; which
    override is in force can depend on EARLIER txs in the same block,
    so every candidate's signature checks are staged in pass 1 and the
    choice is resolved sequentially in pass 3.)
    """

    __slots__ = ("ns", "key", "committed", "inblock")

    def __init__(self, ns: str, key: str, committed, inblock):
        self.ns = ns
        self.key = key
        self.committed = committed        # PendingEval | None
        self.inblock = inblock            # [(tx_idx, PendingEval)]


class _ActionEval:
    __slots__ = ("cc_pending", "key_evals")

    def __init__(self, cc_pending, key_evals):
        self.cc_pending = cc_pending      # chaincode-wide policy
        self.key_evals = key_evals        # [_KeyEval]


class _TxWork:
    """Per-tx staging between the host pass and the device verdict."""

    __slots__ = ("flag", "txid", "creator_slot", "actions", "is_config",
                 "env", "vp_writes", "written_ns")

    def __init__(self):
        self.flag = V.NOT_VALIDATED
        self.txid = ""
        self.creator_slot = None          # (batch_idx | None, host_ok)
        self.actions = []                 # [_ActionEval]
        self.is_config = False
        self.env = None                   # kept only for config txs
        self.vp_writes = []               # [(ns, key, policy_bytes)]
        self.written_ns = set()           # namespaces this tx writes


class StagedBlock:
    """A block after passes 1+2: host staging done, device batch
    dispatched, verdicts pending (resolved by TxValidator.finish).

    `trace_timeline` (FMT_TRACE armed only, else None) is the block's
    flight-recorder timeline riding the stage→commit handoff: the
    engine that staged this block attaches it, the committing side
    resumes it — context propagation by carrying the context.

    `session` (FABRIC_MOD_TPU_TENSOR_POLICY armed only, else None) is
    the block's tensor-policy session: resolve_mask hands it the
    verify mask BEFORE the host sync, so a device-resident mask flows
    straight into the jitted policy program (fused downstream of the
    batch verify) while the host copy is still materializing."""

    __slots__ = ("block", "validator", "works", "mask_fn", "_mask",
                 "trace_timeline", "session", "rwsets")

    def __init__(self, block, validator, works, mask_fn, session=None,
                 rwsets=None):
        self.block = block
        self.validator = validator
        self.works = works
        self.mask_fn = mask_fn
        self._mask = None
        self.trace_timeline = None
        self.session = session
        # the stage-time columnar rwset planes (batchdecode.
        # BlockRWSets | None) — commit_block's vectorized MVCC
        # consumes them so the block's tx bodies are decoded ONCE
        self.rwsets = rwsets

    def resolve_mask(self):
        """Await the device verdicts (idempotent).  The commit
        pipeline calls this under its own await-latency histogram;
        `finish` then reads the cached mask for free."""
        if self._mask is None:
            # the single choke point both the pipelined and the
            # synchronous path pass through — the verdict_await
            # sub-stage is attributed HERE so neither path can hide it
            with tracing.span("verdict_await",
                              block=self.block.header.number):
                raw = self.mask_fn()
                if self.session is not None:
                    # bind (and, on a device mask, dispatch) the
                    # whole-block policy program before the host sync
                    self.session.attach_mask(raw)
                self._mask = np.asarray(raw, bool)
                # the fused verify seam defers its verdict-cache
                # write-back to the consumer's sync point — this is it
                writeback = getattr(self.mask_fn, "writeback", None)
                if writeback is not None:
                    writeback()
        return self._mask

    @property
    def needs_barrier(self) -> bool:
        """True when the NEXT block's staging must wait for this
        block's commit: config txs swap the bundle/MSPs, VALIDATION_
        PARAMETER writes change key-level policies, and lifecycle-
        namespace writes change validation info — all state that
        pass 1 reads (reference: the key-level validator's wait at
        validator_keylevel.go + the config serialization in
        validator.go:400)."""
        from fabric_mod_tpu.peer.lifecycle import LIFECYCLE_NS
        for w in self.works:
            if w.is_config or w.vp_writes or LIFECYCLE_NS in w.written_ns:
                return True
        return False


class TxValidator:
    """(reference: txvalidator/v20/validator.go TxValidator)"""

    def __init__(self, channel_id: str, msp_mgr,
                 policy_eval: ApplicationPolicyEvaluator,
                 verifier,
                 vinfo: ValidationInfoProvider,
                 tx_id_exists: Optional[Callable[[str], bool]] = None,
                 config_apply: Optional[Callable[[m.Envelope], None]] = None,
                 state_metadata: Optional[Callable[[str, str],
                                                   Optional[bytes]]] = None,
                 plugin_registry=None,
                 config_sequence: int = 0):
        self.channel_id = channel_id
        self._msp_mgr = msp_mgr
        self._policy_eval = policy_eval
        self._verifier = verifier
        self._vinfo = vinfo
        # keys the tensor-policy principal memo: a validator is built
        # per bundle, and the sequence makes sure a config update can
        # never be answered from a previous epoch's principal matrix
        self._config_seq = config_sequence
        # named validation plugins (reference: handlers/library
        # registry.go:79); definitions naming an unknown plugin fail
        # closed in _stage_tx
        if plugin_registry is None:
            from fabric_mod_tpu.peer.plugins import PluginRegistry
            plugin_registry = PluginRegistry()
        self._plugins = plugin_registry
        self._tx_id_exists = tx_id_exists or (lambda _txid: False)
        # CONFIG txs: validated + applied through the channel config
        # machinery (reference: txvalidator/v20/validator.go:400-421 —
        # config txs are governance, not a signature check).  Fail
        # closed when no applier is wired.
        self._config_apply = config_apply
        # Committed VALIDATION_PARAMETER reader for key-level policies
        # (reference: the key-level validator's policy fetcher over the
        # state DB) — returns ApplicationPolicy bytes or None.
        self._state_metadata = state_metadata

    # -- pass 1: host unpack + staging -----------------------------------
    def _stage_tx(self, env: m.Envelope, work: _TxWork,
                  collector: BatchCollector, inblock_vp,
                  session=None, spine=None, body=None) -> None:
        """Syntactic validation + creator/endorsement staging for one
        tx.  Sets work.flag on terminal failure, else leaves VALID
        pending the device verdicts.  `spine` (protos/batchdecode) is
        the batch pre-pass's already-decoded envelope/payload/header
        layers — value-identical to the generic decode below, which
        stays as the per-tx fallback for rows the scanner rejected.
        `body` (batchdecode.TxBody) is the columnar batch decoder's
        staged endorser-tx body for this row: the exact ns / prp /
        endorsement / written-key values the generic decode chain
        below would produce, already validated transitively — rows it
        could not prove take the generic chain (counted).
        (reference: msgvalidation.go:248 ValidateTransaction)"""
        if not env.payload:
            work.flag = V.NIL_ENVELOPE
            return
        if spine is not None:
            payload, ch, sh = spine.payload, spine.ch, spine.sh
        else:
            try:
                payload = protoutil.unmarshal_envelope_payload(env)
                ch = m.ChannelHeader.decode(payload.header.channel_header)
                sh = m.SignatureHeader.decode(
                    payload.header.signature_header)
            except Exception:
                work.flag = V.BAD_PAYLOAD
                return
        if not ch.channel_id or ch.channel_id != self.channel_id:
            work.flag = V.BAD_CHANNEL_HEADER
            return
        work.txid = ch.tx_id

        # creator signature (reference: msgvalidation.go:26
        # checkSignatureFromCreator — Validate() then Verify())
        if not sh.creator or not env.signature:
            work.flag = V.BAD_CREATOR_SIGNATURE
            return
        try:
            creator = self._msp_mgr.deserialize_identity(sh.creator)
            self._msp_mgr.validate(creator)
        except Exception:
            work.flag = V.BAD_CREATOR_SIGNATURE
            return
        item = creator.verify_item(env.payload, env.signature)
        if item is not None:
            work.creator_slot = (collector.add(item), False)
        else:
            work.creator_slot = (
                None, creator.verify(env.payload, env.signature))

        if ch.type == m.HeaderType.CONFIG:
            work.is_config = True
            work.env = env                # finish_tx re-validates+applies
            return                        # config txs skip endorsement
        if ch.type != m.HeaderType.ENDORSER_TRANSACTION:
            work.flag = V.UNKNOWN_TX_TYPE
            return

        # tx id binding (reference: utils.CheckTxID in msgvalidation)
        expected = protoutil.compute_tx_id(sh.nonce, sh.creator)
        if ch.tx_id != expected:
            work.flag = V.BAD_PROPOSAL_TXID
            return
        # NOTE: the committed-store duplicate-txid check runs in pass 3
        # (_finish_tx callers), not here — staging may run ahead of the
        # previous block's commit in the pipelined path, and only at
        # finish time is the committed store guaranteed current.

        if body is not None:
            # columnar fast path: the batch decoder already produced
            # this tx's staged body view (single action — the scanner
            # rejects multi-action txs into the fallback), so staging
            # reads fields instead of re-decoding six proto layers
            self._stage_body(body, work, collector, inblock_vp, session)
            return

        # endorsement policy per action (reference: VSCC v20
        # validation_logic.go:185 + validator_keylevel.go:245-258:
        # data = proposal-response-payload ‖ endorser-identity)
        try:
            tx = protoutil.extract_endorser_tx(payload)
            if not tx.actions:
                work.flag = V.NIL_TXACTION
                return
            for action in tx.actions:
                cca, prp_bytes, endorsements = \
                    protoutil.tx_rwset_and_endorsements(action)
                if not endorsements:
                    work.flag = V.ENDORSEMENT_POLICY_FAILURE
                    return
                ns = (cca.chaincode_id.name
                      if cca.chaincode_id is not None else "")
                # ONE rwset decode per action, shared by validation-
                # info resolution and key-level policy staging (these
                # used to each decode cca.results themselves)
                try:
                    rwset = m.TxReadWriteSet.decode(cca.results)
                except Exception:
                    rwset = None
                plugin_name, policy_bytes = self._resolve_vinfo(ns, rwset)
                evaluator = self._plugins.resolve(plugin_name,
                                                  self._policy_eval)
                if evaluator is None:
                    # definition names a plugin this peer does not
                    # have: fail closed (reference: plugindispatcher's
                    # missing-plugin error -> invalid tx)
                    work.flag = V.INVALID_OTHER_REASON
                    return
                sds = [SignedData(data=prp_bytes + e.endorser,
                                  identity=e.endorser,
                                  signature=e.signature)
                       for e in endorsements]
                # session rides only through evaluators that opt in;
                # plugin evaluators keep their 3-arg prepare contract
                if session is not None and getattr(
                        evaluator, "supports_tensor_session", False):
                    cc_pending = evaluator.prepare(
                        policy_bytes, sds, collector, session)
                else:
                    cc_pending = evaluator.prepare(
                        policy_bytes, sds, collector)
                key_evals = self._stage_key_policies(
                    rwset, sds, collector, inblock_vp, work, session)
                work.actions.append(_ActionEval(cc_pending, key_evals))
        except Exception:
            work.flag = V.INVALID_ENDORSER_TRANSACTION
            return

    def _stage_body(self, body, work, collector, inblock_vp,
                    session=None) -> None:
        """Stage one scanner-accepted endorser-tx body — the columnar
        twin of _stage_tx's generic action loop, consuming the values
        batchdecode already proved instead of re-decoding them.  Every
        flag it can set is one the generic chain sets on the same
        bytes (the decoder's soundness gate)."""
        try:
            if body.no_action:
                work.flag = V.NIL_TXACTION
                return
            if not body.endorsements:
                work.flag = V.ENDORSEMENT_POLICY_FAILURE
                return
            ns = body.ns
            plugin_name, policy_bytes = self._resolve_vinfo(
                ns, None, keys=body.lifecycle_write_keys(ns))
            evaluator = self._plugins.resolve(plugin_name,
                                              self._policy_eval)
            if evaluator is None:
                work.flag = V.INVALID_OTHER_REASON
                return
            sds = [SignedData(data=body.prp + endorser,
                              identity=endorser,
                              signature=signature)
                   for endorser, signature in body.endorsements]
            if session is not None and getattr(
                    evaluator, "supports_tensor_session", False):
                cc_pending = evaluator.prepare(
                    policy_bytes, sds, collector, session)
            else:
                cc_pending = evaluator.prepare(
                    policy_bytes, sds, collector)
            key_evals = self._stage_key_policies_columnar(
                body, sds, collector, inblock_vp, work, session)
            work.actions.append(_ActionEval(cc_pending, key_evals))
        except Exception:
            work.flag = V.INVALID_ENDORSER_TRANSACTION
            return

    def _resolve_vinfo(self, ns: str, rwset, keys=None):
        """Validation info for one action; `_lifecycle` writes are
        resolved write-aware when the provider supports it (org-local
        approval txs validate against that org's Endorsement policy —
        see peer/lifecycle.py).  `rwset` is the action's decoded
        TxReadWriteSet (None when cca.results was malformed — fall
        back to tx-level resolution; decode errors are surfaced by
        validation itself).  `keys` short-circuits the inner decode
        when the columnar body already carries this ns's write keys."""
        from fabric_mod_tpu.peer.lifecycle import LIFECYCLE_NS
        write_aware = getattr(self._vinfo, "validation_info_for_writes",
                              None)
        if write_aware is not None and ns == LIFECYCLE_NS and \
                (rwset is not None or keys is not None):
            try:
                if keys is None:
                    keys = [w.key
                            for nsrw in rwset.ns_rwset
                            if nsrw.namespace == ns
                            for w in m.KVRWSet.decode(nsrw.rwset).writes]
                return write_aware(ns, keys)
            except Exception:  # fmtlint: allow[swallowed-exceptions] -- malformed inner rwset: fall back to tx-level VP resolution; decode errors are surfaced by validation itself
                pass
        return self._vinfo.validation_info(ns)

    def _stage_key_policies(self, rwset, sds, collector, inblock_vp,
                            work, session=None):
        """Stage every candidate key-level endorsement policy of this
        action's written keys (reference: validator_keylevel.go — the
        committed VALIDATION_PARAMETER plus any same-block overrides
        whose applicability pass 3 resolves in order).  `rwset` is the
        action's already-decoded TxReadWriteSet (None = malformed ->
        no key evals, the historical behavior)."""
        key_evals = []
        if rwset is None:
            return key_evals
        from fabric_mod_tpu.ledger.rwsetutil import parse_tx_rwset
        for ns, kv in parse_tx_rwset(rwset):
            if kv.writes or kv.metadata_writes:
                work.written_ns.add(ns)
            written = dict.fromkeys(
                [w.key for w in kv.writes]
                + [mw.key for mw in kv.metadata_writes])
            for key in written:
                committed_pending = None
                if self._state_metadata is not None:
                    vp = self._state_metadata(ns, key)
                    if vp:
                        committed_pending = self._policy_eval.prepare(
                            vp, sds, collector, session)
                cands = inblock_vp.get((ns, key), ())
                inblock = [(idx, self._policy_eval.prepare(
                    vp, sds, collector, session))
                           for idx, vp in cands]
                # EVERY written key gets an eval entry: keys without an
                # effective VP resolve to None in pass 3 and force the
                # cc-wide policy — otherwise a tx satisfying one key's
                # narrow VP could smuggle writes to other keys past the
                # chaincode policy (fail-closed, like the reference's
                # per-key fallback to the default policy)
                key_evals.append(
                    _KeyEval(ns, key, committed_pending, inblock))
            # register this tx's own VALIDATION_PARAMETER writes for
            # later txs in the block (applied only if this tx is VALID)
            for mw in kv.metadata_writes:
                for e in mw.entries:
                    if e.name == VALIDATION_PARAMETER:
                        work.vp_writes.append((ns, mw.key, e.value))
        return key_evals

    def _stage_key_policies_columnar(self, body, sds, collector,
                                     inblock_vp, work, session=None):
        """_stage_key_policies over a columnar TxBody: `body.groups`
        is the per-ns-occurrence written view the generic path derives
        from parse_tx_rwset — same occurrence order, same per-
        occurrence key dedup, same eval/vp-write sequence."""
        key_evals = []
        for ns, wkeys, metas in body.groups:
            if wkeys or metas:
                work.written_ns.add(ns)
            written = dict.fromkeys(
                list(wkeys) + [mkey for mkey, _entries in metas])
            for key in written:
                committed_pending = None
                if self._state_metadata is not None:
                    vp = self._state_metadata(ns, key)
                    if vp:
                        committed_pending = self._policy_eval.prepare(
                            vp, sds, collector, session)
                cands = inblock_vp.get((ns, key), ())
                inblock = [(idx, self._policy_eval.prepare(
                    vp, sds, collector, session))
                           for idx, vp in cands]
                key_evals.append(
                    _KeyEval(ns, key, committed_pending, inblock))
            for mkey, entries in metas:
                for name, value in entries:
                    if name == VALIDATION_PARAMETER:
                        work.vp_writes.append((ns, mkey, value))
        return key_evals

    # -- the three passes -------------------------------------------------
    def stage(self, block: m.Block) -> "StagedBlock":
        """Passes 1+2: host unpack/staging, then DISPATCH the device
        batch without awaiting it.  The returned StagedBlock carries
        the pending verdicts; `finish` resolves them.  Staging block
        N+1 while block N commits is the commit pipeline's double
        buffer — legal exactly when block N sets no state the staging
        reads (see StagedBlock.needs_barrier)."""
        works: List[_TxWork] = []
        collector = BatchCollector()
        session = None
        if tensorpolicy.enabled():
            session = tensorpolicy.TensorSession(self._msp_mgr,
                                                 self._config_seq)
        # (ns, key) -> [(tx_idx, ApplicationPolicy bytes)]: the
        # VALIDATION_PARAMETER writes of EARLIER txs in this block —
        # the intra-block dependency structure of validator_keylevel.go
        inblock_vp: Dict[tuple, list] = {}
        with tracing.span("unpack", block=block.header.number,
                          txs=len(block.data.data)):
            # batch pre-pass: the whole block's envelope/payload/
            # header spine in one vectorized scan; rows the scanner
            # could not prove clean come back None and take the
            # generic per-tx decode below (identical outcomes)
            spines = batchdecode.decode_block_spine(block.data.data)
            # batch body pre-pass: every spine-accepted endorser tx's
            # payload.data goes through ONE columnar rwset decode
            # (protos/batchdecode.decode_block_rwsets); accepted
            # bodies are shared by VP resolution, key-level policy
            # staging, and — vectorized — MVCC at commit
            with tracing.span("body_decode",
                              block=block.header.number,
                              txs=len(block.data.data)):
                body_datas: List[Optional[bytes]] = \
                    [None] * len(block.data.data)
                for idx, spine in enumerate(spines):
                    if spine is not None and spine.ch.type == \
                            m.HeaderType.ENDORSER_TRANSACTION:
                        body_datas[idx] = spine.payload.data
                rwsets = batchdecode.decode_block_rwsets(body_datas)
            if rwsets is not None:
                # header facts ride along: value-identical to the
                # generic envelope_channel_header decode commit would
                # otherwise repeat per tx
                for idx, spine in enumerate(spines):
                    if spine is not None:
                        rwsets.txids[idx] = spine.ch.tx_id
                        rwsets.types[idx] = spine.ch.type
                _stage_metrics()[3].add(rwsets.fallbacks)
            for idx, data in enumerate(block.data.data):
                work = _TxWork()
                works.append(work)
                spine = spines[idx]
                if spine is not None:
                    env = spine.env
                else:
                    try:
                        env = m.Envelope.decode(data)
                    except Exception:
                        work.flag = V.BAD_PAYLOAD
                        continue
                body = rwsets.bodies[idx] if rwsets is not None else None
                self._stage_tx(env, work, collector, inblock_vp,
                               session, spine, body)
                for ns, key, vp in work.vp_writes:
                    inblock_vp.setdefault((ns, key), []).append((idx, vp))
        if session is not None and len(session):
            # build the block's dense policy tensors (the MSP
            # principal matrix lands here, memoized per pair)
            with tracing.span("policy_gather",
                              block=block.header.number,
                              instances=len(session),
                              fallbacks=session.fallbacks):
                session.finalize()

        # pass 2: dispatch the device batch (async when the verifier
        # supports it; the resolver blocks only when called).  Repeats
        # across blocks — gossip redelivery, the endorsement/commit
        # dual validation — are the verifier-level memo-cache's job
        # (bccsp/tpu.VerdictCache); within-block repeats never reach
        # it thanks to the collector's dedup, and both effects are
        # exported so coalescing stays observable.
        staged_hist, dedup_ctr, raw_ctr, _fb_ctr = _stage_metrics()
        staged_hist.observe(len(collector.items))
        dedup_ctr.add(collector.requests - len(collector.items))
        # Raw-message items (identities emit them under FABRIC_MOD_
        # TPU_FUSED_HASH) flow through the same collector/dedup into
        # p256.batch_verify_raw — counted so the fused rollout is
        # observable per block.
        raw_ctr.add(sum(1 for it in collector.items
                        if getattr(it, "message", None) is not None))
        with tracing.span("device_dispatch",
                          block=block.header.number,
                          items=len(collector.items)):
            # with a tensor session, prefer the verifier's FUSED seam:
            # its resolver may hand back a device-resident mask the
            # policy program consumes without a host round trip
            async_fn = None
            if session is not None:
                async_fn = getattr(self._verifier,
                                   "verify_many_fused_async", None)
            if async_fn is None:
                async_fn = getattr(self._verifier, "verify_many_async",
                                   None)
            if async_fn is not None:
                mask_fn = async_fn(collector.items)
            else:
                items = collector.items
                mask_fn = lambda: self._verifier.verify_many(items)
        return StagedBlock(block, self, works, mask_fn, session, rwsets)

    def finish(self, staged: "StagedBlock") -> List[int]:
        """Pass 3: await the device verdicts, then sequential flag
        resolution — duplicate marking and key-level override
        application happen in block order so later txs see exactly the
        effects of earlier VALID ones."""
        block, works = staged.block, staged.works
        mask = staged.resolve_mask()
        session = staged.session
        if session is not None and len(session):
            # ONE evaluator pass produces every chaincode-level and
            # key-level verdict of the block (jitted program on a
            # device mask, vectorized numpy on a host mask); the
            # host loop below then reads precomputed booleans
            with tracing.span("policy_device",
                              block=block.header.number,
                              instances=len(session)):
                session.verdicts()
        flags: List[int] = []
        seen_txids = set()
        applied_vp: Dict[tuple, int] = {}   # (ns, key) -> writer tx_idx
        with tracing.span("policy_finish", block=block.header.number):
            for idx, work in enumerate(works):
                flag = self._finish_tx(work, mask, applied_vp)
                if flag == V.VALID and work.txid:
                    if work.txid in seen_txids or \
                            self._tx_id_exists(work.txid):
                        flag = V.DUPLICATE_TXID
                    else:
                        seen_txids.add(work.txid)
                if flag == V.VALID:
                    for ns, key, _vp in work.vp_writes:
                        applied_vp[(ns, key)] = idx
                flags.append(flag)
            protoutil.set_block_txflags(block, bytes(flags))
        return flags

    def validate(self, block: m.Block) -> List[int]:
        """Validate every tx of `block`; ONE device dispatch total.
        Writes the txflags bitmap into the block metadata and returns
        the flags (reference: validator.go:182-267)."""
        return self.finish(self.stage(block))

    def _finish_tx(self, work: _TxWork, mask, applied_vp) -> int:
        if work.flag != V.NOT_VALIDATED:
            return work.flag
        bidx, host_ok = work.creator_slot
        creator_ok = bool(mask[bidx]) if bidx is not None else host_ok
        if not creator_ok:
            return V.BAD_CREATOR_SIGNATURE
        if work.is_config:
            # (reference: validator.go:400-421 — the config envelope is
            # re-validated against the current bundle's mod policies and
            # applied; anything short of that is INVALID, fail-closed)
            if self._config_apply is None:
                return V.INVALID_CONFIG_TRANSACTION
            try:
                self._config_apply(work.env)
            except Exception:
                return V.INVALID_CONFIG_TRANSACTION
            return V.VALID
        for action in work.actions:
            uncovered = not action.key_evals
            for ke in action.key_evals:
                writer = applied_vp.get((ke.ns, ke.key))
                pending = None
                if writer is not None:
                    for tx_idx, cand in ke.inblock:
                        if tx_idx == writer:
                            pending = cand
                            break
                if pending is None:
                    pending = ke.committed
                if pending is None:
                    uncovered = True        # falls to the cc-wide policy
                    continue
                if not pending.finish(mask):
                    return V.ENDORSEMENT_POLICY_FAILURE
            if uncovered and not action.cc_pending.finish(mask):
                return V.ENDORSEMENT_POLICY_FAILURE
        return V.VALID


class Committer:
    """Validate + MVCC + commit, the peer's StoreBlock composition
    (reference: gossip/state/state.go:817 commitBlock ->
    coordinator StoreBlock -> validator -> kvledger CommitLegacy).

    Strictly serial: block N+1's staging starts only after block N's
    commit returns.  peer/commitpipe.PipelinedCommitter is the
    overlapped version of this composition (and collapses to exactly
    this behavior at depth=1)."""

    def __init__(self, validator: TxValidator, ledger):
        self.validator = validator
        self.ledger = ledger

    def store_block(self, block: m.Block) -> List[int]:
        # the synchronous path records the SAME per-block timeline the
        # pipelined engine does, so /flight and the bench attribution
        # see both arms through one lens
        tl = tracing.start_timeline("sync", block.header.number)
        try:
            with tracing.timeline_scope(tl):
                staged = self.validator.stage(block)
                flags = self.validator.finish(staged)
                return self.ledger.commit_block(block, flags,
                                                rwsets=staged.rwsets)
        finally:
            tracing.finish_timeline(tl)
