"""Client-facing event deliver service: Deliver / DeliverFiltered.

(reference: core/peer/deliverevents.go — `Deliver` at :255 streaming
full blocks, `DeliverFiltered` at :240 streaming filtered blocks;
filtered-block construction in blockResponseSender at :293.  This is
the service SDKs use to learn a transaction's validation code after
commit — without it no application can know its tx committed.)

Server side: seek semantics over the PEER ledger (committed blocks,
whose metadata carries the validator's txflags), gated per-stream by
the channel ACLs `event/Block` / `event/FilteredBlock`
(peer/aclmgmt.py).  The stream blocks at the chain tip on the
ledger's commit notification (KvLedger.height_changed), the analog of
the reference's CommitNotifier.

Client side: `EventDeliverClient` signs SeekInfo envelopes and exposes
`wait_for_tx` — scan filtered blocks until a txid appears and return
its validation code — which `chaincode invoke --wait-event` uses.
"""
from __future__ import annotations

import threading
from typing import Callable, Iterator, Optional, Tuple

from fabric_mod_tpu.comm.grpc_comm import GRPCClient, GRPCServer, MethodKind
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.protos.protoutil import SignedData

SERVICE = "protos.Deliver"


# ---------------------------------------------------------------------------
# Filtered-block construction (reference: deliverevents.go:293)
# ---------------------------------------------------------------------------

def filtered_block(channel_id: str, block: m.Block) -> m.FilteredBlock:
    """Project a committed block to its filtered form: per-tx txid,
    header type, validation code, and chaincode events with the
    payload NILLED (the reference strips event payloads so filtered
    streams never leak application data)."""
    flags = protoutil.block_txflags(block)
    ftxs = []
    for i, env in enumerate(protoutil.get_envelopes(block)):
        code = (flags[i] if i < len(flags)
                else m.TxValidationCode.NOT_VALIDATED)
        try:
            payload = protoutil.unmarshal_envelope_payload(env)
            ch = m.ChannelHeader.decode(payload.header.channel_header)
        except Exception:
            ftxs.append(m.FilteredTransaction(tx_validation_code=code))
            continue
        ftx = m.FilteredTransaction(txid=ch.tx_id, type=ch.type,
                                    tx_validation_code=code)
        if ch.type == m.HeaderType.ENDORSER_TRANSACTION:
            try:
                ftx.transaction_actions = _filtered_actions(payload.data)
            except Exception:  # fmtlint: allow[swallowed-exceptions] -- malformed tx body: the filtered event still carries txid+code, which is the contract
                pass
        ftxs.append(ftx)
    return m.FilteredBlock(channel_id=channel_id,
                           number=block.header.number,
                           filtered_transactions=ftxs)


def _is_config_block(block: m.Block) -> bool:
    """Whether a committed block carries a channel config transaction
    (first envelope's header type; config blocks hold exactly one)."""
    try:
        env = protoutil.get_envelopes(block)[0]
        payload = protoutil.unmarshal_envelope_payload(env)
        ch = m.ChannelHeader.decode(payload.header.channel_header)
        return ch.type == m.HeaderType.CONFIG
    except Exception:
        return False


def _filtered_actions(tx_bytes: bytes) -> m.FilteredTransactionActions:
    actions = []
    tx = m.Transaction.decode(tx_bytes)
    for action in tx.actions:
        cap = m.ChaincodeActionPayload.decode(action.payload)
        if cap.action is None:
            continue
        prp = m.ProposalResponsePayload.decode(
            cap.action.proposal_response_payload)
        cca = m.ChaincodeAction.decode(prp.extension)
        event = None
        if cca.events:
            ev = m.ChaincodeEvent.decode(cca.events)
            # payload stripped, per the reference's filtered contract
            event = m.ChaincodeEvent(chaincode_id=ev.chaincode_id,
                                     tx_id=ev.tx_id,
                                     event_name=ev.event_name)
        actions.append(m.FilteredChaincodeAction(chaincode_event=event))
    return m.FilteredTransactionActions(chaincode_actions=actions)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

class EventDeliverServer:
    """Registers Deliver/DeliverFiltered on a gRPC server.

    `acl` is a peer ACLProvider; each stream's first envelope is
    checked against event/Block or event/FilteredBlock before any
    block flows (reference: deliverevents.go's per-stream policy
    check via the deliver.Handler's access control)."""

    def __init__(self, channel_id: str, ledger, acl,
                 grpc: Optional[GRPCServer] = None,
                 address: str = "127.0.0.1:0",
                 server_cert_pem: Optional[bytes] = None,
                 server_key_pem: Optional[bytes] = None,
                 client_root_pem: Optional[bytes] = None,
                 max_streams: int = 40):
        self._channel_id = channel_id
        self._ledger = ledger
        self._acl = acl
        self._closing = threading.Event()
        # admission cap: each BLOCK_UNTIL_READY stream parks a gRPC
        # worker thread at the tip; without a bound, standing event
        # subscriptions could exhaust a shared listener's pool and
        # starve ProcessProposal (the reference bounds this with its
        # grpc server's stream limits + deliver handler accounting)
        self._streams = threading.Semaphore(max_streams)
        # committed blocks are immutable, so their config/not-config
        # classification is too: memoized by block number so N
        # subscribers don't each re-decode every block's first
        # envelope on the event hot path (GIL-atomic dict ops; a
        # racing duplicate compute is harmless)
        self._cfg_memo: dict = {}
        self._owns_grpc = grpc is None
        self._grpc = grpc or GRPCServer(address, server_cert_pem,
                                        server_key_pem, client_root_pem)
        self.port = self._grpc.port
        self._grpc.register(SERVICE, "Deliver", MethodKind.STREAM_STREAM,
                            self._make_handler(filtered=False))
        self._grpc.register(SERVICE, "DeliverFiltered",
                            MethodKind.STREAM_STREAM,
                            self._make_handler(filtered=True))

    def start(self) -> None:
        if self._owns_grpc:
            self._grpc.start()

    def stop(self, grace: float = 1.0) -> None:
        # wake every handler parked at the chain tip so shared-listener
        # shutdown cannot strand worker threads in cond.wait
        self._closing.set()
        with self._ledger.height_changed:
            self._ledger.height_changed.notify_all()
        if self._owns_grpc:
            self._grpc.stop(grace)

    # -- stream handler --------------------------------------------------

    def _block_is_config(self, blk: m.Block) -> bool:
        # local-read/return: a concurrent stream's clear() between our
        # store and a re-read must not KeyError a live subscription
        num = blk.header.number
        val = self._cfg_memo.get(num)
        if val is None:
            val = _is_config_block(blk)
            if len(self._cfg_memo) > 4096:
                self._cfg_memo.clear()
            self._cfg_memo[num] = val
        return val

    def _make_handler(self, filtered: bool):
        def handle(request_iter, context) -> Iterator[bytes]:
            if not self._streams.acquire(blocking=False):
                yield m.DeliverResponse(
                    status=m.Status.SERVICE_UNAVAILABLE).encode()
                return
            try:
                for raw in request_iter:
                    status, seek, recheck = self._check_request(
                        raw, filtered)
                    if seek is None:
                        yield m.DeliverResponse(status=status).encode()
                        return
                    stop_event = threading.Event()
                    context.add_callback(stop_event.set)
                    final = {"status": m.Status.SUCCESS}
                    for blk in self._blocks(seek, stop_event, final,
                                            recheck):
                        if filtered:
                            resp = m.DeliverResponse(
                                filtered_block=filtered_block(
                                    self._channel_id, blk))
                        else:
                            resp = m.DeliverResponse(block=blk)
                        yield resp.encode()
                    yield m.DeliverResponse(
                        status=final["status"]).encode()
            finally:
                self._streams.release()
        return handle

    def _check_request(self, raw: bytes, filtered: bool
                       ) -> Tuple[int, Optional[m.SeekInfo],
                                  Optional[Callable[[], None]]]:
        try:
            env = m.Envelope.decode(raw)
            payload = protoutil.unmarshal_envelope_payload(env)
            ch = m.ChannelHeader.decode(payload.header.channel_header)
            sh = m.SignatureHeader.decode(payload.header.signature_header)
            seek = m.SeekInfo.decode(payload.data)
        except Exception:
            return m.Status.BAD_REQUEST, None, None
        # Only DELIVER_SEEK_INFO envelopes are seek requests: any other
        # well-signed envelope type decoding "successfully" as SeekInfo
        # is an accident of the wire format, not a request (the
        # reference's deliver handler validates the header type before
        # the payload — deliver/deliver.go).
        if ch.type != m.HeaderType.DELIVER_SEEK_INFO:
            return m.Status.BAD_REQUEST, None, None
        if ch.channel_id != self._channel_id:
            return m.Status.NOT_FOUND, None, None
        resource = "event/FilteredBlock" if filtered else "event/Block"
        sd = SignedData(data=env.payload, identity=sh.creator,
                        signature=env.signature)
        # snapshot the config sequence BEFORE the initial ACL check:
        # a config update committing between the check and the
        # snapshot would otherwise record the NEW sequence against a
        # verdict computed under the OLD config, and the session
        # re-check below would never fire for it
        seq_of = getattr(self._acl, "config_sequence", None)
        state = {"seq": seq_of() if seq_of is not None else None}
        try:
            self._acl.check_acl(resource, [sd])
        except Exception:
            return m.Status.FORBIDDEN, None, None
        # the session re-check: the ACL provider reads the CURRENT
        # channel bundle, so re-running this closure after a config
        # block commits evaluates the NEW config (reference:
        # common/deliver/deliver.go:157-199 — SessionAC re-evaluates
        # when the config sequence advances).  Cached by sequence: a
        # full check re-verifies the seek signature against channel
        # policy, too expensive per block — so the closure is a no-op
        # until the sequence moves (or `force`, for a config block
        # flowing through THIS stream, which revokes even when the
        # bundle swap isn't visible as a sequence change).

        def recheck(force: bool = False) -> None:
            seq = seq_of() if seq_of is not None else None
            if force or seq != state["seq"]:
                state["seq"] = seq
                self._acl.check_acl(resource, [sd])
        return m.Status.SUCCESS, seek, recheck

    def _blocks(self, seek: m.SeekInfo, stop_event: threading.Event,
                final: dict, recheck=None) -> Iterator[m.Block]:
        """BLOCK_UNTIL_READY streams wait at the tip indefinitely —
        the client's gRPC deadline/cancel (via `stop_event`) and
        server close (`_closing`) are the only terminators, so long
        event subscriptions are not silently capped (reference:
        deliver.go's commit-notified wait).  FAIL_IF_NOT_READY at a
        missing block sets final["status"]=NOT_FOUND — the retryable
        error, not an empty success.

        `recheck` re-evaluates the stream's ACL against the CURRENT
        channel config before every block send — forced when a config
        block flows through THIS stream, and whenever the channel's
        config sequence has advanced (so a bounded or lagging stream
        that never reaches the config block is still cut off the
        moment the revoking config commits): a revoked subscriber
        gets FORBIDDEN before the next block — fail-closed; a
        standing BLOCK_UNTIL_READY subscription is not a grandfather
        clause (reference: deliver.go:157-199's session-ACL
        re-evaluation on config sequence change)."""
        led = self._ledger
        h = led.height
        num = protoutil.seek_number(seek.start, h, newest_tip=True) or 0
        stop = protoutil.seek_number(seek.stop, h, newest_tip=False)
        cond = led.height_changed
        while stop is None or num <= stop:
            if stop_event.is_set() or self._closing.is_set():
                return
            blk = led.get_block_by_number(num)
            if blk is not None:
                if recheck is not None:
                    try:
                        recheck(force=self._block_is_config(blk))
                    except Exception:
                        final["status"] = m.Status.FORBIDDEN
                        return
                yield blk
                num += 1
                continue
            if seek.behavior == m.SeekBehavior.FAIL_IF_NOT_READY:
                final["status"] = m.Status.NOT_FOUND
                return
            with cond:
                if led.height > num:
                    continue              # raced a commit; re-read
                # short tick: re-check cancellation/close between waits
                cond.wait(timeout=1.0)
        # fallthrough: [start, stop] fully served


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------

def make_signed_seek_envelope(channel_id: str, start: int,
                              stop: Optional[int], signer,
                              behavior: Optional[int] = None
                              ) -> m.Envelope:
    """A SeekInfo envelope with a real creator + signature — the event
    service enforces ACLs, so the anonymous envelope the orderer path
    uses (orderer/server.py make_seek_envelope) is not enough."""
    stop_pos = (m.SeekPosition(specified=m.SeekSpecified(number=stop))
                if stop is not None else None)
    seek = m.SeekInfo(
        start=m.SeekPosition(specified=m.SeekSpecified(number=start)),
        stop=stop_pos,
        behavior=(m.SeekBehavior.BLOCK_UNTIL_READY
                  if behavior is None else behavior))
    ch = protoutil.make_channel_header(
        m.HeaderType.DELIVER_SEEK_INFO, channel_id)
    sh = protoutil.make_signature_header(signer.serialize(),
                                         protoutil.new_nonce())
    payload = protoutil.make_payload(ch, sh, seek.encode())
    return protoutil.sign_envelope(payload, signer)


class EventDeliverClient:
    """Client over the peer event service (the SDK-shaped consumer)."""

    def __init__(self, client: GRPCClient, channel_id: str, signer):
        self._client = client
        self._channel_id = channel_id
        self._signer = signer

    def _stream(self, method: str, start: int, stop: Optional[int],
                timeout_s: Optional[float] = None):
        env = make_signed_seek_envelope(self._channel_id, start, stop,
                                        self._signer)
        return self._client.stream_stream(SERVICE, method,
                                          iter([env.encode()]),
                                          timeout=timeout_s)

    def blocks(self, start: int = 0, stop: Optional[int] = None,
               timeout_s: Optional[float] = None) -> Iterator[m.Block]:
        for raw in self._stream("Deliver", start, stop, timeout_s):
            resp = m.DeliverResponse.decode(raw)
            if resp.block is not None:
                yield resp.block
            else:
                self._raise_unless_ok(resp.status)
                return

    def filtered_blocks(self, start: int = 0, stop: Optional[int] = None,
                        timeout_s: Optional[float] = None
                        ) -> Iterator[m.FilteredBlock]:
        for raw in self._stream("DeliverFiltered", start, stop, timeout_s):
            resp = m.DeliverResponse.decode(raw)
            if resp.filtered_block is not None:
                yield resp.filtered_block
            else:
                self._raise_unless_ok(resp.status)
                return

    @staticmethod
    def _raise_unless_ok(status: int) -> None:
        if status != m.Status.SUCCESS:
            raise EventStreamError(status)

    def wait_for_tx(self, txid: str, start: int = 0,
                    timeout_s: float = 30.0) -> int:
        """Block until `txid` appears in a committed block; return its
        TxValidationCode.  The gRPC deadline bounds the wait (the
        invoke flow: submit to ordering, then wait here for the
        commit-side verdict — reference: the SDK's commit listener
        over DeliverFiltered)."""
        import grpc
        try:
            for fb in self.filtered_blocks(start=start,
                                           timeout_s=timeout_s):
                for ftx in fb.filtered_transactions:
                    if ftx.txid == txid:
                        return ftx.tx_validation_code
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.DEADLINE_EXCEEDED:
                raise TimeoutError(
                    f"tx {txid} not committed within {timeout_s}s")
            raise
        raise TimeoutError(f"tx {txid} not seen before stream end")


class EventStreamError(Exception):
    def __init__(self, status: int):
        super().__init__(f"event deliver stream refused: status {status}")
        self.status = status
