"""Client-facing event deliver service: Deliver / DeliverFiltered.

(reference: core/peer/deliverevents.go — `Deliver` at :255 streaming
full blocks, `DeliverFiltered` at :240 streaming filtered blocks;
filtered-block construction in blockResponseSender at :293.  This is
the service SDKs use to learn a transaction's validation code after
commit — without it no application can know its tx committed.)

Server side: seek semantics over the PEER ledger (committed blocks,
whose metadata carries the validator's txflags), gated per-stream by
the channel ACLs `event/Block` / `event/FilteredBlock`
(peer/aclmgmt.py).  Since ISSUE 17 the server rides the shared
per-block fan-out engine (peer/fanout.py): each block is materialized
and encoded ONCE per form into a bounded ring, streams park on the
ledger's CommitNotifier (one notifier thread, zero tick wakeups), and
the session ACL re-check is batched per (resource, creator) group —
see the fanout module docstring for the full contract.

Client side: `EventDeliverClient` signs SeekInfo envelopes and exposes
`wait_for_tx` — scan filtered blocks until a txid appears and return
its validation code — which `chaincode invoke --wait-event` uses.
"""
from __future__ import annotations

import threading
from typing import Callable, Iterator, Optional, Tuple

from fabric_mod_tpu.comm.grpc_comm import GRPCClient, GRPCServer, MethodKind
from fabric_mod_tpu.concurrency import CancellationEvent
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.peer.fanout import (FanoutEngine, _filtered_actions,
                                        _is_config_block, filtered_block)
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.protos.protoutil import SignedData
from fabric_mod_tpu.utils import knobs

__all__ = ["EventDeliverServer", "EventDeliverClient", "EventStreamError",
           "filtered_block", "make_signed_seek_envelope"]

# the projection primitives live in peer/fanout.py (the fan-out engine
# is the layer below this service); re-exported here because this
# module is their historical home
_ = (_filtered_actions, _is_config_block)

SERVICE = "protos.Deliver"


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

class EventDeliverServer:
    """Registers Deliver/DeliverFiltered on a gRPC server.

    `acl` is a peer ACLProvider; each stream's first envelope is
    checked against event/Block or event/FilteredBlock before any
    block flows (reference: deliverevents.go's per-stream policy
    check via the deliver.Handler's access control).  Session
    re-checks after admission are batched per (resource, creator)
    group by the fan-out engine."""

    def __init__(self, channel_id: str, ledger, acl,
                 grpc: Optional[GRPCServer] = None,
                 address: str = "127.0.0.1:0",
                 server_cert_pem: Optional[bytes] = None,
                 server_key_pem: Optional[bytes] = None,
                 client_root_pem: Optional[bytes] = None,
                 max_streams: Optional[int] = None):
        self._channel_id = channel_id
        self._ledger = ledger
        self._acl = acl
        self._closing = threading.Event()
        if max_streams is None:
            max_streams = knobs.get_int("FABRIC_MOD_TPU_DELIVER_STREAMS")
        # admission cap: each BLOCK_UNTIL_READY stream parks a gRPC
        # worker thread at the tip; without a bound, standing event
        # subscriptions could exhaust a shared listener's pool and
        # starve ProcessProposal (the reference bounds this with its
        # grpc server's stream limits + deliver handler accounting)
        self._streams = threading.Semaphore(max_streams)
        provider = default_provider()
        self._m_active = provider.gauge(MetricOpts(
            "fabric", "deliver", "streams_active",
            "deliver streams currently admitted", ("channel",)))
        self._m_rejected = provider.counter(MetricOpts(
            "fabric", "deliver", "streams_rejected_total",
            "streams refused SERVICE_UNAVAILABLE at the admission cap",
            ("channel",)))
        # the shared fan-out: ring x {full, filtered} + commit
        # notifier + batched session ACL groups (ISSUE 17 tentpole)
        self._fanout = FanoutEngine(channel_id, ledger, acl)
        self._owns_grpc = grpc is None
        self._grpc = grpc or GRPCServer(address, server_cert_pem,
                                        server_key_pem, client_root_pem)
        self.port = self._grpc.port
        self._grpc.register(SERVICE, "Deliver", MethodKind.STREAM_STREAM,
                            self._make_handler(filtered=False))
        self._grpc.register(SERVICE, "DeliverFiltered",
                            MethodKind.STREAM_STREAM,
                            self._make_handler(filtered=True))

    @property
    def fanout(self) -> FanoutEngine:
        return self._fanout

    def start(self) -> None:
        if self._owns_grpc:
            self._grpc.start()

    def stop(self, grace: float = 1.0) -> None:
        # order matters: flag the close, then the notifier close wakes
        # every stream parked at the tip (bounded — no tick to wait
        # out), so shared-listener shutdown cannot strand workers
        self._closing.set()
        self._fanout.close()
        if self._owns_grpc:
            self._grpc.stop(grace)

    # -- stream handler --------------------------------------------------

    def _make_handler(self, filtered: bool):
        form = "filtered" if filtered else "full"

        def handle(request_iter, context) -> Iterator[bytes]:
            if not self._streams.acquire(blocking=False):
                self._m_rejected.with_labels(self._channel_id).add(1)
                yield m.DeliverResponse(
                    status=m.Status.SERVICE_UNAVAILABLE).encode()
                return
            self._m_active.with_labels(self._channel_id).add(1)
            try:
                for raw in request_iter:
                    status, seek, recheck = self._check_request(
                        raw, filtered)
                    if seek is None:
                        yield m.DeliverResponse(status=status).encode()
                        return
                    stop_event = CancellationEvent()
                    context.add_callback(stop_event.set)
                    final = {"status": m.Status.SUCCESS}
                    for frame in self._frames(form, seek, stop_event,
                                              final, recheck):
                        yield frame
                    yield m.DeliverResponse(
                        status=final["status"]).encode()
            finally:
                self._streams.release()
                self._m_active.with_labels(self._channel_id).add(-1)
        return handle

    def _check_request(self, raw: bytes, filtered: bool
                       ) -> Tuple[int, Optional[m.SeekInfo],
                                  Optional[Callable[..., None]]]:
        try:
            env = m.Envelope.decode(raw)
            payload = protoutil.unmarshal_envelope_payload(env)
            ch = m.ChannelHeader.decode(payload.header.channel_header)
            sh = m.SignatureHeader.decode(payload.header.signature_header)
            seek = m.SeekInfo.decode(payload.data)
        except Exception:
            return m.Status.BAD_REQUEST, None, None
        # Only DELIVER_SEEK_INFO envelopes are seek requests: any other
        # well-signed envelope type decoding "successfully" as SeekInfo
        # is an accident of the wire format, not a request (the
        # reference's deliver handler validates the header type before
        # the payload — deliver/deliver.go).
        if ch.type != m.HeaderType.DELIVER_SEEK_INFO:
            return m.Status.BAD_REQUEST, None, None
        if ch.channel_id != self._channel_id:
            return m.Status.NOT_FOUND, None, None
        resource = "event/FilteredBlock" if filtered else "event/Block"
        sd = SignedData(data=env.payload, identity=sh.creator,
                        signature=env.signature)
        # snapshot the config sequence BEFORE the initial ACL check:
        # a config update committing between the check and the
        # snapshot would otherwise record the NEW sequence against a
        # verdict computed under the OLD config, and the session
        # re-check below would never fire for it
        seq0 = self._fanout.acl_groups.sequence()
        # the initial admission check stays PER STREAM: it is the one
        # verification of THIS stream's seek signature
        try:
            self._acl.check_acl(resource, [sd])
        except Exception:
            return m.Status.FORBIDDEN, None, None
        # the session re-check: re-evaluated against the CURRENT
        # channel bundle when the config sequence advances, forced for
        # a config block flowing through THIS stream (reference:
        # common/deliver/deliver.go:157-199 SessionAC).  Batched:
        # streams sharing (resource, creator) evaluate ONCE per
        # (group, sequence [, forced config block]) and fan the
        # verdict — the per-stream no-op-until-the-sequence-moves
        # semantics are preserved by the session handle.
        sess = self._fanout.acl_groups.join(resource, sd, seq0)
        return m.Status.SUCCESS, seek, sess.recheck

    def _frames(self, form: str, seek: m.SeekInfo,
                stop_event: CancellationEvent, final: dict,
                recheck=None) -> Iterator[bytes]:
        """BLOCK_UNTIL_READY streams wait at the tip indefinitely —
        the client's gRPC deadline/cancel (via `stop_event`) and
        server close (`_closing`) are the only terminators, so long
        event subscriptions are not silently capped (reference:
        deliver.go's commit-notified wait).  FAIL_IF_NOT_READY at a
        missing block sets final["status"]=NOT_FOUND — the retryable
        error, not an empty success.

        Frames come from the shared ring (materialized + encoded once
        per (block, form)); the tip wait parks on the CommitNotifier's
        per-stream event — woken by the notifier thread on commit, by
        the stop_event's cancellation hook, or by close, never by a
        tick.  `recheck` is the stream's batched-session handle:
        forced (keyed by block number) when a config block flows
        through THIS stream, and firing whenever the channel's config
        sequence has advanced — a revoked subscriber gets FORBIDDEN
        before the next frame, fail-closed; a standing subscription is
        not a grandfather clause."""
        engine = self._fanout
        h = self._ledger.height
        num = protoutil.seek_number(seek.start, h, newest_tip=True) or 0
        stop = protoutil.seek_number(seek.stop, h, newest_tip=False)
        engine.attach(form)
        waiter = engine.notifier.waiter()
        unhook = stop_event.on_set(waiter.cancel)
        try:
            while stop is None or num <= stop:
                if stop_event.is_set() or self._closing.is_set():
                    return
                frame = engine.get_frame(form, num)
                if frame is not None:
                    if recheck is not None:
                        try:
                            recheck(force=frame.is_config,
                                    config_mark=num)
                        except Exception:
                            final["status"] = m.Status.FORBIDDEN
                            return
                    yield frame.payload
                    num += 1
                    continue
                if seek.behavior == m.SeekBehavior.FAIL_IF_NOT_READY:
                    final["status"] = m.Status.NOT_FOUND
                    return
                if engine.notifier.wait_above(num, waiter) == "closed":
                    return
            # fallthrough: [start, stop] fully served
        finally:
            unhook()
            engine.notifier.release(waiter)
            engine.detach(form)


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------

def make_signed_seek_envelope(channel_id: str, start: int,
                              stop: Optional[int], signer,
                              behavior: Optional[int] = None
                              ) -> m.Envelope:
    """A SeekInfo envelope with a real creator + signature — the event
    service enforces ACLs, so the anonymous envelope the orderer path
    uses (orderer/server.py make_seek_envelope) is not enough."""
    stop_pos = (m.SeekPosition(specified=m.SeekSpecified(number=stop))
                if stop is not None else None)
    seek = m.SeekInfo(
        start=m.SeekPosition(specified=m.SeekSpecified(number=start)),
        stop=stop_pos,
        behavior=(m.SeekBehavior.BLOCK_UNTIL_READY
                  if behavior is None else behavior))
    ch = protoutil.make_channel_header(
        m.HeaderType.DELIVER_SEEK_INFO, channel_id)
    sh = protoutil.make_signature_header(signer.serialize(),
                                         protoutil.new_nonce())
    payload = protoutil.make_payload(ch, sh, seek.encode())
    return protoutil.sign_envelope(payload, signer)


class EventDeliverClient:
    """Client over the peer event service (the SDK-shaped consumer)."""

    def __init__(self, client: GRPCClient, channel_id: str, signer):
        self._client = client
        self._channel_id = channel_id
        self._signer = signer

    def _stream(self, method: str, start: int, stop: Optional[int],
                timeout_s: Optional[float] = None):
        env = make_signed_seek_envelope(self._channel_id, start, stop,
                                        self._signer)
        return self._client.stream_stream(SERVICE, method,
                                          iter([env.encode()]),
                                          timeout=timeout_s)

    def blocks(self, start: int = 0, stop: Optional[int] = None,
               timeout_s: Optional[float] = None) -> Iterator[m.Block]:
        for raw in self._stream("Deliver", start, stop, timeout_s):
            resp = m.DeliverResponse.decode(raw)
            if resp.block is not None:
                yield resp.block
            else:
                self._raise_unless_ok(resp.status)
                return

    def filtered_blocks(self, start: int = 0, stop: Optional[int] = None,
                        timeout_s: Optional[float] = None
                        ) -> Iterator[m.FilteredBlock]:
        for raw in self._stream("DeliverFiltered", start, stop, timeout_s):
            resp = m.DeliverResponse.decode(raw)
            if resp.filtered_block is not None:
                yield resp.filtered_block
            else:
                self._raise_unless_ok(resp.status)
                return

    @staticmethod
    def _raise_unless_ok(status: int) -> None:
        if status != m.Status.SUCCESS:
            raise EventStreamError(status)

    def wait_for_tx(self, txid: str, start: int = 0,
                    timeout_s: float = 30.0) -> int:
        """Block until `txid` appears in a committed block; return its
        TxValidationCode.  The gRPC deadline bounds the wait (the
        invoke flow: submit to ordering, then wait here for the
        commit-side verdict — reference: the SDK's commit listener
        over DeliverFiltered)."""
        import grpc
        try:
            for fb in self.filtered_blocks(start=start,
                                           timeout_s=timeout_s):
                for ftx in fb.filtered_transactions:
                    if ftx.txid == txid:
                        return ftx.tx_validation_code
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.DEADLINE_EXCEEDED:
                raise TimeoutError(
                    f"tx {txid} not committed within {timeout_s}s")
            raise
        raise TimeoutError(f"tx {txid} not seen before stream end")


class EventStreamError(Exception):
    def __init__(self, status: int):
        super().__init__(f"event deliver stream refused: status {status}")
        self.status = status
