"""L5 peer: block validation (one device batch per block), committer,
channel wiring, endorsement, chaincode runtime, deliver client, MCS."""
from fabric_mod_tpu.peer.txvalidator import (  # noqa: F401
    Committer, TxValidator, ValidationInfoProvider)
from fabric_mod_tpu.peer.channel import Channel          # noqa: F401
from fabric_mod_tpu.peer.commitpipe import (             # noqa: F401
    PipelinedCommitter, ValidatorCommitTarget, pipeline_depth)
from fabric_mod_tpu.peer.chaincode import (              # noqa: F401
    ChaincodeRegistry, ChaincodeStub, KvContract)
from fabric_mod_tpu.peer.deliverclient import DeliverClient  # noqa: F401
from fabric_mod_tpu.peer.endorser import Endorser        # noqa: F401
from fabric_mod_tpu.peer.lifecycle import (              # noqa: F401
    LifecycleContract, LifecycleValidationInfo)
from fabric_mod_tpu.peer.mcs import MessageCryptoService  # noqa: F401
