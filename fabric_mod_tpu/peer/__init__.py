"""L5 peer: block validation (one device batch per block), committer,
endorsement."""
from fabric_mod_tpu.peer.txvalidator import (  # noqa: F401
    Committer, TxValidator, ValidationInfoProvider)
