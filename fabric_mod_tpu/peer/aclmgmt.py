"""ACL management: API resource names -> channel policies.

(reference: core/aclmgmt — NewACLProvider with the resource defaults
of resources.go; CheckACL routes a resource's configured or default
policy through the policy manager.)
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

from fabric_mod_tpu.protos.protoutil import SignedData

# Default resource policy map (reference: aclmgmt/defaults —
# the peer's API surface gated by channel policies)
DEFAULT_ACLS: Dict[str, str] = {
    "peer/Propose": "/Channel/Application/Writers",
    "peer/ChaincodeToChaincode": "/Channel/Application/Writers",
    "event/Block": "/Channel/Application/Readers",
    "event/FilteredBlock": "/Channel/Application/Readers",
    "qscc/GetChainInfo": "/Channel/Application/Readers",
    "qscc/GetBlockByNumber": "/Channel/Application/Readers",
    "qscc/GetTransactionByID": "/Channel/Application/Readers",
    "cscc/GetConfigBlock": "/Channel/Application/Readers",
    "cscc/GetChannelConfig": "/Channel/Application/Readers",
    "lifecycle/CommitChaincodeDefinition":
        "/Channel/Application/Writers",
    "lifecycle/QueryChaincodeDefinition":
        "/Channel/Application/Readers",
    "discovery": "/Channel/Application/Readers",
}


class ACLError(Exception):
    pass


class ACLProvider:
    """(reference: aclmgmt.go NewACLProvider + CheckACL)"""

    def __init__(self, bundle_fn, verify_many=None,
                 overrides: Optional[Dict[str, str]] = None):
        self._bundle = bundle_fn
        self._verify_many = verify_many
        self._map = dict(DEFAULT_ACLS)
        self._map.update(overrides or {})

    def policy_for(self, resource: str) -> Optional[str]:
        return self._map.get(resource)

    def config_sequence(self) -> Optional[int]:
        """Current channel config sequence — the invalidation key for
        session-scoped ACL caches (reference: deliver.go's SessionAC
        re-evaluates when this advances)."""
        return getattr(self._bundle(), "sequence", None)

    def check_acl(self, resource: str,
                  sds: Sequence[SignedData]) -> None:
        """Raises ACLError unless the signature set satisfies the
        resource's policy (fail-closed for unknown resources)."""
        ref = self._map.get(resource)
        if ref is None:
            raise ACLError(f"no ACL policy mapped for {resource!r}")
        pol = self._bundle().policy(ref)
        if pol is None:
            raise ACLError(f"policy {ref!r} not in channel config")
        if not pol.evaluate_signed_data(sds, self._verify_many):
            raise ACLError(f"access denied for {resource!r} ({ref})")
