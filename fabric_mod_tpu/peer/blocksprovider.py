"""Deliver failover: rotate across orderer endpoints with backoff.

(reference: internal/pkg/peer/blocksprovider/blocksprovider.go
`DeliverBlocks` — the retry loop with exponential backoff at :141 —
plus internal/pkg/peer/orderers/connection.go's endpoint source.)

`FailoverDeliverSource` has the same ``blocks()`` generator contract as
the in-process DeliverService and the single-endpoint
GrpcDeliverSource, so DeliverClient stays transport-agnostic.  What it
adds:

* a LIST of orderer endpoints, tried round-robin; a stream that ends
  (disconnect, terminal status) moves to the next endpoint and re-seeks
  from the next block the caller still needs — the caller sees one
  uninterrupted, gap-free block sequence;
* exponential backoff between full rotations (every endpoint failed),
  so a fully-down ordering service costs sleep, not spin;
* `report_bad_block(n)`: the caller's verify stage (MCS) flags a block
  that failed verification; the source re-fetches from `n` on a
  DIFFERENT orderer instead of the caller halting commit forever — the
  reference's "disconnect and try another orderer" stance
  (blocksprovider.go:227 VerifyBlock error path).
"""
from __future__ import annotations

import threading
import time
from typing import Iterator, List, Optional, Sequence

from fabric_mod_tpu import faults
from fabric_mod_tpu.comm.grpc_comm import GRPCClient
from fabric_mod_tpu.observability import get_logger
from fabric_mod_tpu.orderer.server import SERVICE, make_seek_envelope
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.utils.retry import Retrier
from fabric_mod_tpu.concurrency.threads import RegisteredThread
from fabric_mod_tpu.concurrency.locks import RegisteredLock

log = get_logger("peer.blocksprovider")


class Endpoint:
    """One orderer address + its TLS material (lazy-dialed)."""

    def __init__(self, address: str,
                 server_root_pem: Optional[bytes] = None,
                 client_cert_pem: Optional[bytes] = None,
                 client_key_pem: Optional[bytes] = None,
                 override_authority: Optional[str] = None):
        self.address = address
        self._tls = (server_root_pem, client_cert_pem, client_key_pem,
                     override_authority)
        self._client: Optional[GRPCClient] = None

    def client(self) -> GRPCClient:
        if self._client is None:
            root, cert, key, auth = self._tls
            self._client = GRPCClient(self.address, server_root_pem=root,
                                      client_cert_pem=cert,
                                      client_key_pem=key,
                                      override_authority=auth)
        return self._client

    def reset(self) -> None:
        """Drop the cached channel (a dead connection must not be
        reused after its orderer restarts)."""
        if self._client is not None:
            self._client.close()
            self._client = None


class FailoverDeliverSource:
    """Multi-orderer deliver stream with rotation + backoff."""

    def __init__(self, endpoints: Sequence[Endpoint], channel_id: str,
                 base_backoff_s: float = 0.1, max_backoff_s: float = 10.0,
                 retrier: Optional[Retrier] = None):
        """`retrier` owns the between-full-rotations backoff schedule
        (jittered exponential, utils/retry.py); pass a seeded one for
        a deterministic schedule — default derives from
        base_backoff_s/max_backoff_s."""
        if not endpoints:
            raise ValueError("at least one orderer endpoint required")
        self._endpoints: List[Endpoint] = list(endpoints)
        self._channel_id = channel_id
        self._retrier = retrier if retrier is not None else Retrier(
            base_s=base_backoff_s, max_s=max_backoff_s,
            name="deliver.failover")
        self._idx = 0                      # current endpoint
        self._resume: Optional[int] = None  # set by report_bad_block
        self._lock = RegisteredLock("peer.blocksprovider._lock")
        self.rotations = 0                 # observability

    def report_bad_block(self, number: int) -> None:
        """The caller's verify stage rejected block `number`: re-fetch
        it from a different orderer (fail-closed per orderer, not
        forever)."""
        with self._lock:
            self._resume = number
        log.warning("block %d failed verification; rotating orderer",
                    number)

    def _rotate(self) -> None:
        with self._lock:
            self._endpoints[self._idx].reset()
            self._idx = (self._idx + 1) % len(self._endpoints)
            self.rotations += 1

    def current_address(self) -> str:
        with self._lock:
            return self._endpoints[self._idx].address

    def blocks(self, start: int = 0, stop: Optional[int] = None,
               stop_event: Optional[threading.Event] = None,
               timeout_s: float = 30.0) -> Iterator[m.Block]:
        """Yield blocks [start, stop] in order, failing over as needed.

        Ends only when `stop` is reached or `stop_event` fires (an
        endless peer stream passes stop=None and stops via the event).
        `timeout_s` bounds ONE quiet stream — a source that hangs
        without closing is treated as failed and rotated away from.
        """
        import grpc

        next_needed = start
        consecutive_failures = 0
        while not (stop_event is not None and stop_event.is_set()):
            if stop is not None and next_needed > stop:
                return
            ep = self._endpoints[self._idx]
            made_progress = False
            try:
                seek = make_seek_envelope(self._channel_id, next_needed,
                                          stop)
                stream = ep.client().stream_stream(
                    SERVICE, "Deliver", iter([seek.encode()]),
                    timeout=None)
                try:
                    watchdog = _StreamWatchdog(stream, timeout_s,
                                               stop_event)
                    for raw in watchdog.iterate():
                        # chaos seam: a mid-stream death of THIS
                        # endpoint (the except below rotates away)
                        faults.point("deliver.failover.stream")
                        resp = m.DeliverResponse.decode(raw)
                        if resp.block is None:
                            break          # terminal status
                        blk = resp.block
                        if blk.header.number != next_needed:
                            # gap or replay: this orderer is not
                            # serving what we asked — rotate
                            log.warning(
                                "orderer %s sent block %d, wanted %d",
                                ep.address, blk.header.number,
                                next_needed)
                            break
                        yield blk
                        # a yield only counts as PROGRESS if the
                        # caller's verify stage did not immediately
                        # reject it — otherwise N orderers all serving
                        # an unverifiable block would rotate in a hot
                        # loop with the backoff never engaging
                        with self._lock:
                            if self._resume is not None:
                                next_needed = self._resume
                                self._resume = None
                                break      # rotate below
                            next_needed = blk.header.number + 1
                        made_progress = True
                        consecutive_failures = 0
                        if stop_event is not None and stop_event.is_set():
                            return
                        if stop is not None and next_needed > stop:
                            return
                finally:
                    watchdog.abandon()
                    stream.cancel()
            except grpc.RpcError as e:
                # repr, not e.code(): an RpcError without a bound
                # code() would make the log call itself raise inside
                # the except block and kill the deliver thread this
                # handler exists to protect
                log.info("deliver stream to %s failed: %r",
                         ep.address, e)
            except Exception as e:
                # anything else a bad orderer can induce (garbage
                # frames failing DeliverResponse.decode, ...) must
                # rotate, not kill the peer's deliver thread
                log.warning("deliver stream to %s raised: %r",
                            ep.address, e)
            self._rotate()
            if not made_progress:
                consecutive_failures += 1
                if consecutive_failures >= len(self._endpoints):
                    # full rotation without progress: back off on the
                    # shared jittered-exponential schedule (the
                    # Retrier clamps the exponent, so a multi-hour
                    # outage cannot overflow the float and kill the
                    # deliver thread)
                    delay = self._retrier.delay_for(
                        consecutive_failures - len(self._endpoints))
                    if stop_event is not None:
                        if stop_event.wait(delay):
                            return
                    else:
                        time.sleep(delay)  # fmtlint: allow[clocks] -- stop_event-less caller: wall-clock backoff; the schedule itself is the injectable Retrier


class _StreamWatchdog:
    """Bounds the gap between stream messages: a stream that stalls
    longer than `timeout_s` without closing is abandoned (cancel) so
    the caller can rotate — gRPC's own keepalive only detects dead
    TCP, not a live-but-silent orderer."""

    _DONE = object()
    _POLL_S = 0.5                         # stop_event responsiveness

    def __init__(self, stream, timeout_s: float,
                 stop_event: Optional[threading.Event]):
        self._stream = stream
        self._timeout = timeout_s
        self._stop_event = stop_event
        self._abandoned = threading.Event()

    def abandon(self) -> None:
        """Unblock the pump thread (it must never stay parked in
        q.put after the consumer walks away — that would leak one
        thread per rotation)."""
        self._abandoned.set()

    def iterate(self):
        import queue as _queue
        q: "_queue.Queue" = _queue.Queue(8)

        def pump():
            try:
                for item in self._stream:
                    while not self._abandoned.is_set():
                        try:
                            q.put(item, timeout=0.5)
                            break
                        except _queue.Full:
                            continue
                    if self._abandoned.is_set():
                        return
            except Exception as e:
                log.debug("watchdog pump exiting: %r", e)
            while not self._abandoned.is_set():
                try:
                    q.put(self._DONE, timeout=0.5)
                    return
                except _queue.Full:
                    continue

        t = RegisteredThread(target=pump, name="deliver-pump",
                             structure="peer.blocksprovider")
        t.start()
        try:
            waited = 0.0
            while True:
                # short polls so a stop_event (peer shutdown) is seen
                # within _POLL_S even under a very long idle timeout
                try:
                    item = q.get(timeout=min(self._POLL_S,
                                             self._timeout))
                except _queue.Empty:
                    if (self._stop_event is not None
                            and self._stop_event.is_set()):
                        self._stream.cancel()
                        return
                    waited += self._POLL_S
                    if waited >= self._timeout:
                        self._stream.cancel()  # silent stream: abandon
                        return
                    continue
                if item is self._DONE:
                    return
                waited = 0.0
                yield item
                if (self._stop_event is not None
                        and self._stop_event.is_set()):
                    self._stream.cancel()
                    return
        finally:
            self.abandon()
