"""Chaincode lifecycle: org approvals + committed definitions.

(reference: core/chaincode/lifecycle — the `_lifecycle` system
chaincode: ApproveChaincodeDefinitionForMyOrg + CheckCommitReadiness +
CommitChaincodeDefinition at scc.go:911, approval bookkeeping at
lifecycle.go:770; committed definitions feed the plugin dispatcher
(plugindispatcher/dispatcher.go:102) per namespace.)

The governance ceremony: each org APPROVES the exact definition
parameters (a digest of version/sequence/policy/collections recorded
under `approvals/<cc>/<seq>/<mspid>`); COMMIT succeeds only when the
approvals of a MAJORITY of the channel's application orgs match the
committed parameters — the multi-party upgrade gate the reference
enforces through the LifecycleEndorsement policy.

Validation subtlety mirrored from the reference: an APPROVE tx is an
org-local act — it is endorsed by ONE org and validated against that
org's own Endorsement policy (the reference stores approvals in the
org's implicit collection, validated org-locally).  Commit and every
other `_lifecycle` write validate against LifecycleEndorsement.
`LifecycleValidationInfo.validation_info_for_writes` implements the
split by inspecting the tx's written keys.

A definition lives in the `_lifecycle` state namespace under
`namespaces/<cc>`; because it arrives via an ordinary endorsed tx, it
is governed, ordered, MVCC-checked, and visible to validation for all
SUBSEQUENT blocks — the lifecycle cache of the reference without the
cache (state reads are cheap here).
"""
from __future__ import annotations

import hashlib
import json
import re
from typing import Callable, List, Optional, Tuple

from fabric_mod_tpu.peer.chaincode import ChaincodeError, ChaincodeStub
from fabric_mod_tpu.protos import messages as m

LIFECYCLE_NS = "_lifecycle"

_APPROVAL_RE = re.compile(r"^approvals/([^/]+)/(\d+)/([^/]+)$")


def definition_key(cc_name: str) -> str:
    return f"namespaces/{cc_name}"


def approval_key(cc_name: str, sequence: int, mspid: str) -> str:
    return f"approvals/{cc_name}/{sequence}/{mspid}"


def _param_digest(version: str, sequence: int, policy: bytes,
                  collections: bytes, plugin: str) -> bytes:
    """Approvals bind to the EXACT definition parameters — including
    the validation plugin: an org that approved (v1, policyA, vscc)
    has not approved (v1, policyA, some-permissive-plugin) (reference:
    the ValidationParameter digest covers the plugin)."""
    h = hashlib.sha256()
    for part in (version.encode(), str(sequence).encode(), policy,
                 collections, plugin.encode()):
        h.update(len(part).to_bytes(4, "big"))
        h.update(part)
    return h.digest()


class LifecycleContract:
    """The `_lifecycle` system chaincode.

    args [op, ...]; ops:
      approve(name, version, sequence, policy, collections) — record
        THIS org's approval (org = tx creator's MSP);
      checkcommitreadiness(name, version, sequence, policy,
        collections) -> JSON {org: approved};
      commit(name, version, sequence, policy, collections) — requires
        matching approvals from a majority of channel orgs;
      queryapproved(name, sequence) -> creator org's approval digest;
      query(name) -> committed definition bytes.

    `channel_orgs`: () -> [mspid] of the channel's application orgs
    (wired from the channel bundle).  Without it the contract runs in
    single-step dev mode: commit needs no approvals (in-process tools
    and bare unit tests)."""

    def __init__(self, channel_orgs: Optional[Callable[[], List[str]]]
                 = None):
        self._channel_orgs = channel_orgs

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _def_args(stub: ChaincodeStub):
        name = stub.args[1].decode()
        version = stub.args[2].decode()
        sequence = int(stub.args[3].decode())
        policy = stub.args[4] if len(stub.args) > 4 else b""
        collections = stub.args[5] if len(stub.args) > 5 else b""
        plugin = (stub.args[6].decode()
                  if len(stub.args) > 6 and stub.args[6] else "vscc")
        if collections:                     # must decode as a package
            m.CollectionConfigPackage.decode(collections)
        if "/" in name:
            raise ChaincodeError(f"invalid chaincode name {name!r}")
        return name, version, sequence, policy, collections, plugin

    def _check_sequence(self, stub: ChaincodeStub, name: str,
                        sequence: int) -> None:
        prev = stub.get_state(definition_key(name))
        prev_seq = (m.ChaincodeDefinition.decode(prev).sequence
                    if prev else 0)
        if sequence != prev_seq + 1:
            raise ChaincodeError(
                f"definition sequence {sequence} != expected "
                f"{prev_seq + 1}")

    def _approvals(self, stub: ChaincodeStub, name: str, sequence: int,
                   digest: bytes):
        """{org: approved_matching} over the channel's orgs."""
        orgs = list(self._channel_orgs()) if self._channel_orgs else []
        out = {}
        for org in orgs:
            got = stub.get_state(approval_key(name, sequence, org))
            out[org] = bool(got) and got == digest
        return out

    # -- dispatch ------------------------------------------------------------
    def invoke(self, stub: ChaincodeStub) -> bytes:
        if not stub.args:
            raise ChaincodeError("no args")
        op = stub.args[0].decode()

        if op == "approve":
            # (reference: ApproveChaincodeDefinitionForMyOrg) — the
            # approving org is the tx CREATOR's org; the key embeds it
            # so one org can never write another org's approval, and
            # validation pins this tx to that org's Endorsement policy
            name, version, sequence, policy, collections, plugin = \
                self._def_args(stub)
            mspid = stub.creator_mspid()
            if not mspid:
                raise ChaincodeError("approve: no creator identity")
            # a late org may approve the CURRENTLY COMMITTED sequence
            # to catch up (reference: ApproveChaincodeDefinitionForMyOrg
            # accepts currentSequence when the parameters match the
            # committed definition); anything else must be committed+1
            prev = stub.get_state(definition_key(name))
            prev_seq = (m.ChaincodeDefinition.decode(prev).sequence
                        if prev else 0)
            if prev and sequence == prev_seq:
                d = m.ChaincodeDefinition.decode(prev)
                if (d.version != version
                        or d.endorsement_policy != policy
                        or d.validation_plugin != plugin
                        or d.collections != collections):
                    raise ChaincodeError(
                        f"approve for committed sequence {sequence} "
                        f"must match the committed definition")
            else:
                self._check_sequence(stub, name, sequence)
            stub.put_state(
                approval_key(name, sequence, mspid),
                _param_digest(version, sequence, policy, collections,
                              plugin))
            return b"ok"

        if op == "checkcommitreadiness":
            # (reference: CheckCommitReadiness, scc.go)
            name, version, sequence, policy, collections, plugin = \
                self._def_args(stub)
            digest = _param_digest(version, sequence, policy,
                                   collections, plugin)
            ready = self._approvals(stub, name, sequence, digest)
            return json.dumps(ready, sort_keys=True).encode()

        if op == "queryapproved":
            # (reference: QueryApprovedChaincodeDefinition)
            name = stub.args[1].decode()
            sequence = int(stub.args[2].decode())
            mspid = stub.creator_mspid()
            got = stub.get_state(approval_key(name, sequence, mspid))
            return got.hex().encode() if got else b""

        if op == "commit":
            name, version, sequence, policy, collections, plugin = \
                self._def_args(stub)
            self._check_sequence(stub, name, sequence)
            if self._channel_orgs is not None:
                digest = _param_digest(version, sequence, policy,
                                       collections, plugin)
                ready = self._approvals(stub, name, sequence, digest)
                yes = sum(ready.values())
                # MAJORITY of application orgs (the channel default
                # LifecycleEndorsement rule)
                if not ready:
                    # zero orgs: need would be 1-of-0, unsatisfiable —
                    # fail with the real cause instead
                    raise ChaincodeError(
                        "commit: channel has no application orgs to "
                        "approve definitions")
                need = len(ready) // 2 + 1
                if yes < need:
                    raise ChaincodeError(
                        f"commit of {name!r} sequence {sequence}: "
                        f"approvals {yes}/{len(ready)} "
                        f"(need {need}): {ready}")
            d = m.ChaincodeDefinition(
                sequence=sequence, version=version,
                endorsement_policy=policy, validation_plugin=plugin,
                collections=collections)
            stub.put_state(definition_key(name), d.encode())
            return b"ok"

        if op == "query":
            raw = stub.get_state(definition_key(stub.args[1].decode()))
            return raw if raw is not None else b""
        raise ChaincodeError(f"unknown lifecycle op {op!r}")


class LifecycleValidationInfo:
    """Namespace -> (plugin, policy) from committed definitions
    (reference: plugindispatcher dispatcher.go:102 + the lifecycle
    ValidatorCommitter).  Falls back to the channel default policy for
    undefined namespaces — and for `_lifecycle` itself, which is
    governed by /Channel/Application/LifecycleEndorsement, EXCEPT
    org-local approval writes, which validate against that single
    org's Endorsement policy (the reference's implicit-collection
    validation split)."""

    def __init__(self, state_get: Callable[[str, str], Optional[bytes]],
                 default_policy: bytes,
                 lifecycle_policy: Optional[bytes] = None):
        self._state_get = state_get
        self._default = default_policy
        self._lifecycle_policy = lifecycle_policy or m.ApplicationPolicy(
            channel_config_policy_reference=
            "/Channel/Application/LifecycleEndorsement").encode()

    def validation_info(self, ns: str) -> Tuple[str, bytes]:
        if ns == LIFECYCLE_NS:
            return "vscc", self._lifecycle_policy
        raw = self._state_get(LIFECYCLE_NS, definition_key(ns))
        if raw:
            try:
                d = m.ChaincodeDefinition.decode(raw)
                if d.endorsement_policy:
                    return (d.validation_plugin or "vscc",
                            d.endorsement_policy)
            except Exception:  # fmtlint: allow[swallowed-exceptions] -- malformed on-ledger definition: fall through to the default vscc policy (the reference does the same)
                pass
        return "vscc", self._default

    def validation_info_for_writes(self, ns: str,
                                   written_keys: List[str]
                                   ) -> Tuple[str, bytes]:
        """Write-aware variant: a `_lifecycle` tx whose writes are ALL
        one single org's approval keys is that org's local act and
        validates against /Channel/Application/<org>/Endorsement."""
        if ns == LIFECYCLE_NS and written_keys:
            orgs = set()
            for key in written_keys:
                got = _APPROVAL_RE.match(key)
                if got is None:
                    orgs = None
                    break
                orgs.add(got.group(3))
            if orgs is not None and len(orgs) == 1:
                org = orgs.pop()
                return "vscc", m.ApplicationPolicy(
                    channel_config_policy_reference=
                    f"/Channel/Application/{org}/Endorsement").encode()
        return self.validation_info(ns)
