"""Chaincode lifecycle: committed definitions drive validation info.

(reference: core/chaincode/lifecycle — the `_lifecycle` system
chaincode (scc.go:911) whose committed definitions the plugin
dispatcher resolves per namespace (plugindispatcher/dispatcher.go:102,
deployedcc_infoprovider.go ValidationInfo).  The approve/commit
two-step collapses to one `commit` op here; the org-approval policy
gate is the channel's LifecycleEndorsement policy enforced by the
normal endorsement path, exactly like the reference.)

A definition lives in the `_lifecycle` state namespace under
`namespaces/<cc>`; because it arrives via an ordinary endorsed tx, it
is governed, ordered, MVCC-checked, and visible to validation for all
SUBSEQUENT blocks — the lifecycle cache of the reference without the
cache (state reads are cheap here).
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

from fabric_mod_tpu.peer.chaincode import ChaincodeError, ChaincodeStub
from fabric_mod_tpu.protos import messages as m

LIFECYCLE_NS = "_lifecycle"


def definition_key(cc_name: str) -> str:
    return f"namespaces/{cc_name}"


class LifecycleContract:
    """The `_lifecycle` system chaincode: args
    [op, name, ...]; ops: commit(name, version, sequence,
    endorsement_policy_bytes), query(name)."""

    def invoke(self, stub: ChaincodeStub) -> bytes:
        if not stub.args:
            raise ChaincodeError("no args")
        op = stub.args[0].decode()
        if op == "commit":
            name = stub.args[1].decode()
            version = stub.args[2].decode()
            sequence = int(stub.args[3].decode())
            policy = stub.args[4] if len(stub.args) > 4 else b""
            collections = stub.args[5] if len(stub.args) > 5 else b""
            if collections:                 # must decode as a package
                m.CollectionConfigPackage.decode(collections)
            prev = stub.get_state(definition_key(name))
            prev_seq = (m.ChaincodeDefinition.decode(prev).sequence
                        if prev else 0)
            if sequence != prev_seq + 1:
                raise ChaincodeError(
                    f"definition sequence {sequence} != expected "
                    f"{prev_seq + 1}")
            d = m.ChaincodeDefinition(
                sequence=sequence, version=version,
                endorsement_policy=policy, validation_plugin="vscc",
                collections=collections)
            stub.put_state(definition_key(name), d.encode())
            return b"ok"
        if op == "query":
            raw = stub.get_state(definition_key(stub.args[1].decode()))
            return raw if raw is not None else b""
        raise ChaincodeError(f"unknown lifecycle op {op!r}")


class LifecycleValidationInfo:
    """Namespace -> (plugin, policy) from committed definitions
    (reference: plugindispatcher dispatcher.go:102 + the lifecycle
    ValidatorCommitter).  Falls back to the channel default policy for
    undefined namespaces — and for `_lifecycle` itself, which is
    governed by /Channel/Application/LifecycleEndorsement."""

    def __init__(self, state_get: Callable[[str, str], Optional[bytes]],
                 default_policy: bytes,
                 lifecycle_policy: Optional[bytes] = None):
        self._state_get = state_get
        self._default = default_policy
        self._lifecycle_policy = lifecycle_policy or m.ApplicationPolicy(
            channel_config_policy_reference=
            "/Channel/Application/LifecycleEndorsement").encode()

    def validation_info(self, ns: str) -> Tuple[str, bytes]:
        if ns == LIFECYCLE_NS:
            return "vscc", self._lifecycle_policy
        raw = self._state_get(LIFECYCLE_NS, definition_key(ns))
        if raw:
            try:
                d = m.ChaincodeDefinition.decode(raw)
                if d.endorsement_policy:
                    return (d.validation_plugin or "vscc",
                            d.endorsement_policy)
            except Exception:
                pass                        # fall through to default
        return "vscc", self._default
