"""Chaincode language platforms registry.

(reference: core/chaincode/platforms/platforms.go:62 Registry — one
Platform per language (golang/java/node), selected by the package's
type metadata, each owning validate/build for its language; the peer
consults the registry before anything else.  platforms.go:198 is the
build dispatch this module's `PlatformRegistry.build_for` mirrors.)

The TPU-native runtime's languages differ from the reference's — the
in-process unit is a Python contract, the out-of-process unit is the
CCaaS dial-out or a launched executable — but the SHAPE is the same:
a registry of named platforms keyed by the package `type`, each
owning detection and build for its language, with external builders
(`extbuilder.py`) as the fallback for types no platform claims
(exactly the reference's externalbuilder-before-docker ordering,
inverted: here platforms are consulted first, external builders
second, and there is no docker tier — see README waivers).
"""
from __future__ import annotations

import json
import os
import shutil
import stat
import subprocess
import sys
import tempfile
import time
from typing import List, Optional


from fabric_mod_tpu.peer.extbuilder import ExternalBuilderError


class PlatformError(ExternalBuilderError):
    """Subclass of ExternalBuilderError so launcher callers keep one
    failure surface across platforms and external builders."""


class PythonPlatform:
    """In-process contracts: the code payload is a module defining
    `contract` (or a callable `invoke`) — the runtime's native unit."""

    name = "python"

    def handles(self, cc_type: str) -> bool:
        return cc_type == "python"

    def build(self, label: str, code: bytes, ctx: "LaunchContext"):
        from fabric_mod_tpu.peer.chaincode import FuncContract
        ns = {}
        exec(compile(code, f"<chaincode {label}>", "exec"), ns)
        contract = ns.get("contract")
        if contract is None and callable(ns.get("invoke")):
            contract = FuncContract(ns["invoke"])
        if contract is None:
            raise PlatformError(
                f"package {label}: defines no `contract`")
        return contract


class CCaaSPlatform:
    """Chaincode-as-a-service: the payload is connection.json; the
    peer dials the already-running server (reference: the ccaas
    external builder shipped with the reference)."""

    name = "ccaas"

    def handles(self, cc_type: str) -> bool:
        return cc_type == "ccaas"

    def build(self, label: str, code: bytes, ctx: "LaunchContext"):
        from fabric_mod_tpu.peer.extbuilder import ExternalContract
        try:
            conn = json.loads(code)
        except Exception as e:
            raise PlatformError(
                f"package {label}: bad connection.json: {e}") from e
        return ExternalContract(conn)


class ScriptPlatform:
    """Generic script language: the payload is an executable script
    (shebang or python) launched as its own OS process; it must speak
    the chaincode-server protocol and publish its listen address —
    NEWLINE-TERMINATED — to the path given in its run metadata (the
    newline marks write completion; write-to-temp-then-rename also
    works).  Same contract as an external builder's bin/run (the
    reference's per-language build+launch collapsed to one runnable
    artifact)."""

    name = "script"

    def handles(self, cc_type: str) -> bool:
        return cc_type in ("script", "binary")

    def build(self, label: str, code: bytes, ctx: "LaunchContext"):
        work = tempfile.mkdtemp(prefix=f"ccscript-{label}-")
        try:
            return self._launch(label, code, ctx, work)
        except BaseException:
            # failed build: reap the workdir (nothing dials into it);
            # on success it must persist — the script runs from it
            shutil.rmtree(work, ignore_errors=True)
            raise

    def _launch(self, label: str, code: bytes, ctx: "LaunchContext",
                work: str):
        from fabric_mod_tpu.peer.extbuilder import ExternalContract
        script = os.path.join(work, "chaincode")
        with open(script, "wb") as f:
            f.write(code)
        os.chmod(script, os.stat(script).st_mode | stat.S_IXUSR)
        addr_file = os.path.join(work, "address")
        meta_path = os.path.join(work, "chaincode.json")
        with open(meta_path, "w") as f:
            json.dump({"address_file": addr_file}, f)
        if code.startswith(b"#!"):
            cmd = [script, meta_path]
        else:
            # no shebang: treat as python source (the common case on
            # this runtime; a compiled binary would carry no shebang
            # but also not parse as text — operators label those
            # "binary" and ship a shebang'd wrapper)
            cmd = [sys.executable, script, meta_path]
        proc = subprocess.Popen(cmd, cwd=work)
        ctx.track(proc)
        deadline = time.monotonic() + ctx.launch_timeout_s
        while time.monotonic() < deadline:
            if os.path.exists(addr_file):
                # The publish contract REQUIRES a newline-terminated
                # address: existence of the file is not completion of
                # the write (a non-atomic writer can be caught
                # mid-write and we would dial a truncated address).
                # Retry until the trailing newline lands.  NOTE this
                # binds atomic-rename writers too — their content must
                # also end with "\n" (the newline is the completion
                # marker, rename or not).
                raw = open(addr_file).read()
                if raw.endswith("\n") and raw.strip():
                    return ExternalContract({"address": raw.strip()})
            if proc.poll() is not None:
                raise PlatformError(
                    f"package {label}: script exited rc="
                    f"{proc.returncode} before publishing an address")
            time.sleep(0.05)
        proc.kill()
        proc.wait(timeout=5)
        raise PlatformError(
            f"package {label}: script never published an address "
            f"(the address file must be newline-terminated)")


class LaunchContext:
    """What a platform may ask of the launcher: process tracking (so
    close() reaps) and the launch timeout."""

    def __init__(self, track, launch_timeout_s: float = 30.0):
        self.track = track
        self.launch_timeout_s = launch_timeout_s


class PlatformRegistry:
    """(reference: platforms.go:62 NewRegistry + :198 the per-type
    dispatch).  Ordered; first platform claiming the type wins; None
    when no platform claims it (caller falls back to the external
    builders)."""

    def __init__(self, platforms: Optional[List] = None):
        self._platforms = (list(platforms) if platforms is not None
                           else [PythonPlatform(), CCaaSPlatform(),
                                 ScriptPlatform()])

    def register(self, platform) -> None:
        self._platforms.append(platform)

    def platform_for(self, cc_type: str):
        for p in self._platforms:
            if p.handles(cc_type):
                return p
        return None

    def build_for(self, label: str, cc_type: str, code: bytes,
                  ctx: LaunchContext):
        """Build via the claiming platform, or None if unclaimed."""
        p = self.platform_for(cc_type)
        if p is None:
            return None
        return p.build(label, code, ctx)
