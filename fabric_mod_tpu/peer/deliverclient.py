"""Peer deliver client: pull ordered blocks, verify, commit — pipelined.

(reference: internal/pkg/peer/blocksprovider/blocksprovider.go
`DeliverBlocks` — the pull loop with `VerifyBlock` at :227 — feeding
gossip/state/state.go:583's `deliverPayloads` commit loop through the
in-order payload buffer.)

Three pipeline stages — the double buffer of SURVEY §2.9 row 2:

  stage 1 (this thread / `run`):   pull block N+2, hash-check + verify
                                   its orderer signature
  stage 2 (pipeline stage loop):   host unpack + policy staging of
                                   block N+1, then DISPATCH its device
                                   verify batch without awaiting it
  stage 3 (pipeline commit loop):  await block N's device verdicts,
                                   resolve flags, MVCC + commit

Stages 2+3 are peer/commitpipe.PipelinedCommitter — the shared
commit-pipeline engine (bounded depth, `needs_barrier` drains,
per-stage histograms); this client owns stage 1 and the MCS gate.
Block N+1's host unmarshalling overlaps block N's device execution:
the device batch is in flight between stage 2's dispatch and stage
3's resolve.  Commit order is block-number order by construction
(single puller).  Staging must not run ahead of a block that changes
what staging reads — config txs, VALIDATION_PARAMETER writes,
lifecycle definitions — so such blocks set `needs_barrier` and the
engine waits for their commit before staging the next block (the
reference's serialization points: validator.go:400 config,
validator_keylevel.go waits).
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional

from fabric_mod_tpu.concurrency import CancellationEvent, OwnedState
from fabric_mod_tpu.observability import tracing
from fabric_mod_tpu.peer.channel import Channel
from fabric_mod_tpu.peer.commitpipe import PipelinedCommitter, pipeline_depth
from fabric_mod_tpu.peer.mcs import BlockVerificationError
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.observability.logging import get_logger

log = get_logger("peer.deliverclient")


class DeliverDisconnected(Exception):
    """The deliver stream died mid-pull (source raised) in a
    single-endpoint (non-failover) configuration.

    Typed, and carries `height` — the last committed ledger height —
    so a supervisor can resume a fresh client from exactly the next
    needed block instead of parsing a bare transport exception.  A
    FailoverDeliverSource never surfaces this: it rotates to another
    orderer internally (reference: blocksprovider.go:141/:227 — the
    retry path this error marks the absence of)."""

    def __init__(self, msg: str, height: Optional[int] = None):
        super().__init__(msg)
        self.height = height


class DeliverClient:
    """Pulls blocks from a deliver source into a channel's commit path.

    `source` must provide `blocks(start, stop=None, stop_event=None,
    timeout_s=...)` — the in-process DeliverService now, the gRPC
    deliver stream later (same generator shape).
    """

    def __init__(self, channel: Channel, source,
                 queue_size: int = 8,
                 on_error: Optional[Callable[[Exception], None]] = None,
                 on_commit: Optional[Callable[[m.Block], None]] = None,
                 depth: Optional[int] = None):
        """`on_commit(block)` fires after each commit — the gossip
        service uses it to fan committed blocks out to non-leader
        peers (reference: the leader's gossip of deliver payloads).
        `depth` bounds staged-but-uncommitted blocks; default: the
        FABRIC_MOD_TPU_COMMIT_PIPELINE knob, else 2 (the double
        buffer this client has always run)."""
        self._channel = channel
        self._source = source
        self._on_commit = on_commit
        # CancellationEvent so an in-process DeliverService tip wait
        # parks tickless: stop() both flags the loop AND (via the
        # service's on_set hook) notifies the writer's condition
        self._stop = CancellationEvent()
        self._depth = depth if depth is not None else \
            (pipeline_depth() or 2)
        self._queue_size = queue_size
        self._on_error = on_error
        # stage/commit seconds of pipes already closed (run() builds a
        # fresh engine per invocation — the client is reusable)
        self._secs_base = [0.0, 0.0, 0.0]  # stage, await, commit
        self._pipe = self._make_pipe()
        self.rejected: List[int] = []      # block numbers that failed MCS
        # stage-1 exclusivity: run() claims this state for its thread;
        # a SECOND concurrent run() on one client would double-pull
        # and double-submit — under FMT_RACECHECK the second claim
        # raises instead (sequential re-runs re-claim freely)
        self._runner = OwnedState("deliverclient-runner")

    def _make_pipe(self) -> PipelinedCommitter:
        def fail(e: Exception) -> None:
            # stop the pull promptly: the source generator honors the
            # stop event, so a dead pipeline doesn't pull until idle
            self._stop.set()
            if self._on_error is not None:
                self._on_error(e)

        return PipelinedCommitter(
            self._channel, depth=self._depth,
            in_queue=self._queue_size,
            on_commit=self._handle_commit, on_error=fail,
            consumer="deliver")

    def _handle_commit(self, block: m.Block, _flags) -> None:
        if self._on_commit is not None:
            try:
                self._on_commit(block)
            except Exception as e:         # gossip fan-out is advisory
                log.debug("gossip fan-out for block %d raised: "
                          "%r", block.header.number, e)

    # cumulative wall seconds per stage (the e2e bench reports these
    # to show the verify-vs-commit overlap); commit_secs keeps the old
    # meaning — everything after dispatch: verdict await + resolve +
    # MVCC + ledger commit
    @property
    def stage_secs(self) -> float:
        return self._secs_base[0] + self._pipe.stage_secs

    @property
    def await_secs(self) -> float:
        return self._secs_base[1] + self._pipe.await_secs

    @property
    def commit_secs(self) -> float:
        return (self._secs_base[1] + self._secs_base[2]
                + self._pipe.await_secs + self._pipe.commit_secs)

    # -- stage 1: pull + verify ------------------------------------------
    def run(self, stop_at: Optional[int] = None,
            idle_timeout_s: float = 30.0) -> None:
        """Pull from the ledger's current height until `stop_at` (block
        number, inclusive) or the source goes idle.  Blocking; callers
        wanting a background client wrap this in a thread.  One run()
        at a time: a concurrent second run() is a race (double pull,
        interleaved submits) and is rejected under FMT_RACECHECK."""
        self._runner.claim()
        try:
            self._run_claimed(stop_at, idle_timeout_s)
        finally:
            # released on EVERY exit (including a raise before or
            # inside the pull loop, or from pipe.close) — a leaked
            # claim would turn every later run() into a false race
            self._runner.release()
        if self._pipe.error is not None:
            raise self._pipe.error

    def _run_claimed(self, stop_at: Optional[int],
                     idle_timeout_s: float) -> None:
        if self._pipe.closed:
            # reusable client (the pre-engine contract): each run()
            # gets fresh workers; prior runs' timings accumulate
            self._secs_base[0] += self._pipe.stage_secs
            self._secs_base[1] += self._pipe.await_secs
            self._secs_base[2] += self._pipe.commit_secs
            self._pipe = self._make_pipe()
            self._stop.clear()
        start = self._channel.ledger.height
        prev_hash = None
        if start > 0:
            prev = self._channel.ledger.get_block_by_number(start - 1)
            prev_hash = protoutil.block_header_hash(prev.header)
        dropped: Optional[BaseException] = None
        try:
            source_iter = iter(self._source.blocks(
                start, stop=stop_at, stop_event=self._stop,
                timeout_s=idle_timeout_s))
            while True:
                # "recv" attributes stage 1: the pull wait + the MCS
                # hash/signature check, per block (the part of the
                # wall the commit pipeline can never hide)
                with tracing.span("recv") as recv_span:
                    try:
                        block = next(source_iter)
                    except StopIteration:
                        break              # clean end / idle timeout
                    except Exception as e:
                        # dropped stream, single-endpoint mode:
                        # surface a TYPED error with the resume point,
                        # not a bare transport exception (a failover
                        # source handles this internally and never
                        # raises here).  Raised AFTER the finally
                        # drains the pipe, so the carried height
                        # includes every in-flight commit — it IS the
                        # next run()'s re-seek point.
                        dropped = e
                        break
                    if self._stop.is_set():
                        break
                    recv_span.set(block=block.header.number)
                    try:
                        self._channel.mcs.verify_block(
                            self._channel.channel_id, block,
                            expected_prev_hash=prev_hash)
                    except BlockVerificationError:
                        # tampered/mis-signed block: drop it, never
                        # commit.  With a failover source, ask it to
                        # re-fetch this block from a DIFFERENT orderer
                        # and keep pulling (reference:
                        # blocksprovider.go:227 — disconnect and retry
                        # another orderer); a single-endpoint source
                        # fails closed by stopping.
                        self.rejected.append(block.header.number)
                        del self.rejected[:-1000]  # bounded memory
                        report = getattr(self._source,
                                         "report_bad_block", None)
                        if report is not None:
                            report(block.header.number)
                            continue
                        break
                prev_hash = protoutil.block_header_hash(block.header)
                try:
                    self._pipe.submit(block)
                except Exception:
                    if self._pipe.error is None:
                        raise              # not a pipeline failure
                    break                  # re-raised after close below
        finally:
            # unbounded join (the pre-engine contract): run() never
            # returns with commits silently in flight, however long
            # the tail block's cold XLA compile takes
            self._pipe.close()
        if dropped is not None:
            height = self._channel.ledger.height
            if isinstance(dropped, DeliverDisconnected):
                if dropped.height is None:
                    dropped.height = height
                raise dropped
            raise DeliverDisconnected(
                f"deliver stream dropped at height {height}: "
                f"{dropped!r}", height=height) from dropped

    def stop(self) -> None:
        self._stop.set()

    def wait_for_height(self, height: int, timeout_s: float = 30.0) -> bool:
        """Block until `height` blocks are committed.  Re-reads the
        pipe each slice: a reused client swaps in a fresh engine per
        run(), and a waiter must follow it rather than watch a closed
        pipe whose height never advances."""
        import time
        deadline = time.monotonic() + timeout_s
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                return False
            try:
                if self._pipe.wait_height(height, min(left, 1.0)):
                    return True
            except Exception:
                return False
