"""Peer deliver client: pull ordered blocks, verify, commit — pipelined.

(reference: internal/pkg/peer/blocksprovider/blocksprovider.go
`DeliverBlocks` — the pull loop with `VerifyBlock` at :227 — feeding
gossip/state/state.go:583's `deliverPayloads` commit loop through the
in-order payload buffer.)

Three pipeline stages — the double buffer of SURVEY §2.9 row 2:

  stage 1 (this thread / `run`):   pull block N+2, hash-check + verify
                                   its orderer signature
  stage 2 (stage worker thread):   host unpack + policy staging of
                                   block N+1, then DISPATCH its device
                                   verify batch without awaiting it
  stage 3 (commit worker thread):  await block N's device verdicts,
                                   resolve flags, MVCC + commit

Block N+1's host unmarshalling overlaps block N's device execution:
the device batch is in flight between stage 2's dispatch and stage
3's resolve.  Bounded in-order queues between stages are the payload
buffer; commit order is block-number order by construction (single
puller).  Staging must not run ahead of a block that changes what
staging reads — config txs, VALIDATION_PARAMETER writes, lifecycle
definitions — so such blocks set `needs_barrier` and stage 2 waits
for their commit before staging the next block (the reference's
serialization points: validator.go:400 config, validator_keylevel.go
waits).
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, List, Optional

from fabric_mod_tpu.peer.channel import Channel
from fabric_mod_tpu.peer.mcs import BlockVerificationError
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil


class DeliverClient:
    """Pulls blocks from a deliver source into a channel's commit path.

    `source` must provide `blocks(start, stop=None, stop_event=None,
    timeout_s=...)` — the in-process DeliverService now, the gRPC
    deliver stream later (same generator shape).
    """

    def __init__(self, channel: Channel, source,
                 queue_size: int = 8,
                 on_error: Optional[Callable[[Exception], None]] = None,
                 on_commit: Optional[Callable[[m.Block], None]] = None):
        """`on_commit(block)` fires after each commit — the gossip
        service uses it to fan committed blocks out to non-leader
        peers (reference: the leader's gossip of deliver payloads)."""
        self._channel = channel
        self._source = source
        self._q: "queue.Queue[Optional[m.Block]]" = queue.Queue(queue_size)
        # staged (dispatched, unresolved) blocks; small: each entry
        # holds a device batch in flight — 2 is the double buffer
        self._staged_q: "queue.Queue" = queue.Queue(2)
        self._stop = threading.Event()
        self._on_error = on_error
        self._on_commit = on_commit
        self.rejected: List[int] = []      # block numbers that failed MCS
        # cumulative wall seconds per stage (the e2e bench reports
        # these to show the verify-vs-commit overlap)
        self.stage_secs = 0.0
        self.commit_secs = 0.0
        self._commit_err: Optional[Exception] = None
        self._committed = threading.Condition()
        self._height = channel.ledger.height

    def _fail(self, e: Exception) -> None:
        self._commit_err = e
        self._stop.set()
        if self._on_error is not None:
            self._on_error(e)

    # -- stage 2: host unpack + device dispatch --------------------------
    def _stage_loop(self) -> None:
        import time as _time
        try:
            while True:
                block = self._q.get()
                if block is None:
                    return
                t0 = _time.perf_counter()
                staged = self._channel.stage_block(block)
                self.stage_secs += _time.perf_counter() - t0
                barrier = staged.needs_barrier
                self._staged_q.put(staged)
                if barrier:
                    # this block changes state that staging reads:
                    # wait for its commit before staging the next one
                    want = block.header.number + 1
                    with self._committed:
                        while (self._height < want
                               and not self._stop.is_set()
                               and self._commit_err is None):
                            self._committed.wait(timeout=0.5)
        except Exception as e:
            self._fail(e)
            # keep draining so the puller's bounded put never deadlocks
            while self._q.get() is not None:
                pass
        finally:
            self._staged_q.put(None)

    # -- stage 3: the commit worker --------------------------------------
    def _commit_loop(self) -> None:
        import time as _time
        while True:
            staged = self._staged_q.get()
            if staged is None:
                return
            try:
                t0 = _time.perf_counter()
                self._channel.commit_staged(staged)
                self.commit_secs += _time.perf_counter() - t0
            except Exception as e:
                self._fail(e)
                # drain so the stage worker's bounded put never blocks
                while self._staged_q.get() is not None:
                    pass
                return
            block = staged.block
            with self._committed:
                self._height = block.header.number + 1
                self._committed.notify_all()
            if self._on_commit is not None:
                try:
                    self._on_commit(block)
                except Exception:          # gossip fan-out is advisory
                    pass

    # -- stage 1: pull + verify ------------------------------------------
    def run(self, stop_at: Optional[int] = None,
            idle_timeout_s: float = 30.0) -> None:
        """Pull from the ledger's current height until `stop_at` (block
        number, inclusive) or the source goes idle.  Blocking; callers
        wanting a background client wrap this in a thread."""
        start = self._channel.ledger.height
        prev_hash = None
        if start > 0:
            prev = self._channel.ledger.get_block_by_number(start - 1)
            prev_hash = protoutil.block_header_hash(prev.header)
        stager = threading.Thread(target=self._stage_loop, daemon=True)
        stager.start()
        worker = threading.Thread(target=self._commit_loop, daemon=True)
        worker.start()
        try:
            for block in self._source.blocks(
                    start, stop=stop_at, stop_event=self._stop,
                    timeout_s=idle_timeout_s):
                if self._stop.is_set():
                    break
                try:
                    self._channel.mcs.verify_block(
                        self._channel.channel_id, block,
                        expected_prev_hash=prev_hash)
                except BlockVerificationError:
                    # tampered/mis-signed block: drop it, never commit.
                    # With a failover source, ask it to re-fetch this
                    # block from a DIFFERENT orderer and keep pulling
                    # (reference: blocksprovider.go:227 — disconnect
                    # and retry another orderer); a single-endpoint
                    # source fails closed by stopping.
                    self.rejected.append(block.header.number)
                    del self.rejected[:-1000]      # bounded memory
                    report = getattr(self._source, "report_bad_block",
                                     None)
                    if report is not None:
                        report(block.header.number)
                        continue
                    break
                prev_hash = protoutil.block_header_hash(block.header)
                self._q.put(block)
        finally:
            self._q.put(None)
            stager.join()
            worker.join()
        if self._commit_err is not None:
            raise self._commit_err

    def stop(self) -> None:
        self._stop.set()

    def wait_for_height(self, height: int, timeout_s: float = 30.0) -> bool:
        """Block until `height` blocks are committed."""
        with self._committed:
            return self._committed.wait_for(
                lambda: self._height >= height, timeout=timeout_s)
