"""External chaincode builders + chaincode-as-a-service.

(reference: core/container/externalbuilder.go:428 — operator-supplied
builder directories with bin/detect, bin/build, bin/release, bin/run
executables run as subprocesses — and the chaincode-as-a-service
pattern where the package's payload is a connection.json pointing at
an ALREADY-RUNNING chaincode server the peer connects to as a client.)

The TPU-native runtime keeps contracts host-side (chaincode is control
plane, SURVEY §2.3); out-of-process execution uses a line-JSON
protocol over TCP instead of the reference's gRPC shim stream, with
the same callback shape: the peer drives `invoke`, the chaincode
answers with state-operation requests (get/put/del/range/query,
public + private) that the peer executes against the live transaction
simulator, then `complete`/`error` ends the exchange.

Three pieces:
* `ChaincodeServer` — the service side: users run any `Contract`
  out-of-process with `serve_forever()`.
* `ExternalContract` — the peer-side adapter implementing the
  Contract protocol over a connection.json address.
* `ExternalBuilderRegistry` + `ChaincodeLauncher` — script-contract
  builders (detect/build/release/run) and the resolver that turns an
  installed package into a live Contract on first use ("python"
  packages exec in-process; "ccaas" packages dial out).
"""
from __future__ import annotations

import base64
import json
import os
import socket
import socketserver
import subprocess
import threading
from typing import Callable, Dict, List, Optional, Tuple

from fabric_mod_tpu.peer.chaincode import ChaincodeError, ChaincodeStub
from fabric_mod_tpu.concurrency.threads import RegisteredThread
from fabric_mod_tpu.concurrency.locks import RegisteredLock


class ExternalBuilderError(Exception):
    pass


# ---------------------------------------------------------------------------
# Wire protocol: newline-delimited JSON, bytes base64-encoded
# ---------------------------------------------------------------------------

def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def _send(sock_file, obj: Dict) -> None:
    sock_file.write(json.dumps(obj, sort_keys=True) + "\n")
    sock_file.flush()


def _recv(sock_file) -> Dict:
    line = sock_file.readline()
    if not line:
        # transport-level: the exchange is dead, not a contract error
        raise ConnectionError("chaincode connection closed")
    return json.loads(line)


# the state callbacks the protocol proxies (name -> stub driver)
def _dispatch_state_op(stub: ChaincodeStub, msg: Dict) -> Dict:
    op = msg.get("op")
    if op == "get_state":
        v = stub.get_state(msg["key"])
        return {"value": _b64(v) if v is not None else None}
    if op == "put_state":
        stub.put_state(msg["key"], _unb64(msg["value"]))
        return {}
    if op == "del_state":
        stub.del_state(msg["key"])
        return {}
    if op == "get_state_range":
        out = [[k, _b64(v)] for k, v in
               stub.get_state_range(msg["start"], msg["end"])]
        return {"results": out}
    if op == "get_query_result":
        results, bookmark = stub.get_query_result(msg["query"])
        return {"results": [[k, d] for k, d in results],
                "bookmark": bookmark}
    if op == "set_state_metadata":
        stub.set_state_metadata(msg["key"], msg["name"],
                                _unb64(msg["value"]))
        return {}
    if op == "put_private_data":
        stub.put_private_data(msg["collection"], msg["key"],
                              _unb64(msg["value"]))
        return {}
    if op == "get_private_data":
        v = stub.get_private_data(msg["collection"], msg["key"])
        return {"value": _b64(v) if v is not None else None}
    if op == "del_private_data":
        stub.del_private_data(msg["collection"], msg["key"])
        return {}
    raise ChaincodeError(f"unknown state op {op!r}")


# ---------------------------------------------------------------------------
# Service side (runs in the chaincode's own process)
# ---------------------------------------------------------------------------

class _ProxyStub:
    """Looks like a ChaincodeStub to the remote contract; every state
    call travels back to the peer over the live exchange."""

    def __init__(self, sock_file, args: List[bytes],
                 transient: Dict[str, bytes], txid: str,
                 namespace: str = "", channel_id: str = ""):
        self._f = sock_file
        self.args = args
        self.transient = transient
        self.txid = txid
        # same public surface as ChaincodeStub: contracts read these
        self.namespace = namespace
        self.channel_id = channel_id

    def _call(self, **msg) -> Dict:
        _send(self._f, {"type": "state", **msg})
        resp = _recv(self._f)
        if resp.get("type") != "state_response":
            raise ChaincodeError("protocol violation from peer")
        if "error" in resp:
            raise ChaincodeError(resp["error"])
        return resp

    def get_state(self, key: str) -> Optional[bytes]:
        v = self._call(op="get_state", key=key).get("value")
        return _unb64(v) if v is not None else None

    def put_state(self, key: str, value: bytes) -> None:
        self._call(op="put_state", key=key, value=_b64(value))

    def del_state(self, key: str) -> None:
        self._call(op="del_state", key=key)

    def get_state_range(self, start: str, end: str):
        out = self._call(op="get_state_range", start=start, end=end)
        return iter([(k, _unb64(v)) for k, v in out["results"]])

    def get_query_result(self, query):
        if isinstance(query, bytes):
            query = query.decode()
        out = self._call(op="get_query_result", query=query)
        return [(k, d) for k, d in out["results"]], out["bookmark"]

    def set_state_metadata(self, key: str, name: str,
                           value: bytes) -> None:
        self._call(op="set_state_metadata", key=key, name=name,
                   value=_b64(value))

    def put_private_data(self, collection: str, key: str,
                         value: bytes) -> None:
        self._call(op="put_private_data", collection=collection,
                   key=key, value=_b64(value))

    def get_private_data(self, collection: str,
                         key: str) -> Optional[bytes]:
        v = self._call(op="get_private_data", collection=collection,
                       key=key).get("value")
        return _unb64(v) if v is not None else None

    def del_private_data(self, collection: str, key: str) -> None:
        self._call(op="del_private_data", collection=collection,
                   key=key)


class ChaincodeServer:
    """Serves one Contract out-of-process (the CCaaS server —
    reference: the peer.connects-to-chaincode mode of external
    builders; here the protocol server the ExternalContract dials)."""

    def __init__(self, contract, host: str = "127.0.0.1",
                 port: int = 0):
        self._contract = contract
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                f = _SockFile(self.rfile, self.wfile)
                while True:
                    try:
                        msg = _recv(f)
                    except Exception:
                        return
                    if msg.get("type") != "invoke":
                        return
                    stub = _ProxyStub(
                        f,
                        [_unb64(a) for a in msg["args"]],
                        {k: _unb64(v)
                         for k, v in msg.get("transient", {}).items()},
                        msg.get("txid", ""),
                        namespace=msg.get("namespace", ""),
                        channel_id=msg.get("channel_id", ""))
                    try:
                        payload = outer._contract.invoke(stub)
                        _send(f, {"type": "complete",
                                  "payload": _b64(payload or b"")})
                    except Exception as e:
                        _send(f, {"type": "error", "message": str(e)})

        self._srv = socketserver.ThreadingTCPServer((host, port), Handler)
        self._srv.daemon_threads = True
        self.address = "%s:%d" % self._srv.server_address

    def start(self) -> None:
        t = RegisteredThread(target=self._srv.serve_forever,
                             name="extbuilder-http",
                             structure="peer.extbuilder")
        t.start()

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class _SockFile:
    """read/write adapter shared by both protocol ends."""

    def __init__(self, rfile, wfile):
        self._r = rfile
        self._w = wfile

    def readline(self) -> str:
        line = self._r.readline()
        return line.decode() if isinstance(line, bytes) else line

    def write(self, s) -> None:
        self._w.write(s.encode() if isinstance(s, str) else s)

    def flush(self) -> None:
        self._w.flush()


# ---------------------------------------------------------------------------
# Peer side
# ---------------------------------------------------------------------------

class ExternalContract:
    """Contract adapter: forwards invoke() to a chaincode server named
    by connection.json (reference: the ccaas connection.json contract
    — {"address": "host:port"}).  One connection, invokes serialized
    (the endorser already serializes per-proposal)."""

    def __init__(self, connection: Dict, timeout_s: float = 30.0):
        address = connection.get("address", "")
        host, _, port = address.partition(":")
        if not host or not port:
            raise ExternalBuilderError(
                f"connection.json address invalid: {address!r}")
        self._addr = (host, int(port))
        self._timeout = timeout_s
        # RLock: the invoke error path closes the connection while
        # already holding the lock
        self._lock = RegisteredLock("peer.extbuilder.ExternalContract._lock")
        self._sock: Optional[socket.socket] = None
        self._file: Optional[_SockFile] = None

    def _connect(self) -> _SockFile:
        if self._file is None:
            s = socket.create_connection(self._addr,
                                         timeout=self._timeout)
            self._sock = s
            rf = s.makefile("rb")
            wf = s.makefile("wb")
            self._file = _SockFile(rf, wf)
        return self._file

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None
                    self._file = None

    def invoke(self, stub: ChaincodeStub) -> bytes:
        with self._lock:
            try:
                return self._invoke_locked(stub)
            except ChaincodeError:
                # the contract reported an error over a COMPLETED
                # exchange: the connection stays usable
                raise
            except Exception as e:
                # transport-level (EOF, refused, protocol violation):
                # the socket may be dead or desynchronized mid-exchange
                # — never reuse it for the next transaction
                self.close()
                raise ChaincodeError(
                    f"external chaincode unreachable: {e}") from e

    def _invoke_locked(self, stub: ChaincodeStub) -> bytes:
        f = self._connect()
        _send(f, {"type": "invoke", "txid": stub.txid,
                  "namespace": getattr(stub, "namespace", ""),
                  "channel_id": getattr(stub, "channel_id", ""),
                  "args": [_b64(a) for a in stub.args],
                  "transient": {k: _b64(v)
                                for k, v in stub.transient.items()}})
        while True:
            msg = _recv(f)
            kind = msg.get("type")
            if kind == "state":
                try:
                    out = _dispatch_state_op(stub, msg)
                    _send(f, {"type": "state_response", **out})
                except Exception as e:
                    _send(f, {"type": "state_response",
                              "error": str(e)})
            elif kind == "complete":
                return _unb64(msg.get("payload", ""))
            elif kind == "error":
                raise ChaincodeError(msg.get("message", "chaincode error"))
            else:
                raise ConnectionError(f"protocol violation: {kind!r}")


# ---------------------------------------------------------------------------
# Script-contract builders (reference: externalbuilder.go detect/
# build/release/run)
# ---------------------------------------------------------------------------

class ExternalBuilder:
    """One builder directory with bin/{detect,build,release,run}.

    detect(BUILD_OUTPUT_DIR=metadata dir) exit 0 claims the package;
    build(SOURCE, METADATA, OUTPUT) materializes runnable output;
    release(OUTPUT, RELEASE) exports artifacts; run(OUTPUT, RUN_META)
    launches the chaincode (long-running subprocess)."""

    def __init__(self, path: str):
        self.path = path
        self.name = os.path.basename(path.rstrip("/"))

    def _script(self, name: str) -> Optional[str]:
        p = os.path.join(self.path, "bin", name)
        return p if os.access(p, os.X_OK) else None

    def _run(self, name: str, args: List[str],
             timeout_s: float = 60.0) -> Tuple[int, bytes]:
        """-> (returncode, stderr).  A hung script counts as failure
        (rc 1), never an escaping TimeoutExpired."""
        script = self._script(name)
        if script is None:
            # detect and build are MANDATORY in the reference's
            # contract; only release (and run, handled separately) are
            # optional — a missing build must not silently "succeed"
            if name == "detect":
                return 1, b""
            if name == "build":
                raise ExternalBuilderError(
                    f"builder {self.name} has no bin/build")
            return 0, b""
        try:
            proc = subprocess.run([script] + args, timeout=timeout_s,
                                  capture_output=True)
        except subprocess.TimeoutExpired:
            return 1, b"timed out after %ds" % int(timeout_s)
        return proc.returncode, proc.stderr or b""

    def detect(self, metadata_dir: str) -> bool:
        return self._run("detect", [metadata_dir])[0] == 0

    def build(self, source_dir: str, metadata_dir: str,
              output_dir: str) -> None:
        rc, stderr = self._run("build", [source_dir, metadata_dir,
                                         output_dir])
        if rc != 0:
            raise ExternalBuilderError(
                f"builder {self.name}: build failed: "
                f"{stderr[-500:].decode(errors='replace')}")

    def release(self, output_dir: str, release_dir: str) -> None:
        rc, stderr = self._run("release", [output_dir, release_dir])
        if rc != 0:
            raise ExternalBuilderError(
                f"builder {self.name}: release failed: "
                f"{stderr[-500:].decode(errors='replace')}")

    def run(self, output_dir: str, run_meta_dir: str
            ) -> subprocess.Popen:
        script = self._script("run")
        if script is None:
            raise ExternalBuilderError(f"builder {self.name} has no "
                                       "bin/run")
        return subprocess.Popen([script, output_dir, run_meta_dir])


class ExternalBuilderRegistry:
    """Ordered builder list scanned from a root dir (reference: the
    externalBuilders core.yaml section; first detect() wins)."""

    def __init__(self, root: Optional[str] = None):
        self.builders: List[ExternalBuilder] = []
        if root and os.path.isdir(root):
            for name in sorted(os.listdir(root)):
                p = os.path.join(root, name)
                if os.path.isdir(p):
                    self.builders.append(ExternalBuilder(p))

    def detect(self, metadata_dir: str) -> Optional[ExternalBuilder]:
        for b in self.builders:
            if b.detect(metadata_dir):
                return b
        return None


# ---------------------------------------------------------------------------
# The launcher: installed package -> live Contract
# ---------------------------------------------------------------------------

class ChaincodeLauncher:
    """Resolves a namespace to a Contract from the installed packages
    on first use (reference: chaincode_support.go:93 Launch).  Wire it
    as the ChaincodeRegistry's resolver.

    Package types route through the language platforms registry
    (peer/platforms.py — python in-proc, ccaas dial-out, script
    launch; reference: core/chaincode/platforms/platforms.go:62);
    types no platform claims are offered to the external builders.
    """

    def __init__(self, package_store, builders=None, platforms=None):
        from fabric_mod_tpu.peer.platforms import (LaunchContext,
                                                   PlatformRegistry)
        self._store = package_store
        self._builders = builders or ExternalBuilderRegistry()
        self._platforms = platforms or PlatformRegistry()
        self._live: Dict[str, object] = {}
        self._procs: List[subprocess.Popen] = []
        self._lock = RegisteredLock("peer.extbuilder.ChaincodeLauncher._lock")
        self._launch_ctx = LaunchContext(self._procs.append)

    def resolve(self, name: str):
        with self._lock:
            if name in self._live:
                return self._live[name]
            contract = self._build(name)
            if contract is not None:
                self._live[name] = contract
            return contract

    def _find_package(self, name: str) -> Optional[Tuple[str, str, bytes]]:
        from fabric_mod_tpu.peer.ccpackage import parse_package
        matches = sorted(pid for pid in self._store.list()
                         if pid.partition(":")[0] == name)
        if not matches:
            return None
        if len(matches) > 1:
            # two installs sharing a label must not resolve by listdir
            # luck — peers would run different code for the same name
            raise ExternalBuilderError(
                f"ambiguous chaincode {name!r}: {len(matches)} "
                f"installed packages share the label ({matches}); "
                "remove the stale install")
        raw = self._store.load(matches[0])
        return parse_package(raw)

    def _build(self, name: str):
        got = self._find_package(name)
        if got is None:
            return None
        label, cc_type, code = got
        # language platforms first (platforms.go:198 dispatch), then
        # the external-builder fallback for unclaimed types
        contract = self._platforms.build_for(label, cc_type, code,
                                             self._launch_ctx)
        if contract is not None:
            return contract
        return self._build_external(label, cc_type, code)

    def _build_external(self, label: str, cc_type: str, code: bytes):
        """Offer an unknown package type to the external builders:
        detect -> build -> release; the artifacts must yield a
        connection.json (directly, via release, or written by a
        launched bin/run — which receives the address file path in
        its run metadata)."""
        import shutil
        import tempfile
        import time as _time
        work = tempfile.mkdtemp(prefix=f"ccbuild-{label}-")
        src, meta, out, rel, run_meta = (
            os.path.join(work, d)
            for d in ("src", "meta", "out", "rel", "run"))
        keep_work = False
        try:
            for d in (src, meta, out, rel, run_meta):
                os.makedirs(d)
            with open(os.path.join(src, "code.bin"), "wb") as f:
                f.write(code)
            with open(os.path.join(meta, "metadata.json"), "w") as f:
                json.dump({"label": label, "type": cc_type}, f)
            builder = self._builders.detect(meta)
            if builder is None:
                raise ExternalBuilderError(
                    f"package {label}: no builder claims type "
                    f"{cc_type!r}")
            builder.build(src, meta, out)
            builder.release(out, rel)
            for d in (rel, out):
                conn_path = os.path.join(d, "connection.json")
                if os.path.exists(conn_path):
                    return ExternalContract(json.load(open(conn_path)))
            # no connection artifact: launch bin/run, which must write
            # its listen address to the advertised file
            addr_file = os.path.join(run_meta, "address")
            with open(os.path.join(run_meta, "chaincode.json"),
                      "w") as f:
                json.dump({"address_file": addr_file}, f)
            proc = builder.run(out, run_meta)
            self._procs.append(proc)
            deadline = _time.monotonic() + 30.0
            while _time.monotonic() < deadline:
                if os.path.exists(addr_file):
                    addr = open(addr_file).read().strip()
                    if addr:
                        # success: the run output stays alive with the
                        # process; failure paths below clean up
                        keep_work = True
                        return ExternalContract({"address": addr})
                if proc.poll() is not None:
                    raise ExternalBuilderError(
                        f"builder {builder.name}: run exited rc="
                        f"{proc.returncode} before publishing an "
                        "address")
                _time.sleep(0.05)
            proc.kill()
            proc.wait(timeout=5)           # no zombies
            raise ExternalBuilderError(
                f"builder {builder.name}: run never published an "
                "address")
        finally:
            if not keep_work:
                shutil.rmtree(work, ignore_errors=True)

    def close(self) -> None:
        """Stop (and reap) launched chaincode processes."""
        for proc in self._procs:
            if proc.poll() is None:
                proc.kill()
            try:
                proc.wait(timeout=5)
            except Exception:  # fmtlint: allow[swallowed-exceptions] -- reaping an already-killed chaincode process is best-effort teardown
                pass
        self._procs.clear()
