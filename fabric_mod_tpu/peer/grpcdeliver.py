"""gRPC deliver source + broadcast client for the peer side.

(reference: internal/pkg/peer/blocksprovider — the deliver stream
client with retry/failover — and the broadcast client the CLI uses.)

`GrpcDeliverSource` has the same `blocks()` generator shape as the
in-process DeliverService, so DeliverClient (and its MCS verification
+ pipelined commit) is transport-agnostic.

`GrpcBroadcaster` is the ingress counterpart, now overload-aware: a
RESOURCE_EXHAUSTED answer (admission shed, orderer/admission.py) is
typed client-side and — when a `retrier` is configured — retried
honoring the server's retry-after hint; a SERVICE_UNAVAILABLE answer
carrying a leader hint re-dials the hinted consenter via `redial`
BEFORE consuming any backoff budget (the ROADMAP's NOT_LEADER
redirect-following — the hint has been on the wire since PR 5).
"""
from __future__ import annotations

import os
import queue
import re
import threading
import time
from typing import Callable, Iterator, Optional, Sequence

from fabric_mod_tpu.comm.grpc_comm import GRPCClient
from fabric_mod_tpu.orderer.server import SERVICE, make_seek_envelope
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.utils.retry import Retrier
from fabric_mod_tpu.concurrency.locks import RegisteredLock


class GrpcDeliverSource:
    def __init__(self, client: GRPCClient, channel_id: str):
        self._client = client
        self._channel_id = channel_id

    def blocks(self, start: int = 0, stop: Optional[int] = None,
               stop_event: Optional[threading.Event] = None,
               timeout_s: float = 30.0) -> Iterator[m.Block]:
        from fabric_mod_tpu.peer.deliverclient import DeliverDisconnected
        import grpc
        seek = make_seek_envelope(self._channel_id, start, stop)
        stream = self._client.stream_stream(
            SERVICE, "Deliver", iter([seek.encode()]))
        try:
            for raw in stream:
                if stop_event is not None and stop_event.is_set():
                    break
                resp = m.DeliverResponse.decode(raw)
                if resp.block is not None:
                    yield resp.block
                else:
                    return                 # terminal status
        except grpc.RpcError as e:
            if stop_event is not None and stop_event.is_set():
                return                     # our own cancel, clean end
            # single-endpoint source: a dropped stream is TYPED (the
            # caller stamps the committed height) instead of ending
            # silently as if the seek range were served
            raise DeliverDisconnected(
                f"deliver stream dropped: {e!r}") from e
        finally:
            stream.cancel()


class BroadcastClientError(RuntimeError):
    """Typed broadcast rejection: `status`/`info` carry the orderer's
    answer.  Subclasses RuntimeError so pre-typed callers keep
    working."""

    def __init__(self, msg: str, status: int = 0, info: str = ""):
        super().__init__(msg)
        self.status = status
        self.info = info


class BroadcastUnavailable(BroadcastClientError):
    """SERVICE_UNAVAILABLE (no leader); `leader_hint` is the consenter
    id the orderer suggested, or None."""

    def __init__(self, msg: str, info: str = "",
                 leader_hint: Optional[str] = None):
        super().__init__(msg, m.Status.SERVICE_UNAVAILABLE, info)
        self.leader_hint = leader_hint


class BroadcastResourceExhausted(BroadcastClientError):
    """RESOURCE_EXHAUSTED (admission shed); `retry_after_s` is the
    server's backoff hint."""

    def __init__(self, msg: str, info: str = "",
                 retry_after_s: float = 0.25):
        super().__init__(msg, m.Status.RESOURCE_EXHAUSTED, info)
        self.retry_after_s = retry_after_s


def _parse_leader_hint(info: str) -> Optional[str]:
    got = re.search(r"\btry (\S+)", info or "")
    return got.group(1) if got else None


def _parse_retry_after(info: str, default: float = 0.25) -> float:
    got = re.search(r"\bretry_after=([0-9.]+)", info or "")
    try:
        return float(got.group(1)) if got else default
    except ValueError:
        return default


class GrpcBroadcaster:
    """Streaming broadcast client: submit() enqueues an envelope and
    returns the orderer's ack status (reference: the broadcast client
    of internal/pkg + peer CLI).

    `retrier`: retries RESOURCE_EXHAUSTED answers, sleeping AT LEAST
    the server's retry-after hint on top of its own backoff schedule;
    None (the default) surfaces the first typed answer — the
    pre-admission behavior.  `redial(consenter_id) -> GRPCClient`
    enables leader-redirect following: a SERVICE_UNAVAILABLE answer
    naming a leader re-dials it and resubmits immediately, without
    consuming retry budget (redirect-dialed clients are owned and
    closed by this object).  The per-stream send queue is BOUNDED
    (`queue_cap`) so a wedged stream surfaces a typed error instead of
    buffering unboundedly."""

    _MAX_REDIRECTS = 3                     # per submit() call

    def __init__(self, client: GRPCClient,
                 retrier: Optional[Retrier] = None,
                 redial: Optional[Callable[[str], GRPCClient]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 queue_cap: int = 1024):
        self._retrier = retrier
        self._redial = redial
        self._sleep = sleep
        self._queue_cap = queue_cap
        self._lock = RegisteredLock("peer.grpcdeliver._lock")
        self._owned: list = []             # redirect-dialed clients
        self._hint_wait = 0.0              # pending retry-after hint
        self.trace_ctx = None              # set when FMT_TRACE is armed
        self._open(client)

    def _open(self, client: GRPCClient) -> None:
        from fabric_mod_tpu.observability import tracing
        self._client = client
        self._q: "queue.Queue[Optional[bytes]]" = queue.Queue(
            maxsize=self._queue_cap)
        # cross-process stitching: when FMT_TRACE is armed, the
        # stream's invocation metadata carries this client's trace
        # context — the orderer's broadcast handler parents its spans
        # under it, so a tx is ONE trace across the process boundary.
        # Unarmed, inject() is None and the wire is byte-identical.
        self._trace_md = tracing.inject(self._trace_root())
        # keyword passed ONLY when armed: scripted/fake clients that
        # predate the metadata parameter keep working untraced
        kw = {"metadata": self._trace_md} \
            if self._trace_md is not None else {}
        self._resps = client.stream_stream(
            SERVICE, "Broadcast", iter(self._q.get, None), **kw)

    def _trace_root(self):
        """The stream's carrier context: the caller's current span if
        one is live, else a fresh per-stream root so even an
        un-spanned client gets a stitched trace id."""
        from fabric_mod_tpu.observability import tracing
        if not tracing.armed():
            return None
        ctx = tracing.current_ctx()
        if ctx is None:
            ctx = tracing.TraceContext(tracing.new_trace_id(),
                                       os.urandom(4).hex())
        self.trace_ctx = ctx
        return ctx

    def _reconnect(self, client: GRPCClient) -> None:
        """Swap streams (caller holds the lock): end the old stream;
        redirect-owned clients are closed, the caller's original
        client stays theirs to close."""
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass
        if self._client in self._owned:
            self._owned.remove(self._client)
            try:
                self._client.close()
            except Exception:  # fmtlint: allow[swallowed-exceptions] -- closing a dead owned client during rotation; the reconnect path is the recovery
                pass
        self._owned.append(client)
        self._open(client)

    def submit(self, env: m.Envelope) -> None:
        """Raises BroadcastClientError (typed by status) when the
        orderer rejects; with a `retrier`, RESOURCE_EXHAUSTED answers
        are retried within its budget before surfacing."""
        raw = env.encode()
        with self._lock:
            self._hint_wait = 0.0
            if self._retrier is None:
                self._submit_once(raw)
            else:
                self._retrier.call(self._submit_once, raw)

    def _submit_once(self, raw: bytes, redirects: int = 0) -> None:
        hint, self._hint_wait = self._hint_wait, 0.0
        if hint > 0.0:
            # honor the server's retry-after ON TOP of the retrier's
            # own backoff: the total wait is never shorter than the
            # hint, so a retrying client can't hammer a shedding node
            self._sleep(hint)
        try:
            self._q.put_nowait(raw)
        except queue.Full:
            raise BroadcastResourceExhausted(
                f"local broadcast queue full ({self._queue_cap})",
                retry_after_s=0.25) from None
        resp = m.BroadcastResponse.decode(next(self._resps))
        if resp.status == m.Status.SUCCESS:
            return
        if resp.status == m.Status.RESOURCE_EXHAUSTED:
            retry_after = _parse_retry_after(resp.info)
            self._hint_wait = retry_after
            raise BroadcastResourceExhausted(
                f"broadcast rejected: {resp.status} {resp.info}",
                info=resp.info, retry_after_s=retry_after)
        if resp.status == m.Status.SERVICE_UNAVAILABLE:
            lead = _parse_leader_hint(resp.info)
            if lead is not None and self._redial is not None \
                    and redirects < self._MAX_REDIRECTS:
                # follow the redirect BEFORE any backoff: the hinted
                # leader is (per the answering node) ready now
                client = None
                try:
                    client = self._redial(lead)
                except Exception:  # fmtlint: allow[swallowed-exceptions] -- redirect redial failure falls through to the bounded backoff path (client stays None)
                    pass
                if client is not None:
                    self._reconnect(client)
                    return self._submit_once(raw, redirects + 1)
            raise BroadcastUnavailable(
                f"broadcast rejected: {resp.status} {resp.info}",
                info=resp.info, leader_hint=lead)
        raise BroadcastClientError(
            f"broadcast rejected: {resp.status} {resp.info}",
            status=resp.status, info=resp.info)

    def close(self) -> None:
        with self._lock:
            try:
                self._q.put_nowait(None)
            except queue.Full:
                pass
            for client in self._owned:
                try:
                    client.close()
                except Exception:  # fmtlint: allow[swallowed-exceptions] -- stream teardown: best-effort close of every owned client
                    pass
            del self._owned[:]
