"""gRPC deliver source + broadcast client for the peer side.

(reference: internal/pkg/peer/blocksprovider — the deliver stream
client with retry/failover — and the broadcast client the CLI uses.)

`GrpcDeliverSource` has the same `blocks()` generator shape as the
in-process DeliverService, so DeliverClient (and its MCS verification
+ pipelined commit) is transport-agnostic.
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional, Sequence

from fabric_mod_tpu.comm.grpc_comm import GRPCClient
from fabric_mod_tpu.orderer.server import SERVICE, make_seek_envelope
from fabric_mod_tpu.protos import messages as m


class GrpcDeliverSource:
    def __init__(self, client: GRPCClient, channel_id: str):
        self._client = client
        self._channel_id = channel_id

    def blocks(self, start: int = 0, stop: Optional[int] = None,
               stop_event: Optional[threading.Event] = None,
               timeout_s: float = 30.0) -> Iterator[m.Block]:
        from fabric_mod_tpu.peer.deliverclient import DeliverDisconnected
        import grpc
        seek = make_seek_envelope(self._channel_id, start, stop)
        stream = self._client.stream_stream(
            SERVICE, "Deliver", iter([seek.encode()]))
        try:
            for raw in stream:
                if stop_event is not None and stop_event.is_set():
                    break
                resp = m.DeliverResponse.decode(raw)
                if resp.block is not None:
                    yield resp.block
                else:
                    return                 # terminal status
        except grpc.RpcError as e:
            if stop_event is not None and stop_event.is_set():
                return                     # our own cancel, clean end
            # single-endpoint source: a dropped stream is TYPED (the
            # caller stamps the committed height) instead of ending
            # silently as if the seek range were served
            raise DeliverDisconnected(
                f"deliver stream dropped: {e!r}") from e
        finally:
            stream.cancel()


class GrpcBroadcaster:
    """Streaming broadcast client: submit() enqueues an envelope and
    returns the orderer's ack status (reference: the broadcast client
    of internal/pkg + peer CLI)."""

    def __init__(self, client: GRPCClient):
        self._client = client
        self._q: "queue.Queue[Optional[bytes]]" = queue.Queue()
        self._resps = self._client.stream_stream(
            SERVICE, "Broadcast", iter(self._q.get, None))
        self._lock = threading.Lock()

    def submit(self, env: m.Envelope) -> None:
        with self._lock:
            self._q.put(env.encode())
            raw = next(self._resps)
        resp = m.BroadcastResponse.decode(raw)
        if resp.status != m.Status.SUCCESS:
            raise RuntimeError(
                f"broadcast rejected: {resp.status} {resp.info}")

    def close(self) -> None:
        self._q.put(None)
