"""The endorser: simulate a proposal, sign the result.

(reference: core/endorser/endorser.go — ProcessProposal at :306,
preProcess's signature+ACL checks at :258, SimulateProposal at :182,
callChaincode at :110 — minus the container launch, which the
in-process chaincode registry replaces.)

Signing stays host-side (the private key never benefits from batching;
SURVEY §7 step 7), but the creator-signature check rides the channel's
batch verify seam when a TpuVerifier is wired.
"""
from __future__ import annotations

import hashlib
from typing import Optional, Sequence

from fabric_mod_tpu.peer.chaincode import ChaincodeRegistry, ChaincodeStub
from fabric_mod_tpu.peer.channel import Channel
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil

CHANNEL_APPLICATION_WRITERS = "/Channel/Application/Writers"


class ProposalRejectedError(Exception):
    pass


class Endorser:
    """One peer's endorsement service for one channel."""

    def __init__(self, channel: Channel, registry: ChaincodeRegistry,
                 signer, max_concurrency: int = 0):
        """`max_concurrency` > 0 caps in-flight ProcessProposal calls
        (reference: internal/peer/node/grpc_limiters.go's Endorser
        semaphore); excess requests shed after a short wait."""
        self._channel = channel
        self._registry = registry
        self._signer = signer
        self._limiter = None
        if max_concurrency > 0:
            from fabric_mod_tpu.utils.semaphore import Semaphore
            self._limiter = Semaphore(max_concurrency)

    # -- request preprocessing (reference: endorser.go:258 preProcess) --
    def _pre_process(self, sp: m.SignedProposal):
        try:
            prop = m.Proposal.decode(sp.proposal_bytes)
            header = m.Header.decode(prop.header)
            ch = m.ChannelHeader.decode(header.channel_header)
            sh = m.SignatureHeader.decode(header.signature_header)
        except Exception as e:
            raise ProposalRejectedError(f"malformed proposal: {e}") from e
        if ch.type != m.HeaderType.ENDORSER_TRANSACTION:
            raise ProposalRejectedError(f"bad header type {ch.type}")
        if ch.channel_id != self._channel.channel_id:
            raise ProposalRejectedError(
                f"proposal for channel {ch.channel_id!r}")
        if ch.tx_id != protoutil.compute_tx_id(sh.nonce, sh.creator):
            raise ProposalRejectedError("tx id does not bind nonce+creator")

        bundle = self._channel.bundle()
        try:
            creator = bundle.msp_manager.deserialize_identity(sh.creator)
            bundle.msp_manager.validate(creator)
        except Exception as e:
            raise ProposalRejectedError(f"bad creator: {e}") from e
        if not creator.verify(sp.proposal_bytes, sp.signature):
            raise ProposalRejectedError("creator signature invalid")

        # ACL: proposals need the channel's application Writers policy
        # (reference: aclmgmt defaults PROPOSE -> /Channel/Application/Writers)
        pol = bundle.policy(CHANNEL_APPLICATION_WRITERS)
        if pol is None:
            raise ProposalRejectedError("no application Writers policy")
        sd = protoutil.SignedData(data=sp.proposal_bytes,
                                  identity=sh.creator,
                                  signature=sp.signature)
        verifier = self._channel.verifier
        verify_many = verifier.verify_many if verifier is not None else None
        if not pol.evaluate_signed_data([sd], verify_many):
            raise ProposalRejectedError("ACL check failed (Writers)")

        if self._channel.ledger.tx_id_exists(ch.tx_id):
            raise ProposalRejectedError(f"duplicate tx id {ch.tx_id}")
        return prop, ch, sh

    # -- the endorsement flow (reference: endorser.go:306) ---------------
    def process_proposal(self, sp: m.SignedProposal) -> m.ProposalResponse:
        if self._limiter is not None:
            from fabric_mod_tpu.utils.semaphore import AcquireTimeout
            try:
                with self._limiter.acquire(timeout_s=5.0):
                    return self._process_proposal(sp)
            except AcquireTimeout as e:
                return m.ProposalResponse(response=m.Response(
                    status=503, message=f"endorser overloaded: {e}"))
        return self._process_proposal(sp)

    def _process_proposal(self, sp: m.SignedProposal) -> m.ProposalResponse:
        prop, ch, sh = self._pre_process(sp)
        try:
            ccpp = m.ChaincodeProposalPayload.decode(prop.payload)
            cis = m.ChaincodeInvocationSpec.decode(ccpp.input)
            spec = cis.chaincode_spec
            ns = spec.chaincode_id.name
            args = list(spec.input.args) if spec.input else []
            transient = {e.key: e.value for e in ccpp.transient_map}
        except Exception as e:
            raise ProposalRejectedError(f"bad chaincode payload: {e}") from e

        # simulate against current state (reference: :182
        # SimulateProposal over a tx simulator with read-your-writes)
        sim = self._channel.ledger.new_tx_simulator(ch.tx_id)
        stub = ChaincodeStub(ns, sim, args, ch.tx_id,
                             self._channel.channel_id,
                             transient=transient, creator=sh.creator)
        try:
            result = self._registry.execute(ns, stub)
            rwset = sim.done()
            pvt = sim.done_pvt()
        except Exception as e:
            return m.ProposalResponse(
                response=m.Response(status=500, message=str(e)))
        if pvt is not None:
            # stage plaintext private writes for the commit path
            # (reference: endorser.go's DistributePrivateData — gossip
            # distribution later; transient staging is the local leg)
            self._channel.transient_store.persist(
                ch.tx_id, self._channel.ledger.height, pvt)

        events = b""
        if stub.event is not None:
            events = m.ChaincodeEvent(
                chaincode_id=ns, tx_id=ch.tx_id,
                event_name=stub.event[0],
                payload=stub.event[1]).encode()
        cca = m.ChaincodeAction(
            results=rwset.encode(),
            events=events,
            response=m.Response(status=200, payload=result),
            chaincode_id=m.ChaincodeID(name=ns))
        prp = m.ProposalResponsePayload(
            proposal_hash=hashlib.sha256(sp.proposal_bytes).digest(),
            extension=cca.encode())
        prp_bytes = prp.encode()
        endorser_bytes = self._signer.serialize()
        endorsement = m.Endorsement(
            endorser=endorser_bytes,
            signature=self._signer.sign_message(
                prp_bytes + endorser_bytes))
        return m.ProposalResponse(
            version=1,
            response=m.Response(status=200, payload=result),
            payload=prp_bytes,
            endorsement=endorsement)


def endorse_and_submit(channel_id: str, chaincode_ns: str,
                       args: Sequence[bytes], client_signer,
                       endorsers: Sequence[Endorser],
                       broadcast, transient=None) -> str:
    """Client convenience: proposal -> N endorsements -> tx envelope ->
    broadcast; returns the tx id (the e2e happy path)."""
    sp, prop, tx_id = protoutil.create_chaincode_proposal(
        channel_id, chaincode_ns, args, client_signer,
        transient=transient)
    responses = [e.process_proposal(sp) for e in endorsers]
    env = protoutil.create_tx_from_responses(prop, responses, client_signer)
    broadcast.submit(env)
    return tx_id
