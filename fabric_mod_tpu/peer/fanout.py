"""Shared per-block deliver fan-out: materialize once, ship to N.

(reference: common/deliver/deliver.go + core/peer/deliverevents.go —
the deliver layer makes BLOCK MATERIALIZATION the shared object and
the stream the cheap thing; before this module every
Deliver/DeliverFiltered stream independently re-fetched, re-projected,
re-encoded and re-ACL-checked every block, so 10k subscribers
multiplied commit-path work 10,000x.)

Three shared dimensions, one engine (ISSUE 17):

* ``BlockFanout`` — one per (channel, form in {full, filtered}): on
  each commit notification the block is materialized ONCE (filtered
  projection once, ``DeliverResponse`` wire bytes encoded once) into a
  bounded ring of ready-to-send frames that N streams consume by
  sequence number.  Slow subscribers past the ring tail fall back to a
  per-stream ledger re-read (counted, never inserted — replay of cold
  history must not evict the tip's hot frames).
* ``CommitNotifier`` (ledger/notifier.py) — ONE thread parked on the
  ledger's commit condition materializes the new frames and fans the
  commit signal to parked streams' private events: zero tick wakeups.
* ``AclGroups`` — standing subscriptions grouped by (resource,
  creator): the session ACL re-check is evaluated ONCE per (group,
  config-sequence [, forced config-block]) with one ``check_acl`` on
  the group's representative SignedData, and the verdict fanned to
  every member.  Sound because members of a group share the creator
  identity and each member's own seek signature was verified at
  admission; the re-check verdict depends only on (creator, current
  config).  Forced-recheck-on-config-block semantics are preserved
  exactly: a config block flowing through a stream forces one
  group evaluation keyed by that block number.

The filtered projection itself reuses protos/batchdecode.py downward
(Transaction/ChaincodeActionPayload layers) so the block body decodes
in one vectorized pass with the sound-not-complete per-tx fallback.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Optional

from fabric_mod_tpu import faults
from fabric_mod_tpu.concurrency.locks import RegisteredLock
from fabric_mod_tpu.ledger.notifier import CommitNotifier
from fabric_mod_tpu.observability import tracing
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.protos import batchdecode
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.utils import knobs

FORMS = ("full", "filtered")


# ---------------------------------------------------------------------------
# Filtered-block projection (reference: deliverevents.go:293), shared
# by the ring (batch path) and the per-stream fallback/legacy arm.
# ---------------------------------------------------------------------------

def _filtered_actions(tx_bytes: bytes) -> m.FilteredTransactionActions:
    """The generic per-tx action projection — the fallback that OWNS
    the verdict for anything the batch scanner cannot prove clean."""
    actions = []
    tx = m.Transaction.decode(tx_bytes)
    for action in tx.actions:
        cap = m.ChaincodeActionPayload.decode(action.payload)
        if cap.action is None:
            continue
        prp = m.ProposalResponsePayload.decode(
            cap.action.proposal_response_payload)
        cca = m.ChaincodeAction.decode(prp.extension)
        event = None
        if cca.events:
            ev = m.ChaincodeEvent.decode(cca.events)
            # payload stripped, per the reference's filtered contract
            event = m.ChaincodeEvent(chaincode_id=ev.chaincode_id,
                                     tx_id=ev.tx_id,
                                     event_name=ev.event_name)
        actions.append(m.FilteredChaincodeAction(chaincode_event=event))
    return m.FilteredTransactionActions(chaincode_actions=actions)


def filtered_block(channel_id: str, block: m.Block,
                   batch: bool = True) -> m.FilteredBlock:
    """Project a committed block to its filtered form: per-tx txid,
    header type, validation code, and chaincode events with the
    payload NILLED (the reference strips event payloads so filtered
    streams never leak application data).

    With `batch` (the default) the spine and tx-body layers decode in
    one vectorized batchdecode pass; any row the scanner cannot prove
    clean falls back to the generic per-tx decode, which owns every
    malformed-input outcome — so the output is value-identical to the
    per-tx-only projection (`batch=False`, the historical path kept
    as the differential reference and the bench's per-stream arm)."""
    flags = protoutil.block_txflags(block)
    envs = protoutil.get_envelopes(block)
    datas = list(block.data.data)
    spine = (batchdecode.decode_block_spine(datas) if batch
             else [None] * len(datas))
    tx_datas = [row.payload.data
                if row is not None
                and row.ch.type == m.HeaderType.ENDORSER_TRANSACTION
                else None
                for row in spine]
    batch_actions = batchdecode.decode_filtered_actions(tx_datas)
    ftxs = []
    for i, env in enumerate(envs):
        code = (flags[i] if i < len(flags)
                else m.TxValidationCode.NOT_VALIDATED)
        row = spine[i]
        if row is not None:
            payload, ch = row.payload, row.ch
        else:
            try:
                payload = protoutil.unmarshal_envelope_payload(env)
                ch = m.ChannelHeader.decode(payload.header.channel_header)
            except Exception:
                ftxs.append(m.FilteredTransaction(tx_validation_code=code))
                continue
        ftx = m.FilteredTransaction(txid=ch.tx_id, type=ch.type,
                                    tx_validation_code=code)
        if ch.type == m.HeaderType.ENDORSER_TRANSACTION:
            if batch_actions[i] is not None:
                ftx.transaction_actions = batch_actions[i]
            else:
                try:
                    ftx.transaction_actions = _filtered_actions(
                        payload.data)
                except Exception:  # fmtlint: allow[swallowed-exceptions] -- malformed tx body: the filtered event still carries txid+code, which is the contract
                    pass
        ftxs.append(ftx)
    return m.FilteredBlock(channel_id=channel_id,
                           number=block.header.number,
                           filtered_transactions=ftxs)


def _is_config_block(block: m.Block) -> bool:
    """Whether a committed block carries a channel config transaction
    (first envelope's header type; config blocks hold exactly one)."""
    try:
        env = protoutil.get_envelopes(block)[0]
        payload = protoutil.unmarshal_envelope_payload(env)
        ch = m.ChannelHeader.decode(payload.header.channel_header)
        return ch.type == m.HeaderType.CONFIG
    except Exception:
        return False


def encode_frame(channel_id: str, form: str, block: m.Block,
                 batch: bool = True) -> bytes:
    """The on-the-wire DeliverResponse for one (block, form) — what a
    per-stream sender would have built; the ring builds it once.
    `batch=False` is the historical per-tx projection (the bench's
    per-stream arm and the identity gate's reference)."""
    if form == "filtered":
        resp = m.DeliverResponse(
            filtered_block=filtered_block(channel_id, block,
                                          batch=batch))
    else:
        resp = m.DeliverResponse(block=block)
    return resp.encode()


# ---------------------------------------------------------------------------
# Metrics (named get-or-create: engines instantiate per channel)
# ---------------------------------------------------------------------------

def _metric(kind, name, help, labels=("channel", "form")):
    opts = MetricOpts("fabric", "deliver", name, help, labels)
    return getattr(default_provider(), kind)(opts)


class _ConfigMemo:
    """Bounded LRU over (block number -> is-config-block).

    Replaces deliverevents' unbounded dict that was wholesale
    ``clear()``-ed at 4096 entries (every standing stream then paid
    the re-classification burst at once).  An LRU keeps the hot window
    resident and evicts one-at-a-time; both forms' rings and every
    per-stream fallback share it, so a block is classified at most
    once while it stays warm."""

    def __init__(self, cap: int = 4096):
        self._cap = cap
        self._d: "collections.OrderedDict[int, bool]" = \
            collections.OrderedDict()
        self._lock = RegisteredLock("peer.fanout.cfgmemo._lock")

    def classify(self, block: m.Block) -> bool:
        num = block.header.number
        with self._lock:
            if num in self._d:
                self._d.move_to_end(num)
                return self._d[num]
        val = _is_config_block(block)
        with self._lock:
            self._d[num] = val
            self._d.move_to_end(num)
            while len(self._d) > self._cap:
                self._d.popitem(last=False)
        return val

    def __len__(self) -> int:
        return len(self._d)


class _Frame:
    """One ready-to-send block frame: encoded once, shipped N times."""

    __slots__ = ("num", "payload", "is_config")

    def __init__(self, num: int, payload: bytes, is_config: bool):
        self.num = num
        self.payload = payload
        self.is_config = is_config


class BlockFanout:
    """The bounded ring of ready frames for one (channel, form)."""

    def __init__(self, channel_id: str, ledger, form: str,
                 ring_size: int, stats: Optional[Dict[str, int]] = None,
                 classify: Optional[Callable[[m.Block], bool]] = None):
        self._channel_id = channel_id
        self._ledger = ledger
        self.form = form
        self._ring_size = max(1, ring_size)
        self._ring: Dict[int, _Frame] = {}
        self._lock = RegisteredLock(f"peer.fanout.{form}._lock")
        self._classify = classify or _is_config_block
        # standalone consumers (the dissemination relay rides a bare
        # ring with no engine around it) get their own counters
        self.stats = stats if stats is not None else {
            "materialized": 0, "encoded": 0, "ring_hits": 0,
            "fallbacks": 0}
        self._m_mat = _metric("counter", "fanout_materialize_total",
                              "blocks materialized once into the ring")
        self._m_enc = _metric("counter", "fanout_encode_total",
                              "DeliverResponse frames encoded once")
        self._m_hit = _metric("counter", "fanout_ring_hits_total",
                              "frames served from the shared ring")
        self._m_fall = _metric("counter", "fanout_fallback_total",
                               "per-stream ledger re-reads past the "
                               "ring tail")

    def _build(self, num: int) -> Optional[_Frame]:
        blk = self._ledger.get_block_by_number(num)
        if blk is None:
            return None
        with tracing.span("fanout.materialize", block=num):
            is_cfg = self._classify(blk)
            payload = encode_frame(self._channel_id, self.form, blk)
        return _Frame(num, payload, is_cfg)

    def materialize_upto(self, height: int) -> None:
        """Fill the ring window [height - ring_size, height) — called
        by the notifier thread on commit, and by a joining stream
        catching up inside the window.  Exactly-once: the whole fill
        runs under the ring lock, so a racing on-demand get() never
        duplicates the projection/encode work."""
        with self._lock:
            lo = max(0, height - self._ring_size)
            for num in range(lo, height):
                if num in self._ring:
                    continue
                fr = self._build(num)
                if fr is None:
                    break
                self._ring[num] = fr
                self.stats["materialized"] += 1
                self.stats["encoded"] += 1
                self._m_mat.with_labels(self._channel_id, self.form).add(1)
                self._m_enc.with_labels(self._channel_id, self.form).add(1)
            for num in [k for k in self._ring if k < lo]:
                del self._ring[num]

    def get(self, num: int) -> Optional[_Frame]:
        """The frame for block `num`, or None when it is not committed
        yet.  Ring window -> shared frame (materialized at most once);
        past the tail -> per-stream fallback re-read, counted and NOT
        inserted (cold replay must not evict the hot tip)."""
        height = self._ledger.height
        if num >= height:
            return None
        with self._lock:
            fr = self._ring.get(num)
        if fr is not None:
            self.stats["ring_hits"] += 1
            self._m_hit.with_labels(self._channel_id, self.form).add(1)
            return fr
        if num >= height - self._ring_size:
            # joining-mid-chain catch-up inside the window: fill the
            # ring on demand (shared with any concurrent joiner)
            self.materialize_upto(height)
            with self._lock:
                fr = self._ring.get(num)
            if fr is not None:
                self.stats["ring_hits"] += 1
                self._m_hit.with_labels(self._channel_id,
                                        self.form).add(1)
                return fr
        self.stats["fallbacks"] += 1
        self._m_fall.with_labels(self._channel_id, self.form).add(1)
        return self._build(num)


# ---------------------------------------------------------------------------
# Batched session ACLs
# ---------------------------------------------------------------------------

class _AclGroup:
    """All standing subscriptions for one (resource, creator)."""

    __slots__ = ("resource", "rep_sd", "verdicts", "lock")

    def __init__(self, resource: str, rep_sd):
        self.resource = resource
        self.rep_sd = rep_sd
        # (config_sequence, forced-config-block-or-None) -> Exception|None
        self.verdicts: "collections.OrderedDict" = collections.OrderedDict()
        self.lock = RegisteredLock("peer.fanout.aclgroup.lock")


class AclGroupSession:
    """One stream's handle on its group's shared session re-check.

    Mirrors the historical per-stream closure exactly: a no-op until
    the config sequence moves, forced when a config block flows
    through THIS stream — but the evaluation happens once per (group,
    key) instead of once per stream."""

    __slots__ = ("_groups", "_group", "_seq")

    def __init__(self, groups: "AclGroups", group: _AclGroup, seq0):
        self._groups = groups
        self._group = group
        self._seq = seq0

    def recheck(self, force: bool = False,
                config_mark: Optional[int] = None) -> None:
        seq = self._groups.sequence()
        if not force and seq == self._seq:
            return
        self._seq = seq
        self._groups.check(self._group, seq,
                           config_mark if force else None)


class AclGroups:
    """Group registry + the once-per-(group, key) evaluator."""

    _VERDICT_KEEP = 64

    def __init__(self, acl, channel_id: str):
        self._acl = acl
        self._seq_of = getattr(acl, "config_sequence", None)
        self._channel_id = channel_id
        self._groups: Dict[tuple, _AclGroup] = {}
        self._lock = RegisteredLock("peer.fanout.aclgroups._lock")
        self.stats = {"checks": 0, "reuses": 0}
        self._m_checks = _metric(
            "counter", "acl_group_checks_total",
            "session ACL evaluations (one per group per key)",
            labels=("channel",))
        self._m_reuse = _metric(
            "counter", "acl_group_reuse_total",
            "session ACL verdicts fanned from a group's cached check",
            labels=("channel",))

    def sequence(self):
        return self._seq_of() if self._seq_of is not None else None

    def join(self, resource: str, sd, seq0) -> AclGroupSession:
        key = (resource, bytes(sd.identity))
        with self._lock:
            group = self._groups.get(key)
            if group is None:
                group = _AclGroup(resource, sd)
                self._groups[key] = group
        return AclGroupSession(self, group, seq0)

    def check(self, group: _AclGroup, seq, mark: Optional[int]) -> None:
        """Evaluate (or reuse) the group verdict for (seq, mark);
        raises the deny for every member — fail-closed fan-out.

        Batching is sound ONLY because a verdict depends on (creator,
        config sequence): a provider that exposes no
        ``config_sequence`` gives us no key under which verdicts are
        provably stable, so every check evaluates fresh (the
        historical per-stream behavior, minus nothing)."""
        key = (seq, mark)
        with group.lock:
            if seq is not None and key in group.verdicts:
                err = group.verdicts[key]
                self.stats["reuses"] += 1
                self._m_reuse.with_labels(self._channel_id).add(1)
            else:
                err = None
                try:
                    self._acl.check_acl(group.resource, [group.rep_sd])
                except Exception as e:  # fmtlint: allow[swallowed-exceptions] -- the deny IS the verdict: cached and re-raised for every member below
                    err = e
                if seq is not None:
                    group.verdicts[key] = err
                    while len(group.verdicts) > self._VERDICT_KEEP:
                        group.verdicts.popitem(last=False)
                self.stats["checks"] += 1
                self._m_checks.with_labels(self._channel_id).add(1)
        if err is not None:
            raise err


# ---------------------------------------------------------------------------
# The engine: ring x2 + notifier + ACL groups, one per channel
# ---------------------------------------------------------------------------

class FanoutEngine:
    """One channel's shared deliver fan-out (see module docstring)."""

    def __init__(self, channel_id: str, ledger, acl,
                 ring_size: Optional[int] = None):
        if ring_size is None:
            ring_size = knobs.get_int("FABRIC_MOD_TPU_FANOUT_RING")
        self.channel_id = channel_id
        self._ledger = ledger
        self.stats: Dict[str, Dict[str, int]] = {
            form: {"materialized": 0, "encoded": 0, "ring_hits": 0,
                   "fallbacks": 0} for form in FORMS}
        self.cfg_memo = _ConfigMemo()
        self.fanouts: Dict[str, BlockFanout] = {
            form: BlockFanout(channel_id, ledger, form, ring_size,
                              self.stats[form],
                              classify=self.cfg_memo.classify)
            for form in FORMS}
        self.acl_groups = AclGroups(acl, channel_id)
        self.notifier = CommitNotifier(
            ledger.height_changed, lambda: ledger.height,
            name=f"deliver-{channel_id}")
        self.notifier.on_commit(self._on_commit)
        self._subs = {form: 0 for form in FORMS}
        self._subs_lock = RegisteredLock("peer.fanout.engine._subs_lock")

    # -- subscriber accounting (forms with no subscribers skip the
    #    eager per-commit materialization; on-demand fills cover joins)
    def attach(self, form: str) -> None:
        with self._subs_lock:
            self._subs[form] += 1

    def detach(self, form: str) -> None:
        with self._subs_lock:
            self._subs[form] -= 1

    def _on_commit(self, height: int) -> None:
        for form in FORMS:
            with self._subs_lock:
                active = self._subs[form] > 0
            if active:
                self.fanouts[form].materialize_upto(height)

    def get_frame(self, form: str, num: int) -> Optional[_Frame]:
        """One stream pulling its next frame; the chaos seam lives
        here so an injected stream death (deliver.fanout) kills THAT
        consumer only — the ring and every other stream are untouched."""
        faults.point("deliver.fanout")
        return self.fanouts[form].get(num)

    def close(self) -> None:
        self.notifier.close()
