"""Message crypto service: block signature verification for the peer.

(reference: internal/peer/gossip/mcs.go:124 `VerifyBlock` — data-hash
recomputation + orderer block-signature policy — consumed by the
deliver client at internal/pkg/peer/blocksprovider/blocksprovider.go:227
before a block may enter the commit queue.)

The signature check routes through the channel's
/Channel/Orderer/BlockValidation policy and the device batch verifier —
the first gossip-layer consumer of the batch crypto path (gossip-storm
batch verify, BASELINE config #5, starts here).
"""
from __future__ import annotations

from typing import Callable, Optional

from fabric_mod_tpu.channelconfig.bundle import Bundle
from fabric_mod_tpu.orderer.blockwriter import block_signed_data
from fabric_mod_tpu.policy.manager import CHANNEL_ORDERER_BLOCK_VALIDATION
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.protos.protoutil import SignedData


class BlockVerificationError(Exception):
    pass


class MessageCryptoService:
    """`bundle_fn` returns the channel's CURRENT bundle; `verifier` is
    the batch verify seam (TpuVerifier / FakeBatchVerifier)."""

    def __init__(self, bundle_fn: Callable[[], Bundle], verifier=None):
        self._bundle = bundle_fn
        self._verifier = verifier

    def verify_block(self, channel_id: str, block: m.Block,
                     expected_prev_hash: Optional[bytes] = None) -> None:
        """Raises BlockVerificationError unless the block is
        well-formed, hash-consistent, and signed per the orderer
        block-validation policy (reference: mcs.go:124)."""
        if block.header is None or block.data is None:
            raise BlockVerificationError("block missing header/data")
        if expected_prev_hash is not None and \
                block.header.previous_hash != expected_prev_hash:
            raise BlockVerificationError(
                f"block {block.header.number}: previous-hash mismatch")
        if protoutil.block_data_hash(block.data) != block.header.data_hash:
            raise BlockVerificationError(
                f"block {block.header.number}: data hash mismatch")

        md = block.metadata.metadata if block.metadata else []
        idx = m.BlockMetadataIndex.SIGNATURES
        if len(md) <= idx or not md[idx]:
            raise BlockVerificationError(
                f"block {block.header.number}: no signature metadata")
        try:
            meta = m.Metadata.decode(md[idx])
        except Exception as e:
            raise BlockVerificationError(f"bad signature metadata: {e}")
        sds = []
        for sig in meta.signatures:
            try:
                sh = m.SignatureHeader.decode(sig.signature_header)
            except Exception:
                continue
            sds.append(SignedData(
                data=block_signed_data(block, meta.value,
                                       sig.signature_header),
                identity=sh.creator, signature=sig.signature))
        if not sds:
            raise BlockVerificationError(
                f"block {block.header.number}: no usable signatures")

        bundle = self._bundle()
        pol = bundle.policy(CHANNEL_ORDERER_BLOCK_VALIDATION)
        if pol is None:
            raise BlockVerificationError(
                "no orderer BlockValidation policy in channel config")
        verify_many = (self._verifier.verify_many
                       if self._verifier is not None else None)
        if not pol.evaluate_signed_data(sds, verify_many):
            raise BlockVerificationError(
                f"block {block.header.number}: signature set does not "
                f"satisfy BlockValidation policy")
