"""Chaincode packages: build/parse/store.

(reference: core/chaincode/persistence — chaincode_package.go's
tar.gz format (metadata.json + code.tar.gz) and persistence.go's
package store keyed by package-id = label:sha256.)

Code payloads here are Python contract sources (the in-process
runtime's unit of distribution); the same envelope carries external-
builder artifacts later.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import re
import tarfile
from typing import List, Optional, Tuple


class PackageError(Exception):
    pass


def build_package(label: str, code: bytes,
                  cc_type: str = "python") -> bytes:
    """-> tar.gz bytes with metadata.json + code payload
    (reference: chaincode_package.go's two-member archive)."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        meta = json.dumps({"label": label, "type": cc_type},
                          sort_keys=True).encode()

        def add(name: str, data: bytes) -> None:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = 0                 # deterministic package id
            tar.addfile(info, io.BytesIO(data))
        add("metadata.json", meta)
        add("code.bin", code)
    return buf.getvalue()


def parse_package(raw: bytes) -> Tuple[str, str, bytes]:
    """-> (label, type, code) with the reference's validation rules
    (label required, exactly the two members)."""
    try:
        with tarfile.open(fileobj=io.BytesIO(raw), mode="r:gz") as tar:
            names = sorted(tar.getnames())
            if names != ["code.bin", "metadata.json"]:
                raise PackageError(
                    "package must contain exactly metadata.json "
                    f"+ code.bin, got {names}")
            meta = json.loads(
                tar.extractfile("metadata.json").read())
            code = tar.extractfile("code.bin").read()
    except PackageError:
        raise
    except Exception as e:
        raise PackageError(f"bad package: {e}") from e
    label = meta.get("label", "")
    if not _label_ok(label):
        raise PackageError(f"invalid label {label!r}")
    return label, meta.get("type", ""), code


_LABEL_RE = re.compile(r"^[a-zA-Z0-9]+([.+\-_][a-zA-Z0-9]+)*$")


def _label_ok(label: str) -> bool:
    """One label rule shared by parse and the store's id guard — the
    reference's regex: alnum runs joined by single . + - _ separators
    (no edge or consecutive separators)."""
    return bool(_LABEL_RE.fullmatch(label))


def package_id(label: str, raw: bytes) -> str:
    """label:sha256 (reference: persistence.go PackageID)."""
    return f"{label}:{hashlib.sha256(raw).hexdigest()}"


class PackageStore:
    """Installed-package store (reference: persistence.go Store)."""

    def __init__(self, dir_path: str):
        self._dir = dir_path
        os.makedirs(dir_path, exist_ok=True)

    @staticmethod
    def _validate_id(pkg_id: str) -> None:
        """Caller-supplied ids hit the filesystem: enforce the
        label:hexdigest shape (path-traversal guard)."""
        label, sep, digest = pkg_id.partition(":")
        if (not sep or len(digest) != 64
                or not all(c in "0123456789abcdef" for c in digest)
                or not _label_ok(label)):
            raise PackageError(f"invalid package id {pkg_id!r}")

    def _path(self, pkg_id: str) -> str:
        self._validate_id(pkg_id)
        return os.path.join(self._dir,
                            pkg_id.replace(":", ".") + ".tar.gz")

    def save(self, raw: bytes) -> str:
        label, _t, _code = parse_package(raw)
        pid = package_id(label, raw)
        path = self._path(pid)
        if not os.path.exists(path):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        return pid

    def load(self, pkg_id: str) -> Optional[bytes]:
        path = self._path(pkg_id)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def list(self) -> List[str]:
        out = []
        for name in sorted(os.listdir(self._dir)):
            if name.endswith(".tar.gz"):
                base = name[:-len(".tar.gz")]
                label, _, digest = base.rpartition(".")
                out.append(f"{label}:{digest}")
        return out
