"""The peer's gRPC endorsement surface: Endorser/ProcessProposal.

(reference: core/endorser — the peer's ProcessProposal gRPC service at
endorser.go:330, registered by internal/peer/node/start.go:205 — plus
the client side the chaincode CLI uses, internal/peer/chaincode/
common.go's EndorserClient.)

Wire contract: SignedProposal / ProposalResponse as this framework's
deterministic encodings over comm/grpc_comm's generic byte services.
"""
from __future__ import annotations

from typing import Iterator, Optional, Sequence

from fabric_mod_tpu.comm.grpc_comm import GRPCClient, GRPCServer, MethodKind
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil

SERVICE = "protos.Endorser"


class EndorserServer:
    """Binds an in-process Endorser to a gRPC listener."""

    def __init__(self, endorser, address: str = "127.0.0.1:0",
                 server_cert_pem: Optional[bytes] = None,
                 server_key_pem: Optional[bytes] = None,
                 client_root_pem: Optional[bytes] = None,
                 grpc: Optional[GRPCServer] = None):
        self._endorser = endorser
        # `grpc`: share one listener with the peer's other services
        # (events, admin) the way the reference registers everything on
        # the single peer server (internal/peer/node/start.go:205)
        self._owns_grpc = grpc is None
        self._grpc = grpc or GRPCServer(address, server_cert_pem,
                                        server_key_pem, client_root_pem)
        self.port = self._grpc.port
        self._grpc.register(SERVICE, "ProcessProposal",
                            MethodKind.UNARY, self._process)

    def start(self) -> None:
        if self._owns_grpc:
            self._grpc.start()

    def stop(self, grace: float = 1.0) -> None:
        if self._owns_grpc:
            self._grpc.stop(grace)

    def _process(self, request: bytes, _context) -> bytes:
        try:
            sp = m.SignedProposal.decode(request)
        except Exception as e:
            return m.ProposalResponse(response=m.Response(
                status=400, message=f"bad proposal: {e}")).encode()
        try:
            resp = self._endorser.process_proposal(sp)
        except Exception as e:
            resp = m.ProposalResponse(response=m.Response(
                status=500, message=str(e)))
        return resp.encode()


class RemoteEndorser:
    """Client-side view with the in-process Endorser's shape, so
    endorse_and_submit and the CLI are transport-agnostic
    (reference: the EndorserClient of internal/peer/common)."""

    def __init__(self, client: GRPCClient, timeout_s: float = 30.0):
        self._client = client
        self._timeout = timeout_s

    def process_proposal(self, sp: m.SignedProposal) -> m.ProposalResponse:
        raw = self._client.unary(SERVICE, "ProcessProposal",
                                 sp.encode(), timeout=self._timeout)
        return m.ProposalResponse.decode(raw)


def invoke_remote(channel_id: str, chaincode: str,
                  args: Sequence[bytes], client_signer,
                  endorsers: Sequence[RemoteEndorser], broadcaster,
                  transient=None) -> str:
    """proposal -> remote endorsements -> tx -> broadcast; the
    cross-process flavor of endorse_and_submit.  Raises if any
    endorsement failed."""
    from concurrent.futures import ThreadPoolExecutor
    sp, prop, tx_id = protoutil.create_chaincode_proposal(
        channel_id, chaincode, args, client_signer,
        transient=transient)
    # endorsements are independent: gather them concurrently so wall
    # time is the slowest peer, not the sum (the reference client
    # fans out the same way)
    with ThreadPoolExecutor(max_workers=max(1, len(endorsers))) as ex:
        responses = list(ex.map(
            lambda e: e.process_proposal(sp), endorsers))
    bad = [r for r in responses if r.response.status != 200]
    if bad:
        raise RuntimeError(
            f"endorsement failed: {bad[0].response.status} "
            f"{bad[0].response.message}")
    env = protoutil.create_tx_from_responses(prop, responses,
                                             client_signer)
    broadcaster.submit(env)
    return tx_id


def query_remote(channel_id: str, chaincode: str,
                 args: Sequence[bytes], client_signer,
                 endorser: RemoteEndorser) -> bytes:
    """Evaluate-only: one endorsement, never ordered (reference:
    `peer chaincode query`)."""
    sp, _prop, _tx_id = protoutil.create_chaincode_proposal(
        channel_id, chaincode, args, client_signer)
    resp = endorser.process_proposal(sp)
    if resp.response.status != 200:
        raise RuntimeError(f"query failed: {resp.response.status} "
                           f"{resp.response.message}")
    return resp.response.payload
