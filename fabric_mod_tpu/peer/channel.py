"""Per-channel peer wiring: bundle + validator + committer + deliver.

(reference: core/peer/peer.go:248 `createChannel` — the function that
assembles validator, committer, gossip state and config callbacks for
one channel — plus the channelconfig bundle-swap pattern of
common/channelconfig/bundlesource.go:103.)

The Channel owns the mutable piece (the current Bundle) and rebuilds
the per-bundle objects (policy evaluator, validator) atomically when a
CONFIG tx commits.  Everything downstream reads through `bundle()` /
`validator()` accessors so a block always validates under exactly one
config snapshot.
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional

from fabric_mod_tpu.channelconfig import (
    Bundle, ConfigTxError, extract_config_update, propose_config_update)
from fabric_mod_tpu.channelconfig.configtx import config_from_block
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.peer.mcs import MessageCryptoService
from fabric_mod_tpu.peer.txvalidator import (
    Committer, TxValidator, ValidationInfoProvider)
from fabric_mod_tpu.policy import ApplicationPolicyEvaluator
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.concurrency.locks import RegisteredLock

# Default endorsement policy reference when the namespace has none
# (reference: lifecycle's default /Channel/Application/Endorsement)
DEFAULT_ENDORSEMENT_REF = "/Channel/Application/Endorsement"

_REBUILD_OPTS = MetricOpts(
    "fabric", "commitpipe", "rebuilds_total",
    help="Poisoned commit pipelines discarded and rebuilt from the "
         "committed height (one bad block never bricks the channel).")


class Channel:
    """One channel on one peer (reference: core/peer/peer.go Channel)."""

    def __init__(self, channel_id: str, ledger, verifier, bundle: Bundle,
                 csp, vinfo: Optional[ValidationInfoProvider] = None,
                 plugin_registry=None):
        self.channel_id = channel_id
        self.ledger = ledger
        self.verifier = verifier
        self._verifier = verifier
        self._csp = csp
        self._plugin_registry = plugin_registry
        self._lock = RegisteredLock("peer.channel._lock")
        self._commit_pipe = None           # lazy; see commit_pipeline()
        self._shard_router = None          # set via use_shard_router()
        # serializes pipe (re)builds: never held by pipe worker
        # threads, so the unbounded drain-join inside cannot deadlock
        self._pipe_rebuild_lock = RegisteredLock("peer.channel._pipe_rebuild_lock")
        if vinfo is None:
            # lifecycle-backed: committed chaincode definitions resolve
            # each namespace's endorsement policy (peer/lifecycle.py)
            from fabric_mod_tpu.peer.lifecycle import LifecycleValidationInfo

            def state_get(ns: str, key: str):
                got = self.ledger.state.get_state(ns, key)
                return got[0] if got else None
            vinfo = LifecycleValidationInfo(
                state_get,
                m.ApplicationPolicy(
                    channel_config_policy_reference=DEFAULT_ENDORSEMENT_REF
                ).encode())
        self._vinfo = vinfo
        self.mcs = MessageCryptoService(self.bundle, verifier)
        # private data plumbing (reference: transientstore + the
        # privdata coordinator wiring of peer.go createChannel); on a
        # durable ledger both stores are durable too — committed
        # private plaintext and the pending-reconciliation index
        # survive restarts (reference: pvtdatastorage/store.go,
        # transientstore/store.go are leveldb instances)
        import os as _os
        from fabric_mod_tpu.ledger.pvtdata import (
            PvtDataStore, TransientStore)
        pvt_root = (ledger.dir if getattr(ledger, "_durable", False)
                    else None)
        self.transient_store = TransientStore(
            dir_path=(_os.path.join(pvt_root, "transient")
                      if pvt_root else None))
        self.pvtdata_store = PvtDataStore(
            dir_path=(_os.path.join(pvt_root, "pvtdata")
                      if pvt_root else None))
        self.ledger.attach_pvt(self.transient_store, self.pvtdata_store,
                               self._collection_btl)
        self._install_bundle(bundle)

    def _static_collection_config(self, ns: str, collection: str):
        """The committed StaticCollectionConfig for (chaincode,
        collection), or None (reference: privdata's collection-config
        retrieval from the lifecycle definition)."""
        from fabric_mod_tpu.peer.lifecycle import (
            LIFECYCLE_NS, definition_key)
        got = self.ledger.state.get_state(LIFECYCLE_NS,
                                          definition_key(ns))
        if got is None:
            return None
        try:
            d = m.ChaincodeDefinition.decode(got[0])
            pkg = m.CollectionConfigPackage.decode(d.collections)
        except Exception:
            return None
        for cc in pkg.config:
            sc = cc.static_collection_config
            if sc is not None and sc.name == collection:
                return sc
        return None

    def collection_policy(self, ns: str, collection: str):
        """member_orgs_policy (SignaturePolicyEnvelope) of a committed
        collection config, or None."""
        sc = self._static_collection_config(ns, collection)
        return sc.member_orgs_policy if sc is not None else None

    def _collection_btl(self, ns: str, collection: str) -> int:
        """BTL from the committed chaincode definition's collection
        configs (reference: the BTL policy of pvtstatepurgemgmt)."""
        sc = self._static_collection_config(ns, collection)
        return sc.block_to_live if sc is not None else 0

    # -- bundle lifecycle -------------------------------------------------
    def _install_bundle(self, bundle: Bundle) -> None:
        # second-chance caches around the bundle's MSP manager
        # (reference: msp/cache/cache.go): the validator's pass-1
        # staging deserializes + chain-validates the SAME handful of
        # creator/endorser identities for every tx of every block —
        # cache them per bundle.  A config update swaps the bundle,
        # builds a fresh manager, and therefore starts cold: revoked
        # or re-rooted identities can never be served from a previous
        # epoch's cache.
        from fabric_mod_tpu.msp.cache import CachedMsp
        cached_mgr = CachedMsp(bundle.msp_manager)
        policy_eval = ApplicationPolicyEvaluator(
            cached_mgr, bundle.policy_manager,
            sequence=bundle.sequence)
        def state_vp(ns: str, key: str):
            meta = self.ledger.state.get_metadata(ns, key)
            if meta:
                from fabric_mod_tpu.peer.txvalidator import (
                    VALIDATION_PARAMETER)
                return meta.get(VALIDATION_PARAMETER)
            return None

        validator = TxValidator(
            self.channel_id, cached_mgr, policy_eval,
            self._verifier, self._vinfo,
            tx_id_exists=self.ledger.tx_id_exists,
            config_apply=self._validate_and_apply_config,
            state_metadata=state_vp,
            plugin_registry=self._plugin_registry,
            config_sequence=bundle.sequence)
        with self._lock:
            self._bundle = bundle
            self._validator = validator

    def bundle(self) -> Bundle:
        with self._lock:
            return self._bundle

    def validator(self) -> TxValidator:
        with self._lock:
            return self._validator

    # -- config tx path ---------------------------------------------------
    def _validate_and_apply_config(self, env: m.Envelope) -> None:
        """Re-validate an ordered CONFIG envelope against the current
        bundle and adopt it (reference: validator.go:400-421 +
        configtx validator Validate).  Called from inside block
        validation; raising marks the tx INVALID_CONFIG_TRANSACTION."""
        payload = protoutil.unmarshal_envelope_payload(env)
        cenv = m.ConfigEnvelope.decode(payload.data)
        if cenv.config is None:
            raise ConfigTxError("config envelope carries no config")
        bundle = self.bundle()
        if cenv.last_update is None:
            raise ConfigTxError("config envelope carries no last_update")
        cue = extract_config_update(cenv.last_update)
        verify_many = (self._verifier.verify_many
                       if self._verifier is not None else None)
        computed = propose_config_update(bundle, cue, verify_many)
        if computed.encode() != cenv.config.encode():
            raise ConfigTxError(
                "ordered config does not match the one computed from "
                "last_update under the current bundle")
        self._install_bundle(Bundle(self.channel_id, computed, self._csp))

    def init_from_genesis(self, genesis_block: m.Block) -> List[int]:
        """Commit block 0 (already validated out-of-band: genesis is
        the trust anchor, reference: peer channel join)."""
        flags = [m.TxValidationCode.VALID] * len(genesis_block.data.data)
        protoutil.set_block_txflags(genesis_block, bytes(flags))
        return self.ledger.commit_block(genesis_block, flags)

    # -- commit path ------------------------------------------------------
    def store_block(self, block: m.Block) -> List[int]:
        """validate -> MVCC -> commit (the reference's coordinator
        StoreBlock composition, gossip/state/state.go:817).

        With FABRIC_MOD_TPU_COMMIT_PIPELINE set, the commit routes
        through the channel's shared PipelinedCommitter: this call is
        still synchronous (waits for THIS block's commit, returns its
        final flags), but overlapping callers pipeline — stage(N+1)
        proceeds while commit(N) runs."""
        pipe = self.commit_pipeline()
        if pipe is not None:
            try:
                return pipe.store_block(block)
            except Exception:
                # the failure may be INHERITED — a pipe another
                # caller's block poisoned (sticky error) or closed
                # under us mid-rebuild.  One retry through a fresh
                # pipe separates that from this block's own error:
                # an own-error block fails again with the real cause,
                # and a gate rejection returns the SAME healthy pipe
                # so we re-raise without a pointless resubmit.
                retry = self.commit_pipeline()
                if retry is None or retry is pipe:
                    raise
                return retry.store_block(block)
        flags = self.validator().validate(block)
        return self.ledger.commit_block(block, flags)

    def use_shard_router(self, router) -> None:
        """Bind this channel to a ChannelShardRouter (sharding/):
        commit_pipeline() then delegates to the router's slice-pinned
        engine — the router carries the same rebuild-on-poison
        contract, plus placement.  The router must already hold this
        channel (add_channel); binding is one-way for the channel's
        lifetime (unbinding mid-stream would race two engines onto
        one ledger).  A knob-built pipe that predates the binding is
        DRAINED here first, for the same reason — and the router
        target binds only AFTER that drain, so a direct router caller
        (submit_block/pipeline_for) cannot build the slice engine
        while the old one still commits."""
        with self._pipe_rebuild_lock:
            with self._lock:
                old, self._commit_pipe = self._commit_pipe, None
            if old is not None:
                old.close()
            # only after the old engine fully drained: from here on
            # the router may build, and every commit_pipeline() caller
            # gets, the slice-pinned engine
            router.bind_target(self.channel_id, self)
            with self._lock:
                self._shard_router = router

    def commit_pipeline(self):
        """The channel's shared PipelinedCommitter when the
        FABRIC_MOD_TPU_COMMIT_PIPELINE knob enables one (or a shard
        router is bound — router-bound channels always pipeline,
        pinned to their slice), else None.
        Shared so every commit producer on this channel (gossip drain,
        store_block callers) feeds ONE in-order pipeline.

        A failed pipeline is sticky only until its error has been
        surfaced: the caller that hit it gets the exception (from
        submit/wait), and the next call here discards the poisoned
        pipe and builds a fresh one from the committed height — the
        retry semantics the synchronous path always had (one bad
        block never bricks the channel).  The rebuild fully drains
        the old engine FIRST (unbounded close, outside self._lock so
        an in-flight config_apply can still take it) — two engines
        never run against the ledger at once."""
        with self._lock:
            router = self._shard_router
        if router is not None:
            return router.pipeline_for(self.channel_id)
        from fabric_mod_tpu.peer.commitpipe import pipeline_depth
        depth = pipeline_depth()
        if depth <= 0:
            return None
        def healthy():
            with self._lock:
                pipe = self._commit_pipe
            return pipe if (pipe is not None and pipe.error is None
                            and not pipe.closed) else None
        pipe = healthy()
        if pipe is not None:
            return pipe                    # hot path: no rebuild lock
        with self._pipe_rebuild_lock:
            with self._lock:
                router = self._shard_router
            if router is not None:
                # a use_shard_router() bind landed while we waited on
                # the rebuild lock: building a knob pipe now would put
                # a second engine on the ledger — delegate instead
                return router.pipeline_for(self.channel_id)
            pipe = healthy()
            if pipe is not None:
                return pipe                # another caller rebuilt
            with self._lock:
                old, self._commit_pipe = self._commit_pipe, None
            if old is not None:
                old.close()                # join until the engine died
                # crash-resume observability: a discarded poisoned
                # engine is the channel's recovery event — a nonzero
                # rate here is the ops signal that blocks are failing
                # and being re-driven through fresh pipes
                default_provider().counter(_REBUILD_OPTS).add(1)
            from fabric_mod_tpu.peer.commitpipe import PipelinedCommitter
            pipe = PipelinedCommitter(self, depth=depth,
                                      consumer="channel")
            with self._lock:
                self._commit_pipe = pipe
            return pipe

    # pipelined split: stage (host unpack + async device dispatch) may
    # run ahead of the previous block's commit; commit_staged resolves
    # the verdicts and commits.  `staged.needs_barrier` tells the
    # pipeline when staging must NOT run ahead (config / vp-write /
    # lifecycle blocks).
    def stage_block(self, block: m.Block):
        return self.validator().stage(block)

    def commit_staged(self, staged) -> List[int]:
        # finish on the validator that staged: its pending evaluators
        # hold that validator's batch slots
        flags = staged.validator.finish(staged)
        return self.ledger.commit_block(
            staged.block, flags,
            rwsets=getattr(staged, "rwsets", None))

    def committer(self) -> Committer:
        return _ChannelCommitter(self)


class _ChannelCommitter:
    """Committer facade bound to the channel's CURRENT validator."""

    def __init__(self, channel: Channel):
        self._channel = channel

    def store_block(self, block: m.Block) -> List[int]:
        return self._channel.store_block(block)
