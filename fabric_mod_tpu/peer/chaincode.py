"""In-process chaincode runtime: contracts, stub, registry.

(reference: core/chaincode/chaincode_support.go:193 `Execute` and the
shim message protocol handler.go:180-202 HandleGetState/HandlePutState
— here the container+gRPC stream machinery collapses to a direct call:
a contract is a Python object invoked against a stub bound to a
TxSimulator.  The registry is the launch cache; external processes can
ride behind the same seam later, exactly like the reference's external
builders.)
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Protocol


class ChaincodeError(Exception):
    pass


class ChaincodeStub:
    """What a contract sees (reference: the shim's stub API surface —
    GetState/PutState/DelState/GetStateByRange over the tx simulator,
    which records the read-write set)."""

    def __init__(self, namespace: str, simulator, args: List[bytes],
                 txid: str, channel_id: str,
                 transient: Optional[Dict[str, bytes]] = None,
                 creator: bytes = b""):
        self.namespace = namespace
        self._sim = simulator
        self.args = args
        self.txid = txid
        self.channel_id = channel_id
        # side-channel inputs; never part of the ordered tx
        # (reference: the shim's GetTransient)
        self.transient = dict(transient or {})
        # serialized creator identity (reference: shim GetCreator)
        self.creator = creator
        # at most one event per tx (reference: shim SetEvent —
        # handler.go overwrites on repeat calls)
        self.event = None               # (name, payload) | None

    def set_event(self, name: str, payload: bytes = b"") -> None:
        """Attach a chaincode event to this tx's action; delivered to
        event listeners on commit (payload stripped on the filtered
        stream)."""
        if not name:
            raise ValueError("event name must be non-empty")
        self.event = (name, payload)

    def creator_mspid(self) -> str:
        """MSP id of the proposal creator ('' when unavailable)."""
        from fabric_mod_tpu.protos import messages as _m
        try:
            return _m.SerializedIdentity.decode(self.creator).mspid
        except Exception:
            return ""

    def get_state(self, key: str) -> Optional[bytes]:
        return self._sim.get_state(self.namespace, key)

    def put_state(self, key: str, value: bytes) -> None:
        self._sim.set_state(self.namespace, key, value)

    def del_state(self, key: str) -> None:
        self._sim.delete_state(self.namespace, key)

    def get_query_result(self, query):
        """Rich JSON-selector query (reference: the shim's
        GetQueryResult; handler.go HandleGetQueryResult).  Returns
        ([(key, doc)], bookmark)."""
        return self._sim.execute_query(self.namespace, query)

    def get_state_range(self, start: str, end: str):
        return self._sim.get_state_range(self.namespace, start, end)

    def set_state_metadata(self, key: str, name: str, value: bytes) -> None:
        """(reference: shim PutStateMetadata — e.g. key-level
        endorsement via the VALIDATION_PARAMETER entry)"""
        self._sim.set_state_metadata(self.namespace, key, name, value)

    # -- private data (reference: shim PutPrivateData/GetPrivateData) --
    def put_private_data(self, collection: str, key: str,
                         value: bytes) -> None:
        self._sim.set_private_data(self.namespace, collection, key, value)

    def get_private_data(self, collection: str, key: str):
        return self._sim.get_private_data(self.namespace, collection, key)

    def del_private_data(self, collection: str, key: str) -> None:
        self._sim.delete_private_data(self.namespace, collection, key)


class Contract(Protocol):
    def invoke(self, stub: ChaincodeStub) -> bytes: ...


class ChaincodeRegistry:
    """name -> contract (reference: the launch registry + system
    chaincode table, core/scc/scc.go)."""

    def __init__(self):
        self._contracts: Dict[str, Contract] = {}
        self._resolver: Optional[Callable[[str], Optional[Contract]]] = None

    def register(self, name: str, contract: Contract) -> None:
        self._contracts[name] = contract

    def set_resolver(self, resolver) -> None:
        """Miss handler (reference: the Launch-on-first-use path of
        chaincode_support.go:93 — the ChaincodeLauncher plugs in
        here).  A non-None result is cached; None is NOT, so a
        chaincode installed later becomes resolvable — misses must
        therefore be cheap (the launcher's miss is one listdir)."""
        self._resolver = resolver

    def get(self, name: str) -> Optional[Contract]:
        cc = self._contracts.get(name)
        if cc is None and self._resolver is not None:
            cc = self._resolver(name)
            if cc is not None:
                self._contracts[name] = cc
        return cc

    def execute(self, name: str, stub: ChaincodeStub) -> bytes:
        cc = self.get(name)
        if cc is None:
            raise ChaincodeError(f"chaincode {name!r} not installed")
        return cc.invoke(stub)


class FuncContract:
    """Adapter: plain function(stub) -> bytes as a contract."""

    def __init__(self, fn: Callable[[ChaincodeStub], bytes]):
        self._fn = fn

    def invoke(self, stub: ChaincodeStub) -> bytes:
        return self._fn(stub)


class KvContract:
    """The classic example contract: args [op, key, value?] with
    put/get/del — enough to drive the e2e pipeline and tests."""

    def invoke(self, stub: ChaincodeStub) -> bytes:
        if not stub.args:
            raise ChaincodeError("no args")
        op = stub.args[0].decode()
        if op == "put":
            stub.put_state(stub.args[1].decode(), stub.args[2])
            return b"ok"
        if op == "get":
            val = stub.get_state(stub.args[1].decode())
            return val if val is not None else b""
        if op == "del":
            stub.del_state(stub.args[1].decode())
            return b"ok"
        if op == "putev":
            # put + a chaincode event (drives the event deliver tests)
            stub.put_state(stub.args[1].decode(), stub.args[2])
            stub.set_event("kv-put", stub.args[1])
            return b"ok"
        if op == "setvp":
            # key-level endorsement override (state-based endorsement,
            # reference: integration/sbe suites)
            stub.set_state_metadata(stub.args[1].decode(),
                                    "VALIDATION_PARAMETER", stub.args[2])
            return b"ok"
        if op == "query":
            # rich query: args[1] = Mango query JSON; returns the
            # matches as a JSON array of {key, doc} (the marbles
            # queryMarblesByOwner pattern)
            import json
            results, bookmark = stub.get_query_result(stub.args[1])
            return json.dumps(
                {"results": [{"key": k, "doc": d} for k, d in results],
                 "bookmark": bookmark}).encode()
        if op == "putpvt":
            # value arrives via the transient map so it never lands in
            # the ordered tx (reference: the pvt marbles pattern)
            value = stub.transient.get("value")
            if value is None:
                raise ChaincodeError("putpvt needs transient 'value'")
            stub.put_private_data(stub.args[1].decode(),
                                  stub.args[2].decode(), value)
            return b"ok"
        if op == "getpvt":
            val = stub.get_private_data(stub.args[1].decode(),
                                        stub.args[2].decode())
            return val if val is not None else b""
        raise ChaincodeError(f"unknown op {op!r}")
