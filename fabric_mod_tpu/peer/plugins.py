"""Named validation-plugin registry.

(reference: core/handlers/library/registry.go:79 — the registry that
maps plugin names from chaincode definitions to validation plugin
factories — and core/handlers/validation/api's plugin contract.)

The contract here is batch-first, matching this framework's validator
pipeline: a plugin is a factory returning an EVALUATOR with

    prepare(policy_bytes, signed_datas, collector) -> pending

where `pending.finish(device_mask) -> bool` delivers the verdict after
the shared device dispatch — exactly the shape of
policy/application.ApplicationPolicyEvaluator, which backs the
built-in ``vscc``.  A definition naming an UNREGISTERED plugin fails
closed: its txs are marked INVALID_OTHER_REASON (the reference marks
txs invalid when the mapped plugin is missing).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

BUILTIN_VSCC = "vscc"


class PluginRegistry:
    """name -> evaluator factory; the factory runs ONCE per name and
    the evaluator instance is cached (resolve() sits on the per-action
    validation hot path, and stateful plugins keep their state)."""

    def __init__(self):
        self._factories: Dict[str, Callable[[], object]] = {}
        self._instances: Dict[str, object] = {}

    def register(self, name: str,
                 factory: Callable[[], object]) -> None:
        if name == BUILTIN_VSCC:
            raise ValueError("'vscc' is the built-in policy evaluator")
        self._factories[name] = factory
        self._instances.pop(name, None)

    def names(self):
        return sorted([BUILTIN_VSCC] + list(self._factories))

    def resolve(self, name: str, builtin) -> Optional[object]:
        """The evaluator for `name`; `builtin` backs ``vscc`` (and an
        empty name, which definitions may omit).  None for an unknown
        plugin — the caller fails the tx closed."""
        if name in ("", BUILTIN_VSCC):
            return builtin
        got = self._instances.get(name)
        if got is not None:
            return got
        factory = self._factories.get(name)
        if factory is None:
            return None
        got = self._instances[name] = factory()
        return got
