"""Config-update validation and application.

(reference: common/configtx/validator.go:212 `ValidatorImpl` —
ProposeConfigUpdate/Validate — with update.go:203's authorizeUpdate and
the configmap delta model in update.go/compare.go.)

The model: a CONFIG_UPDATE carries a read_set (elements it depends on,
pinned at their current versions) and a write_set (elements it
changes, each with version = current+1).  Validation is:

  1. read_set versions must match the current config exactly;
  2. every element of the write_set either equals the current element
     (same version, identical bytes — context carried along) or bumps
     its version by exactly one (modified) or is new (version 0);
  3. each modified/new element's mod_policy — resolved against the
     CURRENT bundle's policy tree — must be satisfied by the update's
     signature set;
  4. the result is current-config-with-write-set-merged, sequence+1.

Policy checks run through the two-phase batch evaluators, so a config
tx's signatures ride the same device verify path as everything else.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from fabric_mod_tpu.channelconfig.bundle import (
    Bundle, ConfigError, groups_of, policies_of, set_group, set_policy,
    set_value, values_of)
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.protos.protoutil import SignedData


class ConfigTxError(Exception):
    pass


# -- read_set verification --------------------------------------------------

def _verify_read_set(cur: Optional[m.ConfigGroup],
                     rs: m.ConfigGroup, path: str) -> None:
    if cur is None:
        raise ConfigTxError(f"read_set references missing group {path}")
    if rs.version != cur.version:
        raise ConfigTxError(
            f"read_set version mismatch at {path}: "
            f"{rs.version} != {cur.version}")
    cur_groups = groups_of(cur)
    for key, sub in groups_of(rs).items():
        _verify_read_set(cur_groups.get(key), sub, f"{path}/{key}")
    for kind, accessor in (("value", values_of), ("policy", policies_of)):
        cur_items = accessor(cur)
        for key, item in accessor(rs).items():
            if key not in cur_items:
                raise ConfigTxError(
                    f"read_set references missing {kind} {path}/{key}")
            if item.version != cur_items[key].version:
                raise ConfigTxError(
                    f"read_set {kind} version mismatch at {path}/{key}")


# -- write_set delta + merge ------------------------------------------------

class _Change:
    """One modified/new element and the policy that must authorize it."""

    __slots__ = ("path", "mod_policy", "policy_path")

    def __init__(self, path: str, mod_policy: str, policy_path: str):
        self.path = path              # for error messages
        self.mod_policy = mod_policy  # name as written in config
        self.policy_path = policy_path  # resolved lookup path


def _resolve_policy_path(mod_policy: str, group_path: str) -> str:
    """mod_policy names resolve relative to their group unless absolute
    (reference: common/policies/util.go + update.go policyForItem)."""
    if not mod_policy:
        return ""
    if mod_policy.startswith("/"):
        return mod_policy
    return f"{group_path}/{mod_policy}"


def _merge_group(cur: Optional[m.ConfigGroup], wr: m.ConfigGroup,
                 group_path: str, changes: List[_Change]) -> m.ConfigGroup:
    """Return the merged group; record every version-bumped element.

    `group_path` is the policy-manager path of THIS group (e.g.
    "/Channel/Application").  Group mod-policies resolve against the
    group's own path; value/policy mod-policies against their
    containing group (reference: update.go policyForItem).
    """
    if cur is None:
        # brand-new group: authorized via its own mod_policy resolved at
        # this path — which must exist in the CURRENT tree's ancestors
        # (fail-closed: empty mod_policy on a new element is an error)
        if wr.version != 0:
            raise ConfigTxError(
                f"new group {group_path} must have version 0")
        changes.append(_Change(
            group_path, wr.mod_policy,
            _resolve_policy_path(wr.mod_policy, group_path)))
        cur = m.ConfigGroup()

    out = m.ConfigGroup(version=wr.version, mod_policy=wr.mod_policy or
                        cur.mod_policy)
    bumped = wr.version == cur.version + 1
    if bumped:
        changes.append(_Change(
            group_path, cur.mod_policy,
            _resolve_policy_path(cur.mod_policy, group_path)))
    elif wr.version != cur.version:
        raise ConfigTxError(
            f"group {group_path}: version {wr.version} vs current "
            f"{cur.version} (must be same or +1)")

    # Merge: write_set entries overlay the current contents.  A
    # version-bumped group's membership is authoritative — elements it
    # omits are REMOVED (the reference's configmap unflattening); an
    # unbumped group only carries context, so omissions persist.
    cur_groups, wr_groups = groups_of(cur), groups_of(wr)
    for key in sorted(set(cur_groups) | set(wr_groups)):
        if key in wr_groups:
            merged = _merge_group(cur_groups.get(key), wr_groups[key],
                                  f"{group_path}/{key}", changes)
            set_group(out, key, merged)
        elif not bumped:
            set_group(out, key, cur_groups[key])

    for kind, accessor, setter in (("value", values_of, set_value),
                                   ("policy", policies_of, set_policy)):
        cur_items = accessor(cur)
        wr_items = accessor(wr)
        for key in sorted(set(cur_items) | set(wr_items)):
            path = f"{group_path}/{key}"
            if key not in wr_items:
                if not bumped:
                    setter(out, key, cur_items[key])
                continue
            item = wr_items[key]
            cur_item = cur_items.get(key)
            if cur_item is None:
                if item.version != 0:
                    raise ConfigTxError(
                        f"new {kind} {path} must have version 0")
                changes.append(_Change(
                    path, item.mod_policy,
                    _resolve_policy_path(item.mod_policy, group_path)))
            elif item.version == cur_item.version + 1:
                changes.append(_Change(
                    path, cur_item.mod_policy,
                    _resolve_policy_path(cur_item.mod_policy, group_path)))
            elif item.version == cur_item.version:
                if item.encode() != cur_item.encode():
                    raise ConfigTxError(
                        f"{kind} {path} changed without version bump")
            else:
                raise ConfigTxError(
                    f"{kind} {path}: version {item.version} vs current "
                    f"{cur_item.version}")
            setter(out, key, item)
    return out


# -- the validator entry points ---------------------------------------------

def _update_signature_set(cue: m.ConfigUpdateEnvelope) -> List[SignedData]:
    """(reference: configtx/update.go:203 — signed data is
    signature_header ‖ config_update per signature)"""
    sds = []
    for sig in cue.signatures:
        try:
            sh = m.SignatureHeader.decode(sig.signature_header)
        except Exception:
            continue
        sds.append(SignedData(
            data=sig.signature_header + cue.config_update,
            identity=sh.creator, signature=sig.signature))
    return sds


def propose_config_update(bundle: Bundle, cue: m.ConfigUpdateEnvelope,
                          verify_many=None) -> m.Config:
    """Validate a ConfigUpdateEnvelope against `bundle`; return the new
    Config to adopt (reference: validator.go ProposeConfigUpdate)."""
    if not cue.config_update:
        raise ConfigTxError("empty config update")
    try:
        cu = m.ConfigUpdate.decode(cue.config_update)
    except Exception as e:
        raise ConfigTxError(f"bad ConfigUpdate: {e}") from e
    if cu.channel_id != bundle.channel_id:
        raise ConfigTxError(
            f"config update for channel {cu.channel_id!r}, "
            f"expected {bundle.channel_id!r}")
    if cu.write_set is None:
        raise ConfigTxError("config update has no write_set")
    if cu.read_set is not None:
        _verify_read_set(bundle.config.channel_group, cu.read_set,
                         "/Channel")

    changes: List[_Change] = []
    merged = _merge_group(bundle.config.channel_group, cu.write_set,
                          "/Channel", changes)
    if not changes:
        raise ConfigTxError("config update changes nothing")

    sds = _update_signature_set(cue)
    for ch in changes:
        if not ch.policy_path:
            raise ConfigTxError(
                f"element {ch.path} has no mod_policy (fail-closed)")
        pol = bundle.policy_manager.get_policy(ch.policy_path)
        if pol is None:
            raise ConfigTxError(
                f"mod_policy {ch.policy_path!r} for {ch.path} not found")
        if not pol.evaluate_signed_data(sds, verify_many):
            raise ConfigTxError(
                f"mod_policy {ch.policy_path!r} rejected change to "
                f"{ch.path}")
    return m.Config(sequence=bundle.sequence + 1, channel_group=merged)


def config_from_block(block: m.Block) -> Tuple[str, m.Config]:
    """Extract (channel_id, Config) from a CONFIG block (genesis or
    later) — reference: protoutil/configtxutils + bundle re-creation on
    commit (txvalidator/v20/validator.go:400-421)."""
    envs = protoutil.get_envelopes(block)
    if len(envs) != 1:
        raise ConfigTxError("config block must carry exactly one tx")
    payload = protoutil.unmarshal_envelope_payload(envs[0])
    ch = m.ChannelHeader.decode(payload.header.channel_header)
    if ch.type != m.HeaderType.CONFIG:
        raise ConfigTxError("not a CONFIG envelope")
    cenv = m.ConfigEnvelope.decode(payload.data)
    if cenv.config is None:
        raise ConfigTxError("CONFIG envelope has no config")
    return ch.channel_id, cenv.config


def extract_config_update(env: m.Envelope) -> m.ConfigUpdateEnvelope:
    """Unwrap a CONFIG_UPDATE envelope (client-submitted)."""
    payload = protoutil.unmarshal_envelope_payload(env)
    ch = m.ChannelHeader.decode(payload.header.channel_header)
    if ch.type != m.HeaderType.CONFIG_UPDATE:
        raise ConfigTxError("not a CONFIG_UPDATE envelope")
    return m.ConfigUpdateEnvelope.decode(payload.data)
