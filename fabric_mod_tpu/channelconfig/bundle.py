"""Typed, immutable view of a channel's on-ledger configuration.

(reference: common/channelconfig/bundle.go `Bundle` — the materialized
config-tx view every service consults — plus api.go:262's typed
Application/Orderer/Channel accessors.)

A Bundle is built once from a `Config` proto tree and never mutated;
config updates produce a NEW bundle that is atomically swapped in by
whoever owns the reference (registrar, validator) — the reference's
bundlesource.go:103 callback pattern.  That immutability is what makes
the commit path safe to pipeline: a block validates against exactly one
bundle snapshot.

The policy tree and MSP manager are materialized here so every consumer
shares one compiled form: signature policies compile to the two-phase
batch-first evaluators of policy/cauthdsl.py (the device-batch seam),
implicit meta policies resolve over the group tree exactly like
common/policies/implicitmeta.go.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

try:
    from cryptography import x509
except ImportError:
    # Wheel-less container: minimal DER x509 fallback (see
    # bccsp/_x509fallback.py; bccsp/sw.py logged the downgrade).
    from fabric_mod_tpu.bccsp import _x509fallback as x509

from fabric_mod_tpu.msp.mspimpl import Msp, MspManager, NodeOUs
from fabric_mod_tpu.policy.cauthdsl import PolicyError
from fabric_mod_tpu.policy.manager import PolicyManager
from fabric_mod_tpu.protos import messages as m

# Canonical group / value keys (reference: common/channelconfig/api.go)
APPLICATION = "Application"
ORDERER = "Orderer"
MSP_KEY = "MSP"
BATCH_SIZE = "BatchSize"
BATCH_TIMEOUT = "BatchTimeout"
CONSENSUS_TYPE = "ConsensusType"
CAPABILITIES = "Capabilities"
HASHING_ALGORITHM = "HashingAlgorithm"
BLOCK_DATA_HASHING_STRUCTURE = "BlockDataHashingStructure"
ORDERER_ADDRESSES = "OrdererAddresses"

BLOCK_VALIDATION_POLICY = "BlockValidation"


class ConfigError(Exception):
    pass


# -- map-style accessors over the repeated entry encoding -------------------

def groups_of(g: m.ConfigGroup) -> Dict[str, m.ConfigGroup]:
    return {e.key: e.value for e in g.groups if e.value is not None}


def values_of(g: m.ConfigGroup) -> Dict[str, m.ConfigValue]:
    return {e.key: e.value for e in g.values if e.value is not None}


def policies_of(g: m.ConfigGroup) -> Dict[str, m.ConfigPolicy]:
    return {e.key: e.value for e in g.policies if e.value is not None}


def set_group(g: m.ConfigGroup, key: str, sub: m.ConfigGroup) -> None:
    g.groups = [e for e in g.groups if e.key != key]
    g.groups.append(m.ConfigGroupEntry(key=key, value=sub))
    g.groups.sort(key=lambda e: e.key)


def set_value(g: m.ConfigGroup, key: str, val: m.ConfigValue) -> None:
    g.values = [e for e in g.values if e.key != key]
    g.values.append(m.ConfigValueEntry(key=key, value=val))
    g.values.sort(key=lambda e: e.key)


def set_policy(g: m.ConfigGroup, key: str, pol: m.ConfigPolicy) -> None:
    g.policies = [e for e in g.policies if e.key != key]
    g.policies.append(m.ConfigPolicyEntry(key=key, value=pol))
    g.policies.sort(key=lambda e: e.key)


# -- MSP materialization ----------------------------------------------------

def msp_from_config(conf: m.MSPConfig, csp) -> Msp:
    """FabricMSPConfig -> live Msp (reference: msp/configbuilder.go +
    mspimplsetup.go — certs, CRLs, NodeOUs)."""
    if conf.type != 0:
        raise ConfigError(f"unsupported MSP type {conf.type}")
    f = m.FabricMSPConfig.decode(conf.config)
    if not f.name or not f.root_certs:
        raise ConfigError("MSP config needs a name and root certs")
    roots = [x509.load_pem_x509_certificate(c) for c in f.root_certs]
    inters = [x509.load_pem_x509_certificate(c)
              for c in f.intermediate_certs]
    admins = [x509.load_pem_x509_certificate(c) for c in f.admins]
    crls = [x509.load_der_x509_crl(c) for c in f.revocation_list]
    node_ous = None
    if f.fabric_node_ous is not None and f.fabric_node_ous.enable:
        nu = f.fabric_node_ous

        def ou(ident, default):
            return (ident.organizational_unit_identifier
                    if ident is not None and
                    ident.organizational_unit_identifier else default)
        node_ous = NodeOUs(
            enable=True,
            client_ou=ou(nu.client_ou_identifier, "client"),
            peer_ou=ou(nu.peer_ou_identifier, "peer"),
            admin_ou=ou(nu.admin_ou_identifier, "admin"),
            orderer_ou=ou(nu.orderer_ou_identifier, "orderer"))
    return Msp(f.name, csp, roots, inters, admins, crls=crls,
               node_ous=node_ous)


# -- typed sections ---------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class OrdererConfig:
    """(reference: channelconfig/orderer.go OrdererConfig)"""
    batch_size: m.BatchSize
    batch_timeout_s: float
    consensus_type: str
    consensus_metadata: bytes           # consenter set etc. (reference:
    #                                     ConsensusType.Metadata)
    org_mspids: Tuple[str, ...]
    capabilities: Tuple[str, ...]

    def consenters(self) -> Tuple[str, ...]:
        """Raft consenter node ids from the consensus metadata
        (reference: etcdraft.ConfigMetadata's consenter list); empty
        when the channel predates/omits the metadata."""
        if not self.consensus_metadata:
            return ()
        try:
            md = m.RaftMetadata.decode(self.consensus_metadata)
        except Exception:
            return ()
        return tuple(md.consenters)


@dataclasses.dataclass(frozen=True)
class ApplicationConfig:
    """(reference: channelconfig/application.go ApplicationConfig)"""
    org_mspids: Tuple[str, ...]
    capabilities: Tuple[str, ...]


def _parse_timeout(s: str) -> float:
    """Duration strings the way the reference's yaml uses them: "2s",
    "500ms", "1m"."""
    s = s.strip()
    for suffix, mult in (("ms", 1e-3), ("s", 1.0), ("m", 60.0)):
        if s.endswith(suffix):
            return float(s[:-len(suffix)]) * mult
    return float(s)


def _capabilities(values: Dict[str, m.ConfigValue]) -> Tuple[str, ...]:
    cv = values.get(CAPABILITIES)
    if cv is None:
        return ()
    caps = m.Capabilities.decode(cv.value)
    return tuple(e.key for e in caps.capabilities)


# -- the bundle -------------------------------------------------------------

class Bundle:
    """Immutable channel config snapshot: raw tree + typed views +
    policy/MSP managers (reference: channelconfig/bundle.go)."""

    def __init__(self, channel_id: str, config: m.Config, csp):
        if config.channel_group is None:
            raise ConfigError("config has no channel group")
        self.channel_id = channel_id
        self.config = config
        self.sequence = config.sequence
        root = config.channel_group
        top = groups_of(root)

        # MSPs first (policies compile against them)
        msps: List[Msp] = []
        for section in (APPLICATION, ORDERER):
            sec = top.get(section)
            if sec is None:
                continue
            for org_name, org in groups_of(sec).items():
                mv = values_of(org).get(MSP_KEY)
                if mv is None:
                    raise ConfigError(f"org {org_name} has no MSP value")
                msps.append(msp_from_config(m.MSPConfig.decode(mv.value), csp))
        self.msp_manager = MspManager(msps)

        # Policy tree mirrors the group tree (reference: the policy
        # manager is constructed per config in policies.NewManagerImpl)
        self.policy_manager = self._build_policy_tree("Channel", root)

        # Typed sections
        self.orderer: Optional[OrdererConfig] = None
        osec = top.get(ORDERER)
        if osec is not None:
            vals = values_of(osec)
            if BATCH_SIZE not in vals or BATCH_TIMEOUT not in vals:
                raise ConfigError("orderer group needs BatchSize/BatchTimeout")
            ctv = (m.ConsensusType.decode(vals[CONSENSUS_TYPE].value)
                   if CONSENSUS_TYPE in vals else m.ConsensusType(
                       type="solo"))
            self.orderer = OrdererConfig(
                batch_size=m.BatchSize.decode(vals[BATCH_SIZE].value),
                batch_timeout_s=_parse_timeout(
                    m.BatchTimeout.decode(vals[BATCH_TIMEOUT].value).timeout),
                consensus_type=ctv.type or "solo",
                consensus_metadata=ctv.metadata,
                org_mspids=tuple(sorted(groups_of(osec))),
                capabilities=_capabilities(vals))

        self.application: Optional[ApplicationConfig] = None
        asec = top.get(APPLICATION)
        if asec is not None:
            self.application = ApplicationConfig(
                org_mspids=tuple(sorted(groups_of(asec))),
                capabilities=_capabilities(values_of(asec)))

    def _build_policy_tree(self, name: str,
                           group: m.ConfigGroup) -> PolicyManager:
        mgr = PolicyManager(name)
        for key, sub in sorted(groups_of(group).items()):
            mgr.add_sub_manager(self._build_policy_tree(key, sub))
        metas: List[Tuple[str, m.ImplicitMetaPolicy]] = []
        for pname, cp in sorted(policies_of(group).items()):
            pol = cp.policy
            if pol is None:
                continue
            if pol.type == m.PolicyType.SIGNATURE:
                from fabric_mod_tpu.policy.manager import (
                    compile_policy_bytes)
                mgr.add_policy(pname, compile_policy_bytes(
                    pol.value, self.msp_manager, self.sequence))
            elif pol.type == m.PolicyType.IMPLICIT_META:
                metas.append((pname, m.ImplicitMetaPolicy.decode(pol.value)))
            else:
                raise PolicyError(f"unsupported policy type {pol.type}")
        for pname, meta in metas:
            mgr.resolve_implicit_meta(pname, meta)
        return mgr

    # -- conveniences used by orderer/peer wiring ------------------------
    def batch_config(self):
        from fabric_mod_tpu.orderer.blockcutter import BatchConfig
        oc = self.orderer
        if oc is None:
            raise ConfigError("no orderer section in channel config")
        return BatchConfig(
            max_message_count=oc.batch_size.max_message_count,
            absolute_max_bytes=oc.batch_size.absolute_max_bytes,
            preferred_max_bytes=oc.batch_size.preferred_max_bytes,
            batch_timeout_s=oc.batch_timeout_s)

    def policy(self, path: str):
        return self.policy_manager.get_policy(path)
