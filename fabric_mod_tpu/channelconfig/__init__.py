"""Channel configuration: typed bundle, config-tx validation, genesis
construction (reference: common/channelconfig, common/configtx,
internal/configtxgen)."""
from fabric_mod_tpu.channelconfig.bundle import (        # noqa: F401
    APPLICATION, ORDERER, Bundle, ConfigError, groups_of, policies_of,
    values_of)
from fabric_mod_tpu.channelconfig.configtx import (      # noqa: F401
    ConfigTxError, config_from_block, extract_config_update,
    propose_config_update)
from fabric_mod_tpu.channelconfig import genesis         # noqa: F401
from fabric_mod_tpu.channelconfig.update import (        # noqa: F401
    compute_update, signed_update_envelope)
