"""Capability levels: feature gating from channel config.

(reference: common/capabilities — application.go:163 /channel.go:
typed accessors over the Capabilities config values, deciding which
protocol features a channel may use.)
"""
from __future__ import annotations

from typing import Sequence

V2_0 = "V2_0"
V2_5 = "V2_5"


class ApplicationCapabilities:
    """(reference: capabilities/application.go)"""

    def __init__(self, names: Sequence[str]):
        self._names = set(names)

    _ORDER = (V2_0, V2_5)

    def _at_least(self, level: str) -> bool:
        return any(n in self._names
                   for n in self._ORDER[self._ORDER.index(level):])

    def key_level_endorsement(self) -> bool:
        return self._at_least(V2_0)

    def lifecycle_v20(self) -> bool:
        return self._at_least(V2_0)

    def storage_pvtdata(self) -> bool:
        return self._at_least(V2_0)

    def supported(self) -> bool:
        """Are all declared capabilities ones we implement?
        (reference: the Supported() gate rejecting unknown levels)"""
        return self._names.issubset({V2_0, V2_5})


class ChannelCapabilities:
    def __init__(self, names: Sequence[str]):
        self._names = set(names)

    def supported(self) -> bool:
        return self._names.issubset({V2_0, V2_5})
