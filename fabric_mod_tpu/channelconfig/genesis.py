"""Genesis config construction — the configtxgen library core.

(reference: internal/configtxgen/encoder/encoder.go — NewChannelGroup /
NewApplicationGroup / NewOrdererGroup / NewOrgGroup — and
genesisconfig/config.go's standard profile shapes.)

Builds the standard config tree: per-org groups carrying MSP material
and Readers/Writers/Admins/Endorsement signature policies, Application
and Orderer sections with implicit-meta roll-ups, channel-level values
and policies, wrapped into a signed-nothing genesis block (block 0 of
every chain, reference: orderer/common/bootstrap).
"""
from __future__ import annotations

from typing import Optional, Sequence

from fabric_mod_tpu.channelconfig.bundle import (
    APPLICATION, BATCH_SIZE, BATCH_TIMEOUT, BLOCK_DATA_HASHING_STRUCTURE,
    BLOCK_VALIDATION_POLICY, CAPABILITIES, CONSENSUS_TYPE,
    HASHING_ALGORITHM, MSP_KEY, ORDERER)
from fabric_mod_tpu.channelconfig.bundle import set_group, set_policy, set_value
from fabric_mod_tpu.policy import policydsl
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil

ADMINS = "Admins"
READERS = "Readers"
WRITERS = "Writers"
ENDORSEMENT = "Endorsement"
LIFECYCLE_ENDORSEMENT = "LifecycleEndorsement"


def _sig_policy(dsl: str) -> m.Policy:
    return m.Policy(type=m.PolicyType.SIGNATURE,
                    value=policydsl.from_string(dsl).encode())


def _meta_policy(rule: int, sub_policy: str) -> m.Policy:
    return m.Policy(
        type=m.PolicyType.IMPLICIT_META,
        value=m.ImplicitMetaPolicy(sub_policy=sub_policy, rule=rule).encode())


def _config_policy(pol: m.Policy, mod_policy: str = ADMINS) -> m.ConfigPolicy:
    return m.ConfigPolicy(policy=pol, mod_policy=mod_policy)


def _config_value(msg, mod_policy: str = ADMINS) -> m.ConfigValue:
    return m.ConfigValue(value=msg.encode(), mod_policy=mod_policy)


def org_group(mspid: str, root_cert_pems: Sequence[bytes],
              node_ous: bool = True,
              admin_cert_pems: Sequence[bytes] = (),
              crls_der: Sequence[bytes] = ()) -> m.ConfigGroup:
    """One organization's config group (reference:
    encoder.go NewOrdererOrgGroup/NewApplicationOrgGroup)."""
    fconf = m.FabricMSPConfig(
        name=mspid,
        root_certs=list(root_cert_pems),
        admins=list(admin_cert_pems),
        revocation_list=list(crls_der),
        fabric_node_ous=m.FabricNodeOUs(enable=1) if node_ous else None)
    g = m.ConfigGroup(mod_policy=ADMINS)
    set_value(g, MSP_KEY, _config_value(
        m.MSPConfig(type=0, config=fconf.encode())))
    set_policy(g, READERS, _config_policy(
        _sig_policy(f"OR('{mspid}.member')")))
    set_policy(g, WRITERS, _config_policy(
        _sig_policy(f"OR('{mspid}.member')")))
    set_policy(g, ADMINS, _config_policy(
        _sig_policy(f"OR('{mspid}.admin')")))
    set_policy(g, ENDORSEMENT, _config_policy(
        _sig_policy(f"OR('{mspid}.peer')")))
    return g


def _std_meta_policies(g: m.ConfigGroup) -> None:
    set_policy(g, READERS, _config_policy(
        _meta_policy(m.ImplicitMetaRule.ANY, READERS)))
    set_policy(g, WRITERS, _config_policy(
        _meta_policy(m.ImplicitMetaRule.ANY, WRITERS)))
    set_policy(g, ADMINS, _config_policy(
        _meta_policy(m.ImplicitMetaRule.MAJORITY, ADMINS)))


def application_group(orgs: Sequence[m.ConfigGroup],
                      org_names: Sequence[str]) -> m.ConfigGroup:
    g = m.ConfigGroup(mod_policy=ADMINS)
    for name, org in zip(org_names, orgs):
        set_group(g, name, org)
    _std_meta_policies(g)
    set_policy(g, ENDORSEMENT, _config_policy(
        _meta_policy(m.ImplicitMetaRule.MAJORITY, ENDORSEMENT)))
    set_policy(g, LIFECYCLE_ENDORSEMENT, _config_policy(
        _meta_policy(m.ImplicitMetaRule.MAJORITY, ENDORSEMENT)))
    return g


def orderer_group(orgs: Sequence[m.ConfigGroup], org_names: Sequence[str],
                  consensus_type: str = "solo",
                  max_message_count: int = 500,
                  absolute_max_bytes: int = 10 * 1024 * 1024,
                  preferred_max_bytes: int = 2 * 1024 * 1024,
                  batch_timeout: str = "2s",
                  consenters: Sequence[str] = ()) -> m.ConfigGroup:
    g = m.ConfigGroup(mod_policy=ADMINS)
    for name, org in zip(org_names, orgs):
        set_group(g, name, org)
    _std_meta_policies(g)
    # Block signatures validate against ANY orderer-org Writers
    # (reference: encoder.go NewOrdererGroup BlockValidation policy)
    set_policy(g, BLOCK_VALIDATION_POLICY, _config_policy(
        _meta_policy(m.ImplicitMetaRule.ANY, WRITERS)))
    set_value(g, BATCH_SIZE, _config_value(m.BatchSize(
        max_message_count=max_message_count,
        absolute_max_bytes=absolute_max_bytes,
        preferred_max_bytes=preferred_max_bytes)))
    set_value(g, BATCH_TIMEOUT, _config_value(
        m.BatchTimeout(timeout=batch_timeout)))
    set_value(g, CONSENSUS_TYPE, _config_value(m.ConsensusType(
        type=consensus_type,
        metadata=(m.RaftMetadata(consenters=list(consenters)).encode()
                  if consenters else b""))))
    return g


def channel_group(app: Optional[m.ConfigGroup],
                  ordr: Optional[m.ConfigGroup]) -> m.ConfigGroup:
    root = m.ConfigGroup(mod_policy=ADMINS)
    if app is not None:
        set_group(root, APPLICATION, app)
    if ordr is not None:
        set_group(root, ORDERER, ordr)
    _std_meta_policies(root)
    set_value(root, HASHING_ALGORITHM, _config_value(
        m.HashingAlgorithm(name="SHA256")))
    set_value(root, BLOCK_DATA_HASHING_STRUCTURE, _config_value(
        m.BlockDataHashingStructure(width=(1 << 32) - 1)))
    return root


def genesis_config(channel_group_: m.ConfigGroup) -> m.Config:
    return m.Config(sequence=0, channel_group=channel_group_)


def config_block(channel_id: str, config: m.Config,
                 number: int = 0, previous_hash: bytes = b"",
                 last_update: Optional[m.Envelope] = None) -> m.Block:
    """Wrap a Config into a CONFIG block (genesis when number == 0;
    reference: encoder.go New + blockwriter's config-block path)."""
    cenv = m.ConfigEnvelope(config=config, last_update=last_update)
    ch = protoutil.make_channel_header(m.HeaderType.CONFIG, channel_id)
    sh = protoutil.make_signature_header(b"", protoutil.new_nonce())
    payload = protoutil.make_payload(ch, sh, cenv.encode())
    env = m.Envelope(payload=payload.encode())
    return protoutil.new_block(number, previous_hash, [env])


def standard_network(channel_id: str, org_cas: dict,
                     orderer_cas: dict, **orderer_kwargs) -> m.Block:
    """Convenience: {mspid: [root PEM]} maps for application and
    orderer orgs -> genesis block (the e2e/test topology builder)."""
    app_orgs = [org_group(mspid, pems) for mspid, pems in
                sorted(org_cas.items())]
    ord_orgs = [org_group(mspid, pems) for mspid, pems in
                sorted(orderer_cas.items())]
    root = channel_group(
        application_group(app_orgs, sorted(org_cas)),
        orderer_group(ord_orgs, sorted(orderer_cas), **orderer_kwargs))
    return config_block(channel_id, genesis_config(root))
