"""Config-update computation: diff two configs into a ConfigUpdate.

(reference: internal/configtxlator/update/update.go `Compute` — the
tool that turns (current config, desired config) into the minimal
read_set/write_set pair clients sign and submit.)

Semantics mirrored from the reference:
* an element whose bytes change gets version+1 in the write_set;
* a group whose membership changes (add/remove) bumps its own version
  and carries its FULL desired membership (merge treats bumped
  membership as authoritative, so removals work);
* unchanged elements inside a bumped group ride along at their current
  version as context; unchanged groups are omitted entirely;
* the read_set pins every group on the path to a change at its
  current version (version stubs, no bodies).
"""
from __future__ import annotations

from typing import Optional, Tuple

from fabric_mod_tpu.channelconfig.bundle import (
    groups_of, policies_of, set_group, set_policy, set_value, values_of)
from fabric_mod_tpu.protos import messages as m


class UpdateComputeError(Exception):
    pass


def _items_equal(a, b) -> bool:
    return a.encode() == b.encode()


def _compute_group(cur: m.ConfigGroup, new: m.ConfigGroup
                   ) -> Tuple[Optional[m.ConfigGroup],
                              Optional[m.ConfigGroup], bool]:
    """Returns (read_stub, write_group, changed)."""
    read = m.ConfigGroup(version=cur.version)
    write = m.ConfigGroup(version=cur.version,
                          mod_policy=new.mod_policy or cur.mod_policy)

    cg, ng = groups_of(cur), groups_of(new)
    cv_all = {acc: (acc(cur), acc(new))
              for acc in (values_of, policies_of)}

    # pass 1: does THIS group's version bump?  (membership or mod_policy
    # or any direct value/policy difference — reference: update.go's
    # sameness check covers the whole group body)
    changed_here = (new.mod_policy not in ("", cur.mod_policy)
                    or set(cg) != set(ng))
    for accessor in (values_of, policies_of):
        cv, nv = cv_all[accessor]
        if set(cv) != set(nv):
            changed_here = True
            continue
        for key in nv:
            if not _items_equal(_strip_version(cv[key]),
                                _strip_version(nv[key])):
                changed_here = True
                break

    # pass 2: emit.  A bumped group's write_set carries its FULL
    # membership (the merge treats it as authoritative), exactly like
    # the reference's Compute emitting the whole updated group.
    child_changed = False
    for key in sorted(set(cg) & set(ng)):
        r, w, ch = _compute_group(cg[key], ng[key])
        if ch:
            child_changed = True
            set_group(read, key, r)
            set_group(write, key, w)
        elif changed_here:
            set_group(write, key, ng[key])
    for key in sorted(set(ng) - set(cg)):
        set_group(write, key, _zero_versions(ng[key]))

    for accessor, setter in ((values_of, set_value),
                             (policies_of, set_policy)):
        cv, nv = cv_all[accessor]
        for key in sorted(set(nv)):
            if key not in cv:
                setter(write, key, _copy_item(nv[key], version=0))
            elif not _items_equal(_strip_version(cv[key]),
                                  _strip_version(nv[key])):
                setter(write, key,
                       _copy_item(nv[key], version=cv[key].version + 1))
            elif changed_here:
                setter(write, key,
                       _copy_item(nv[key], version=cv[key].version))
    if changed_here:
        write.version = cur.version + 1
    return read, write, changed_here or child_changed


def _strip_version(item):
    c = type(item).decode(item.encode())
    c.version = 0
    return c


def _copy_item(item, version: int):
    c = type(item).decode(item.encode())
    c.version = version
    return c


def _zero_versions(group: m.ConfigGroup) -> m.ConfigGroup:
    """New subtrees enter at version 0 everywhere."""
    out = m.ConfigGroup(version=0, mod_policy=group.mod_policy)
    for key, sub in sorted(groups_of(group).items()):
        set_group(out, key, _zero_versions(sub))
    for accessor, setter in ((values_of, set_value),
                             (policies_of, set_policy)):
        for key, item in sorted(accessor(group).items()):
            setter(out, key, _copy_item(item, version=0))
    return out


def compute_update(channel_id: str, cur: m.Config,
                   new_group: m.ConfigGroup) -> m.ConfigUpdate:
    """Diff the current config against a desired channel group
    (reference: update.go Compute)."""
    if cur.channel_group is None:
        raise UpdateComputeError("current config has no channel group")
    read, write, changed = _compute_group(cur.channel_group, new_group)
    if not changed:
        raise UpdateComputeError("no differences between configs")
    return m.ConfigUpdate(channel_id=channel_id, read_set=read,
                          write_set=write)


def signed_update_envelope(channel_id: str, update: m.ConfigUpdate,
                           signers) -> m.Envelope:
    """Wrap + sign a ConfigUpdate as the CONFIG_UPDATE envelope clients
    broadcast (reference: configtx signing + protoutil)."""
    from fabric_mod_tpu.protos import protoutil
    cu_bytes = update.encode()
    sigs = []
    for signer in signers:
        sh = protoutil.make_signature_header(
            signer.serialize(), protoutil.new_nonce()).encode()
        sigs.append(m.ConfigSignature(
            signature_header=sh,
            signature=signer.sign_message(sh + cu_bytes)))
    cue = m.ConfigUpdateEnvelope(config_update=cu_bytes, signatures=sigs)
    lead = signers[0]
    ch = protoutil.make_channel_header(
        m.HeaderType.CONFIG_UPDATE, channel_id)
    shdr = protoutil.make_signature_header(
        lead.serialize(), protoutil.new_nonce())
    payload = protoutil.make_payload(ch, shdr, cue.encode())
    return protoutil.sign_envelope(payload, lead)
