"""fabric_mod_tpu — a TPU-native permissioned-ledger framework.

A from-scratch framework with the capabilities of Hyperledger Fabric
(reference: trustbloc/fabric-mod): pluggable crypto provider (BCCSP),
membership services (MSP), signature-policy engine, endorse/order/validate/
commit transaction flow, ordering service, gossip dissemination, and a
versioned KV ledger with MVCC.

The design is TPU-first: the block-commit path's compute — batched
ECDSA-P256 signature verification, SHA-256 hashing, and endorsement-policy
evaluation — runs as JAX kernels on device (see ``fabric_mod_tpu.ops``),
fed by a host-side batching provider (``fabric_mod_tpu.bccsp.tpu_provider``)
behind the same pluggable boundary the reference exposes
(reference: bccsp/bccsp.go:90, core/peer/peer.go:313).

Layer map (mirrors SURVEY.md §1):
  protos/    L0 wire types + canonical codec
  ops/       device kernels (limb bignum, P-256, ECDSA, SHA-256)
  bccsp/     L1 crypto provider (sw + tpu batch provider + factory)
  msp/       L1 identity (certs, validation, principal matching)
  policy/    L2 signature-policy compiler + vectorized evaluation
  ledger/    L3 block store, versioned state DB, MVCC
  orderer/   L5 ordering service (blockcutter, solo/raft consenters)
  peer/      L5 commit pipeline (txvalidator, committer), endorser
  gossip/    L4 dissemination (membership, anti-entropy, state transfer)
  parallel/  device mesh / sharding utilities (dp sharding of verify batches)
  utils/     logging, metrics, config
"""

__version__ = "0.1.0"
