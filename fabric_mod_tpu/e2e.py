"""In-process end-to-end network: the minimum slice, whole loop.

(reference: the integration/nwo "network world order" declarative
topology builder, network.go:44-60, shrunk to one process: client ->
endorsers -> broadcast -> solo consenter -> deliver -> MCS verify ->
validator (device batch) -> MVCC -> commit.)

This is the BASELINE config #3 shape without gRPC between the parts;
the seams (Broadcast.submit, DeliverService.blocks, verify_many) are
exactly where the wire goes when the comm layer lands.
"""
from __future__ import annotations

import os
import tempfile
import time
from typing import Dict, List, Optional, Sequence

from fabric_mod_tpu.bccsp.sw import SwCSP
from fabric_mod_tpu.channelconfig import Bundle, genesis
from fabric_mod_tpu.channelconfig.configtx import config_from_block
from fabric_mod_tpu.ledger.kvledger import LedgerManager
from fabric_mod_tpu.msp import ca as calib
from fabric_mod_tpu.msp.identities import SigningIdentity
from fabric_mod_tpu.orderer import Broadcast, DeliverService, Registrar
from fabric_mod_tpu.peer.chaincode import ChaincodeRegistry, KvContract
from fabric_mod_tpu.peer.channel import Channel
from fabric_mod_tpu.peer.deliverclient import DeliverClient
from fabric_mod_tpu.peer.endorser import Endorser, endorse_and_submit
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.concurrency.threads import RegisteredThread


class Network:
    """One channel, N orgs, one solo orderer, one committing peer,
    one endorser per org — all in-process."""

    def __init__(self, root_dir: str, channel_id: str = "testchannel",
                 orgs: Sequence[str] = ("Org1", "Org2", "Org3"),
                 verifier=None, csp=None,
                 max_message_count: int = 500,
                 batch_timeout: str = "250ms",
                 ingress_batching: bool = False):
        self.channel_id = channel_id
        self.csp = csp or SwCSP()
        if verifier is None:
            from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier
            verifier = FakeBatchVerifier(self.csp)
        self.verifier = verifier

        # crypto material (the cryptogen step)
        self.cas: Dict[str, calib.CA] = {
            org: calib.CA(f"ca.{org.lower()}", org) for org in orgs}
        self.orderer_ca = calib.CA("ca.orderer", "OrdererOrg")
        ocert, okey = self.orderer_ca.issue(
            "orderer0", "OrdererOrg", ous=["orderer"])
        self.orderer_signer = SigningIdentity(
            "OrdererOrg", ocert, calib.key_pem(okey), self.csp)

        self.peer_signers: Dict[str, SigningIdentity] = {}
        for org, ca in self.cas.items():
            cert, key = ca.issue(f"peer0.{org.lower()}", org, ous=["peer"])
            self.peer_signers[org] = SigningIdentity(
                org, cert, calib.key_pem(key), self.csp)
        first = orgs[0]
        ccert, ckey = self.cas[first].issue(
            f"client@{first.lower()}", first, ous=["client"])
        self.client = SigningIdentity(
            first, ccert, calib.key_pem(ckey), self.csp)
        self.admins: Dict[str, SigningIdentity] = {}
        for org, ca in self.cas.items():
            acert, akey = ca.issue(f"admin@{org.lower()}", org,
                                   ous=["admin"])
            self.admins[org] = SigningIdentity(
                org, acert, calib.key_pem(akey), self.csp)

        # genesis (the configtxgen step)
        self.genesis_block = genesis.standard_network(
            channel_id,
            {org: [calib.cert_pem(ca.cert)] for org, ca in self.cas.items()},
            {"OrdererOrg": [calib.cert_pem(self.orderer_ca.cert)]},
            max_message_count=max_message_count,
            batch_timeout=batch_timeout)

        # ordering service; with ingress batching, concurrent
        # broadcast submissions coalesce their policy verifies into
        # shared deadline-batched device dispatches (bccsp/tpu.py
        # BatchingVerifyService — the admission-control knob)
        self.ingress_service = None
        ingress_verify = None
        if ingress_batching:
            from fabric_mod_tpu.bccsp.tpu import BatchingVerifyService
            self.ingress_service = BatchingVerifyService(self.verifier)
            ingress_verify = self.ingress_service.verify_many
        self.registrar = Registrar(
            os.path.join(root_dir, "orderer"), self.orderer_signer,
            self.csp, verify_many=ingress_verify)
        self.support = self.registrar.create_channel(self.genesis_block)
        self.broadcast = Broadcast(self.registrar)
        self.deliver = DeliverService(self.support)

        # the committing peer
        _, config = config_from_block(self.genesis_block)
        bundle = Bundle(channel_id, config, self.csp)
        self.ledger_mgr = LedgerManager(os.path.join(root_dir, "peer"))
        self.ledger = self.ledger_mgr.create_or_open(channel_id)
        self.channel = Channel(channel_id, self.ledger, verifier, bundle,
                               self.csp)
        if self.ledger.height == 0:
            self.channel.init_from_genesis(self.genesis_block)

        # chaincode + endorsers (user contract + the system
        # chaincodes; wiring shared with the real peer process)
        from fabric_mod_tpu.peer.scc import build_default_registry
        self.chaincodes = build_default_registry(self.channel,
                                                 self.ledger)
        self.endorsers: Dict[str, Endorser] = {
            org: Endorser(self.channel, self.chaincodes,
                          self.peer_signers[org])
            for org in orgs}

    # -- client operations ------------------------------------------------
    def invoke(self, args: Sequence[bytes],
               endorsing_orgs: Optional[Sequence[str]] = None,
               chaincode: str = "mycc", transient=None,
               signer=None) -> str:
        orgs = list(endorsing_orgs or list(self.endorsers)[:2])
        return endorse_and_submit(
            self.channel_id, chaincode, args, signer or self.client,
            [self.endorsers[o] for o in orgs], self.broadcast,
            transient=transient)

    def pump_committed(self, want_txs: int, timeout: float = 30.0
                       ) -> int:
        """Run a deliver client until `want_txs` total txs committed."""
        client = self.deliver_client()
        t = RegisteredThread(
            target=lambda: client.run(idle_timeout_s=5.0),
            name="e2e-deliver", structure="e2e")
        t.start()
        deadline = time.time() + timeout
        committed = 0
        while time.time() < deadline:
            committed = sum(
                len(self.ledger.get_block_by_number(i).data.data)
                for i in range(1, self.ledger.height))
            if committed >= want_txs:
                break
            time.sleep(0.02)
        client.stop()
        t.join(timeout=5)
        return committed

    def deploy_chaincode(self, name: str, version: str, sequence: int,
                         policy: bytes = b"", collections: bytes = b"",
                         approving_orgs: Optional[Sequence[str]] = None
                         ) -> int:
        """The full lifecycle ceremony (reference: approve-per-org ->
        commit): each approving org's ADMIN submits an approval
        endorsed by its OWN peer (org-local act), the approvals
        commit, then the commit op (endorsed by a majority) commits.
        Returns the total committed tx count afterwards."""
        from fabric_mod_tpu.peer.lifecycle import LIFECYCLE_NS
        orgs = list(approving_orgs
                    or list(self.endorsers)[:len(self.endorsers) // 2
                                            + 1])
        base = sum(len(self.ledger.get_block_by_number(i).data.data)
                   for i in range(1, self.ledger.height))
        args = [b"approve", name.encode(), version.encode(),
                str(sequence).encode(), policy, collections]
        txids = []
        for org in orgs:
            txids.append(self.invoke(args, endorsing_orgs=[org],
                                     chaincode=LIFECYCLE_NS,
                                     signer=self.admins[org]))
        got = self.pump_committed(base + len(orgs))
        if got < base + len(orgs):
            raise RuntimeError(
                f"approvals did not commit ({got}/{base + len(orgs)})")
        txids.append(self.invoke(
            [b"commit", name.encode(), version.encode(),
             str(sequence).encode(), policy, collections],
            chaincode=LIFECYCLE_NS))
        got = self.pump_committed(base + len(orgs) + 1)
        if got < base + len(orgs) + 1:
            raise RuntimeError("definition commit did not commit")
        # every ceremony tx must have VALIDATED — checked by txid, not
        # by block position (unrelated txs may share our blocks)
        for txid in txids:
            pt = self.ledger.get_transaction_by_id(txid)
            if pt is None or pt.validation_code != \
                    m.TxValidationCode.VALID:
                raise RuntimeError(
                    f"lifecycle tx {txid} invalid "
                    f"({None if pt is None else pt.validation_code})")
        return got

    def deliver_client(self, **kw) -> DeliverClient:
        return DeliverClient(self.channel, self.deliver, **kw)

    def close(self) -> None:
        self.registrar.close()
        self.ledger_mgr.close()
        if self.ingress_service is not None:
            self.ingress_service.close()


def run_pipeline(n_txs: int, verifier, reps_unused: int = 1,
                 stats: dict = None) -> float:
    """Endorse n_txs txs, broadcast them, commit them through the full
    peer pipeline; return committed tx/s over the ordering+commit span
    (endorsement/signing excluded — it is client work).

    `stats`, if given, receives the pipeline's stage wall times
    (stage_secs = host unpack + device dispatch, commit_secs = verdict
    resolve + MVCC + ledger commit, wall_secs = the measured span) so
    the bench can show how much verify time the double buffer hides."""
    from fabric_mod_tpu.observability import tracing
    trace_t0 = ({k: v["secs"]
                 for k, v in tracing.substage_totals().items()}
                if tracing.armed() else None)
    with tempfile.TemporaryDirectory() as root:
        net = Network(root, verifier=verifier)
        try:
            # endorse everything up front (client-side work)
            from fabric_mod_tpu.protos import protoutil
            envs = []
            orgs = list(net.endorsers)[:2]
            for i in range(n_txs):
                sp, prop, _ = protoutil.create_chaincode_proposal(
                    net.channel_id, "mycc",
                    [b"put", b"k%d" % i, b"v%d" % i], net.client)
                responses = [net.endorsers[o].process_proposal(sp)
                             for o in orgs]
                envs.append(protoutil.create_tx_from_responses(
                    prop, responses, net.client))

            t0 = time.perf_counter()
            for env in envs:
                net.broadcast.submit(env)
            # orderer cuts blocks; peer pulls + commits
            client = net.deliver_client()
            runner = RegisteredThread(target=client.run,
                                      name="e2e-deliver-runner",
                                      structure="e2e")
            runner.start()
            # wait until everything committed; the floor covers a COLD
            # XLA compile of the verify program inside the first
            # block's MCS/validate step (minutes on the CPU backend)
            want = net.ledger.height  # will grow; recompute below
            deadline = time.time() + max(420.0, n_txs / 50)
            while time.time() < deadline:
                committed = sum(
                    len(b.data.data)
                    for b in (net.ledger.get_block_by_number(i)
                              for i in range(1, net.ledger.height))
                    if b is not None)
                if committed >= n_txs:
                    break
                time.sleep(0.01)
            dt = time.perf_counter() - t0
            client.stop()
            runner.join(timeout=30)
            if committed < n_txs:
                raise RuntimeError(
                    f"only {committed}/{n_txs} txs committed")
            if stats is not None:
                stats["stage_secs"] = round(client.stage_secs, 3)
                stats["commit_secs"] = round(client.commit_secs, 3)
                # the device-verdict wait inside commit_secs — the
                # part the pipeline hides under the next block's
                # staging (commitpipe's await histogram, summed)
                stats["await_secs"] = round(client.await_secs, 3)
                stats["wall_secs"] = round(dt, 3)
                if trace_t0 is not None:
                    # FMT_TRACE sub-span split of the buckets above:
                    # which part of stage/await/commit actually burns
                    # the wall (recv/unpack/der_marshal/device_
                    # dispatch/verdict_await/policy_*/mvcc/
                    # ledger_write) — the data the next kernel is
                    # chosen by
                    stats["stage_attribution"] = {
                        k: round(v["secs"] - trace_t0.get(k, 0.0), 3)
                        for k, v in tracing.substage_totals().items()}
            return n_txs / dt
        finally:
            net.close()
