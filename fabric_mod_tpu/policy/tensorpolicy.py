"""Whole-block tensor policy evaluation: SignaturePolicy trees as
dense mask/threshold tensors, every verdict in one program.

PR 9's first trace attribution measured the commit bucket at 87%
``policy_eval`` — the per-tx host loop that walks compiled-policy
closures one ``PendingEval.finish`` at a time, re-running
``msp.satisfies_principal`` (a full cert-chain validation) for every
(identity, principal) visit of every evaluation.  The reference
already evaluates in the batch-friendly shape (``cauthdsl.compile``
verifies all signatures first, then runs the combinatorial walk); this
module finishes the job by compiling the walk itself into data:

* ``TensorProgram`` — a ``SignaturePolicyEnvelope`` rule tree
  flattened into a fixed op list (LEAF / ENTER / SAVE / COMMIT /
  THRESH) whose execution reproduces the closure compiler's greedy
  used-flag semantics EXACTLY: a leaf consumes the first unused
  satisfying identity, an NOutOf child runs against a trial copy of
  the used flags and commits only on success, children never early
  exit.  Trees that exceed the fixed caps (depth, ops, identities,
  principals) are non-tensorizable and fall back to the closure path,
  counted on /metrics.
* ``PrincipalMemo`` — the host-side principal-satisfaction matrix is
  computed via the MSP exactly once per (identity, principal) pair,
  keyed by certificate fingerprint + principal bytes + the channel's
  CONFIG SEQUENCE (a config update that changes membership must never
  be answered from a stale matrix).  One memo per MspManager
  (weak-keyed), so a bundle swap naturally starts cold.
* ``TensorSession`` — per-block: every policy evaluation staged by
  the validator lands as one row of the session's dense tensors
  (satisfaction matrix, verdict-mask gather indices, flattened op
  program), and ALL verdicts — chaincode-level and key-level — are
  produced by ONE evaluator pass fused downstream of the block's
  ``p256.batch_verify_raw`` mask.  When the mask arrives as a jax
  device array the jitted program is dispatched against it directly
  (no device->host->device round trip); a host (numpy) mask runs the
  same op semantics through the vectorized numpy interpreter — no XLA
  compile on the sw arm, bit-identical verdicts (differential-tested
  against each other and against the closures).

Gated by ``FABRIC_MOD_TPU_TENSOR_POLICY``; unset, the validator stays
on the closure path byte-for-byte.
"""
from __future__ import annotations

import functools
import threading
import weakref
from typing import List, Optional, Sequence, Tuple

import numpy as np

from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.utils import knobs as _knobs

# ---------------------------------------------------------------------------
# Tensorizability caps: fixed so the jitted program compiles for a
# handful of padded shapes, ever (the BUCKETS discipline of
# bccsp/tpu.py).  Anything larger falls back to the closure path.
# ---------------------------------------------------------------------------
MAX_IDENTS = 8          # identity slots per evaluation instance
MAX_PRINCIPALS = 8      # principals per policy envelope
MAX_DEPTH = 4           # NOutOf nesting depth (SAVE trial frames)
MAX_OPS = 64            # flattened program length
# stack slots: NOutOf nodes legally sit at depths 0..MAX_DEPTH and
# each pushes a COUNTER, so the counter stack needs MAX_DEPTH+1
# slots; SAVE frames max out at MAX_DEPTH (the root has none) but
# share the sizing for one mask range
STACK_SLOTS = MAX_DEPTH + 1

# opcodes of the flattened program
OP_NOP = 0              # padding
OP_LEAF = 1             # arg = principal column: greedy first-unused pick
OP_ENTER = 2            # push a zero child-success counter
OP_SAVE = 3             # push a trial copy of the used flags
OP_COMMIT = 4           # pop trial: keep on child success, else restore
OP_THRESH = 5           # arg = n: result = (popped counter >= n)
# LEAF CHILD fused with its trial/commit: SAVE/LEAF/COMMIT around a
# bare leaf is semantically the leaf alone (a failed leaf consumes
# nothing, so the restore is a no-op; a successful leaf's consumption
# is always committed) plus the parent counter increment — one op
# instead of three, and most real programs (NOutOf over SignedBy) are
# nothing but these
OP_LEAFC = 6            # arg = principal column; counter += success


def enabled() -> bool:
    """The FABRIC_MOD_TPU_TENSOR_POLICY gate."""
    return _knobs.get_bool("FABRIC_MOD_TPU_TENSOR_POLICY")


_FALLBACK_OPTS = MetricOpts(
    "fabric", "policy", "tensor_fallback_total",
    help="Policy evaluations that fell back to the closure path "
         "(non-tensorizable tree shape, or more identities than the "
         "tensor caps).")
_INSTANCES_OPTS = MetricOpts(
    "fabric", "policy", "tensor_instances_total",
    help="Policy evaluations answered by the whole-block tensor "
         "program.")
_MEMO_HITS_OPTS = MetricOpts(
    "fabric", "policy", "principal_memo_hits",
    help="Principal-satisfaction lookups answered by the "
         "config-sequence-keyed memo (MSP cert-chain walk skipped).")
_MEMO_MISSES_OPTS = MetricOpts(
    "fabric", "policy", "principal_memo_misses",
    help="Principal-satisfaction pairs computed via the MSP.")


@functools.lru_cache(maxsize=None)
def _metrics():
    prov = default_provider()
    return (prov.counter(_FALLBACK_OPTS), prov.counter(_INSTANCES_OPTS),
            prov.counter(_MEMO_HITS_OPTS), prov.counter(_MEMO_MISSES_OPTS))


# ---------------------------------------------------------------------------
# Compilation: rule tree -> flat op program
# ---------------------------------------------------------------------------

class TensorProgram:
    """One SignaturePolicyEnvelope compiled to the flat op form.
    Immutable; shared by every evaluation instance of the policy."""

    __slots__ = ("ops", "args", "n_ops", "depth", "principals",
                 "principal_bytes")

    def __init__(self, ops: List[int], args: List[int], depth: int,
                 principals: Sequence):
        self.n_ops = len(ops)
        self.ops = np.asarray(ops, np.int32)
        self.args = np.asarray(args, np.int32)
        self.depth = depth
        self.principals = list(principals)
        self.principal_bytes = [p.encode() for p in self.principals]


def compile_tensor_program(envelope) -> Optional[TensorProgram]:
    """SignaturePolicyEnvelope -> TensorProgram, or None when the tree
    is non-tensorizable (over the caps, or malformed — malformed trees
    must keep failing through the closure compiler's own errors)."""
    rule = envelope.rule
    principals = envelope.identities
    if rule is None or len(principals) > MAX_PRINCIPALS:
        return None
    ops: List[int] = []
    args: List[int] = []
    depth = [0]

    def emit(node, d: int) -> bool:
        if d > MAX_DEPTH:
            return False
        depth[0] = max(depth[0], d)
        if node.n_out_of is not None:
            ops.append(OP_ENTER)
            args.append(0)
            for child in node.n_out_of.rules:
                if child.n_out_of is None:
                    idx = child.signed_by
                    if not 0 <= idx < len(principals):
                        return False  # the closure compiler raises here
                    ops.append(OP_LEAFC)
                    args.append(idx)
                    if len(ops) > MAX_OPS:
                        return False
                    continue
                ops.append(OP_SAVE)
                args.append(0)
                if not emit(child, d + 1):
                    return False
                ops.append(OP_COMMIT)
                args.append(0)
            n = int(node.n_out_of.n)
            if not -(1 << 31) <= n < (1 << 31):
                # outside the int32 args plane: fall back rather than
                # overflow (the closure path evaluates `verified >= n`
                # for any n, so the verdict must come from there)
                return False
            ops.append(OP_THRESH)
            args.append(n)
            return len(ops) <= MAX_OPS
        idx = node.signed_by
        if not 0 <= idx < len(principals):
            return False              # the closure compiler raises here
        ops.append(OP_LEAF)
        args.append(idx)
        return len(ops) <= MAX_OPS

    if not emit(rule, 0):
        return None
    return TensorProgram(ops, args, max(1, depth[0]), principals)


# ---------------------------------------------------------------------------
# Principal-satisfaction memo
# ---------------------------------------------------------------------------

class PrincipalMemo:
    """Bounded memo of msp.satisfies_principal verdicts keyed by
    (mspid, cert fingerprint, principal bytes, config sequence).

    satisfies_principal re-walks the identity's cert chain on every
    call — the closure path paid that per (identity, principal) visit
    per evaluation; the tensor path pays it once per unique pair per
    config epoch.  The config-sequence key makes a config update (new
    CRLs, changed NodeOUs) a clean miss even if a caller keeps one
    memo across bundles.
    """

    def __init__(self, capacity: int = 65536):
        self.capacity = capacity
        self._d: dict = {}
        # leaf lock, never nested (same stance as VerdictCache)
        self._lock = threading.Lock()  # fmtlint: allow[locks] -- leaf lock on the per-pair memo path, never nested; C-level speed matters

    def usable(self, ident) -> bool:
        """Can this identity be memo-keyed?  The key is the x509 cert
        fingerprint; cert-less identities (idemix pseudonyms — exactly
        the non-P256 host-verdict lanes) cannot ride the tensors and
        their evaluations fall back to the closure path."""
        return getattr(ident, "cert", None) is not None

    def satisfied(self, msp_mgr, ident, principal,
                  principal_bytes: bytes, seq: int) -> bool:
        # cert fingerprint cached on the identity object: the CachedMsp
        # deserialize cache hands back the SAME Identity for repeated
        # creator/endorser bytes, so this hash is paid once per cert,
        # not once per (pair, block) probe
        fp = getattr(ident, "_fmt_cert_fp", None)
        if fp is None:
            from fabric_mod_tpu.msp.identities import cert_fingerprint
            fp = cert_fingerprint(ident.cert)
            try:
                ident._fmt_cert_fp = fp
            except Exception:  # fmtlint: allow[swallowed-exceptions] -- slotted/frozen identity: skip the attr cache, correctness unchanged
                pass
        key = (ident.mspid, fp, principal_bytes, seq)
        with self._lock:
            got = self._d.get(key)
        _fb, _inst, hits, misses = _metrics()
        if got is not None:
            hits.add(1)
            return got
        misses.add(1)
        val = bool(msp_mgr.satisfies_principal(ident, principal))
        with self._lock:
            if len(self._d) >= self.capacity:
                # wholesale reset beats LRU bookkeeping here: the live
                # working set (a channel's identities x principals) is
                # tiny next to the bound, so an overflow means key
                # churn (config sequences advancing) — old epochs
                # never hit again anyway
                self._d.clear()
            self._d[key] = val
        return val

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)


_MEMO_BY_MGR: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_MEMO_LOCK = threading.Lock()  # fmtlint: allow[locks] -- leaf lock guarding a weak dict get-or-create, never nested


def principal_memo_for(msp_mgr) -> PrincipalMemo:
    """One memo per MspManager (weak-keyed): managers are immutable
    per bundle, so verdicts never cross trust-root boundaries, and a
    bundle swap (new manager) starts a fresh memo."""
    with _MEMO_LOCK:
        memo = _MEMO_BY_MGR.get(msp_mgr)
        if memo is None:
            memo = PrincipalMemo()
            _MEMO_BY_MGR[msp_mgr] = memo
        return memo


# ---------------------------------------------------------------------------
# The evaluator: one op-step semantics, two drivers (numpy / jax)
# ---------------------------------------------------------------------------

def _step(xp, state, opc, arg, sat_col, valid):
    """Execute op t for every instance at once.  Ops are exclusive per
    instance, so the per-op updates compose with where-masks; the
    semantics mirror cauthdsl._compile exactly:

      LEAF    first unused valid identity satisfying the principal is
              consumed (argmax = the closure's in-order scan)
      SAVE    trial = list(used) before a child runs
      COMMIT  child failed -> used[:] = trial restored; succeeded ->
              keep mutations, count += 1 (no early exit either way)
      THRESH  verified >= n
    """
    used, ustack, usp, cstack, csp, result = state
    n_i = used.shape[1]
    is_leafc = opc == OP_LEAFC
    is_leaf = (opc == OP_LEAF) | is_leafc
    is_enter = opc == OP_ENTER
    is_save = opc == OP_SAVE
    is_commit = opc == OP_COMMIT
    is_thresh = opc == OP_THRESH
    one = xp.int32(1) if hasattr(xp, "int32") else 1
    # mask range sized from the stacks themselves: the counter stack
    # must hold one more level than the SAVE frames (see STACK_SLOTS)
    depth = ustack.shape[1]

    # LEAF / LEAFC: greedy first-unused pick
    avail = valid & ~used & sat_col
    found = avail.any(axis=1)
    first = xp.argmax(avail, axis=1)
    pick = ((xp.arange(n_i)[None, :] == first[:, None])
            & found[:, None] & is_leaf[:, None])
    used = used | pick
    result = xp.where(is_leaf, found, result)

    drange = xp.arange(depth)
    # SAVE: push the trial copy at usp
    push = (drange[None, :] == usp[:, None]) & is_save[:, None]
    ustack = xp.where(push[:, :, None], used[:, None, :], ustack)
    usp = usp + is_save.astype(usp.dtype)

    # COMMIT: pop the trial; restore on child failure; count a success
    top = drange[None, :] == (usp - one)[:, None]
    saved = (ustack & top[:, :, None]).any(axis=1)
    restore = is_commit[:, None] & ~result[:, None]
    used = xp.where(restore, saved, used)
    usp = usp - is_commit.astype(usp.dtype)
    ctop = drange[None, :] == (csp - one)[:, None]
    # counter increments: a COMMIT whose child succeeded, or a fused
    # leaf child (LEAFC) that found an identity this step
    counted = (is_commit & result) | (is_leafc & found)
    cstack = cstack + xp.where(
        ctop & counted[:, None], 1, 0).astype(cstack.dtype)

    # ENTER: push a zero counter at csp
    cpush = (drange[None, :] == csp[:, None]) & is_enter[:, None]
    cstack = xp.where(cpush, xp.zeros((), cstack.dtype), cstack)
    csp = csp + is_enter.astype(csp.dtype)

    # THRESH: verified >= n, pop the counter (ctop is this op's own
    # counter: thresh instances took no enter/commit branch this step)
    count_top = xp.where(ctop, cstack, 0).sum(axis=1)
    result = xp.where(is_thresh, count_top >= arg, result)
    csp = csp - is_thresh.astype(csp.dtype)
    return used, ustack, usp, cstack, csp, result


def eval_numpy(valid: np.ndarray, sat: np.ndarray, ops: np.ndarray,
               args: np.ndarray, depth: int = STACK_SLOTS) -> np.ndarray:
    """Vectorized host interpreter: (N, I) valid, (N, I, P) sat,
    (N, T) ops/args -> (N,) verdicts.  Tight shapes, no compile — the
    sw/CPU arm's evaluator."""
    n, n_i = valid.shape
    t_ops = ops.shape[1]
    state = (np.zeros((n, n_i), bool),
             np.zeros((n, depth, n_i), bool),
             np.zeros(n, np.int32),
             np.zeros((n, depth), np.int32),
             np.zeros(n, np.int32),
             np.zeros(n, bool))
    n_p = sat.shape[2]
    for t in range(t_ops):
        a = args[:, t]
        sat_col = np.take_along_axis(
            sat, np.clip(a, 0, n_p - 1)[:, None, None], axis=2)[:, :, 0]
        state = _step(np, state, ops[:, t], a, sat_col, valid)
    return state[-1]


@functools.lru_cache(maxsize=None)
def _jax_eval_fn():
    """The jitted whole-block evaluator (cached once).  Shapes are
    padded to the session buckets so the set of compiled programs
    stays small; padded instances run NOP programs and are sliced off
    by the caller."""
    import jax
    import jax.numpy as jnp

    def run(mask, gather, host_ok, present, sat, ops_t, args_t):
        valid = jnp.where(gather >= 0,
                          mask[jnp.clip(gather, 0, mask.shape[0] - 1)],
                          host_ok) & present
        n, n_i = present.shape
        n_p = sat.shape[2]
        init = (jnp.zeros((n, n_i), bool),
                jnp.zeros((n, STACK_SLOTS, n_i), bool),
                jnp.zeros(n, jnp.int32),
                jnp.zeros((n, STACK_SLOTS), jnp.int32),
                jnp.zeros(n, jnp.int32),
                jnp.zeros(n, bool))

        def body(state, opa):
            opc, a = opa
            sat_col = jnp.take_along_axis(
                sat, jnp.clip(a, 0, n_p - 1)[:, None, None],
                axis=2)[:, :, 0]
            return _step(jnp, state, opc, a, sat_col, valid), None

        state, _ = jax.lax.scan(body, init, (ops_t, args_t))
        return state[-1]

    return jax.jit(run)


def _pow2_at_least(n: int, floor: int) -> int:
    n = max(n, floor)
    return 1 << (n - 1).bit_length()


# ---------------------------------------------------------------------------
# The per-block session
# ---------------------------------------------------------------------------

class TensorPending:
    """The tensor path's PendingEval twin: `finish(mask)` reads the
    instance's precomputed verdict from the session's single evaluator
    pass (the mask argument is accepted for interface parity; the
    session is bound to the same block mask by the validator)."""

    __slots__ = ("_session", "_idx")

    def __init__(self, session: "TensorSession", idx: int):
        self._session = session
        self._idx = idx

    def finish(self, mask) -> bool:
        return self._session.verdict(self._idx)


class TensorSession:
    """All policy evaluations of one block as dense tensors.

    Lifecycle (driven by TxValidator):
      stage(...)    per prepared policy: register (program, identities,
                    verdict slots); returns a TensorPending or None
                    (non-tensorizable -> caller falls back to closures)
      finalize()    build the block tensors; the MSP principal matrix
                    is computed here (under the policy_gather span)
      attach_mask() bind the block's batch-verify mask; a jax device
                    mask dispatches the jitted program immediately
                    (fused downstream, no host round trip), a host
                    mask defers to the numpy interpreter
      verdicts()    the (N,) verdict vector, computed exactly once
    """

    def __init__(self, msp_mgr, seq: int = 0,
                 memo: Optional[PrincipalMemo] = None):
        self._msp_mgr = msp_mgr
        self._seq = seq
        self._memo = memo if memo is not None else \
            principal_memo_for(msp_mgr)
        self._staged: List[Tuple[TensorProgram, list, list]] = []
        self._tensors = None
        self._mask: Optional[np.ndarray] = None
        self._lazy = None
        self._verdicts: Optional[np.ndarray] = None
        self.fallbacks = 0

    def __len__(self) -> int:
        return len(self._staged)

    # -- staging ---------------------------------------------------------
    def stage(self, program: Optional[TensorProgram], idents: list,
              slots: list) -> Optional[TensorPending]:
        """Register one policy evaluation.  None (with the fallback
        counter bumped) when this evaluation cannot ride the tensors —
        the caller keeps its closure PendingEval."""
        fb, inst, _h, _m = _metrics()
        if (program is None or len(idents) > MAX_IDENTS
                or not all(self._memo.usable(i) for i in idents)):
            # non-tensorizable tree, too many identities, or an
            # identity the principal memo cannot key (idemix) — the
            # caller keeps its closure PendingEval
            self.fallbacks += 1
            fb.add(1)
            return None
        inst.add(1)
        idx = len(self._staged)
        self._staged.append((program, idents, slots))
        return TensorPending(self, idx)

    # -- tensor build (the policy_gather sub-stage) ----------------------
    def finalize(self) -> None:
        if self._tensors is not None or not self._staged:
            return
        n = len(self._staged)
        n_i = max(1, max(len(idents) for _p, idents, _s in self._staged))
        n_p = max(1, max(len(p.principals)
                         for p, _i, _s in self._staged))
        n_t = max(1, max(p.n_ops for p, _i, _s in self._staged))
        gather = np.full((n, n_i), -1, np.int32)
        host_ok = np.zeros((n, n_i), bool)
        present = np.zeros((n, n_i), bool)
        sat = np.zeros((n, n_i, n_p), bool)
        ops = np.zeros((n, n_t), np.int32)
        args = np.zeros((n, n_t), np.int32)
        memo, mgr, seq = self._memo, self._msp_mgr, self._seq
        # block-local probe cache: a 1k-tx block re-asks the same few
        # (identity, principal) pairs thousands of times — answer the
        # repeats with one dict hit instead of a locked memo probe
        # (identity objects are stable across txs via the msp cache)
        local: dict = {}
        for row, (prog, idents, slots) in enumerate(self._staged):
            ops[row, :prog.n_ops] = prog.ops
            args[row, :prog.n_ops] = prog.args
            for i, (ident, (bidx, hok)) in enumerate(zip(idents, slots)):
                present[row, i] = True
                if bidx is not None:
                    gather[row, i] = bidx
                else:
                    host_ok[row, i] = bool(hok)
                for p, (principal, pbytes) in enumerate(
                        zip(prog.principals, prog.principal_bytes)):
                    lkey = (id(ident), pbytes)
                    got = local.get(lkey)
                    if got is None:
                        got = memo.satisfied(mgr, ident, principal,
                                             pbytes, seq)
                        local[lkey] = got
                    if got:
                        sat[row, i, p] = True
        self._tensors = (gather, host_ok, present, sat, ops, args)

    # -- mask binding + evaluation ---------------------------------------
    def attach_mask(self, raw) -> None:
        """Bind the block's verify mask.  `raw` is whatever the
        verifier's resolver produced: a jax device array (the fused
        path — the jitted program is dispatched against it HERE,
        before the validator's host sync, so verify and policy overlap
        on device) or a host array (numpy interpreter at verdicts())."""
        if self._verdicts is not None or not self._staged:
            return
        self.finalize()
        if isinstance(raw, (np.ndarray, list, tuple)):
            self._mask = np.asarray(raw, bool)
            return
        # device-resident mask: pad + dispatch the jitted program now
        # (async); verdicts() syncs the result
        import jax.numpy as jnp
        gather, host_ok, present, sat, ops, args = self._pad_for_device()
        mask_len = int(raw.shape[0]) if raw.ndim else 0
        pad_m = _pow2_at_least(mask_len, 64)
        mask_dev = jnp.zeros(pad_m, bool)
        if mask_len:
            mask_dev = mask_dev.at[:mask_len].set(raw.astype(bool))
        self._lazy = _jax_eval_fn()(
            mask_dev, jnp.asarray(gather), jnp.asarray(host_ok),
            jnp.asarray(present), jnp.asarray(sat),
            jnp.asarray(np.ascontiguousarray(ops.T)),
            jnp.asarray(np.ascontiguousarray(args.T)))

    def _pad_for_device(self):
        """Pad the tight tensors to the bucketed jit shapes (bounded
        compile count; padded rows are NOP programs)."""
        gather, host_ok, present, sat, ops, args = self._tensors
        n, n_i = present.shape
        pn = _pow2_at_least(n, 8)
        pt = _pow2_at_least(ops.shape[1], 16)

        def pad(a, shape):
            out = np.zeros(shape, a.dtype)
            out[tuple(slice(0, s) for s in a.shape)] = a
            return out

        # gather pads with 0 (not -1): padded slots are present=False,
        # so their `valid` lanes are False whatever they gather
        return (pad(gather, (pn, MAX_IDENTS)),
                pad(host_ok, (pn, MAX_IDENTS)),
                pad(present, (pn, MAX_IDENTS)),
                pad(sat, (pn, MAX_IDENTS, MAX_PRINCIPALS)),
                pad(ops, (pn, pt)), pad(args, (pn, pt)))

    def verdicts(self) -> np.ndarray:
        """The (N,) verdict vector; computed exactly once."""
        if self._verdicts is not None:
            return self._verdicts
        if not self._staged:
            self._verdicts = np.zeros(0, bool)
            return self._verdicts
        if self._lazy is not None:
            self._verdicts = np.asarray(self._lazy, bool)[:len(self)]
        else:
            if self._mask is None:
                raise RuntimeError(
                    "tensor session evaluated before its verify mask "
                    "was attached (resolve_mask must run first)")
            gather, host_ok, present, sat, ops, args = self._tensors
            mask = self._mask
            if mask.size:
                valid = np.where(gather >= 0,
                                 mask[np.clip(gather, 0, mask.size - 1)],
                                 host_ok) & present
            else:
                valid = host_ok & present
            self._verdicts = eval_numpy(valid, sat, ops, args)
        return self._verdicts

    def verdict(self, idx: int) -> bool:
        return bool(self.verdicts()[idx])
