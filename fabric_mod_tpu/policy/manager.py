"""Hierarchical policy manager + implicit meta policies.

(reference: common/policies/policy.go `ManagerImpl`/`GetPolicy` and
common/policies/implicitmeta.go.)  A channel's policy tree mirrors its
config tree: the root manager holds /Channel-level policies and child
managers (Application, Orderer, per-org groups), each with their own
named policies.  Implicit meta policies ("ANY Writers", "MAJORITY
Admins") aggregate the same-named sub-policy of every child group.

Every policy object speaks the two-phase prepare/finish protocol from
cauthdsl.py so a whole block's policy checks share one device batch.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from fabric_mod_tpu.policy.cauthdsl import (
    BatchCollector, CompiledPolicy, PendingEval, PolicyError)
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos.protoutil import SignedData

# Well-known policy names (reference: common/policies/policy.go:25-47)
CHANNEL_APPLICATION_READERS = "/Channel/Application/Readers"
CHANNEL_APPLICATION_WRITERS = "/Channel/Application/Writers"
CHANNEL_APPLICATION_ADMINS = "/Channel/Application/Admins"
CHANNEL_ORDERER_BLOCK_VALIDATION = "/Channel/Orderer/BlockValidation"
CHANNEL_ORDERER_WRITERS = "/Channel/Orderer/Writers"


class _MetaPending:
    def __init__(self, pendings: List, threshold: int):
        self._pendings = pendings
        self._threshold = threshold

    def finish(self, mask) -> bool:
        got = sum(1 for p in self._pendings if p.finish(mask))
        return got >= self._threshold


class ImplicitMetaPolicyObj:
    """N-of child policies, N from ANY/ALL/MAJORITY
    (reference: common/policies/implicitmeta.go NewPolicy)."""

    def __init__(self, sub_policies: Sequence, rule: int):
        self._subs = list(sub_policies)
        n = len(self._subs)
        if rule == m.ImplicitMetaRule.ANY:
            self.threshold = 1
        elif rule == m.ImplicitMetaRule.ALL:
            self.threshold = n
        elif rule == m.ImplicitMetaRule.MAJORITY:
            self.threshold = n // 2 + 1
        else:
            raise PolicyError(f"unknown implicit meta rule {rule}")
        if n == 0:
            # pinned like the reference: a meta policy over zero
            # sub-policies can never pass (threshold 0 = fail-open)
            self.threshold = 1

    def prepare(self, signed_datas: Sequence[SignedData],
                collector: BatchCollector, session=None):
        # the meta threshold itself is a trivial host sum; the
        # sub-policies each ride the tensor session when they can
        return _MetaPending(
            [s.prepare(signed_datas, collector, session)
             for s in self._subs],
            self.threshold)

    def evaluate_signed_data(self, signed_datas: Sequence[SignedData],
                             verify_many=None) -> bool:
        collector = BatchCollector()
        pending = self.prepare(signed_datas, collector)
        if verify_many is None:
            verify_many = _first_csp_verify(self._subs)
        mask = verify_many(collector.items)
        return pending.finish(mask)


def _first_csp_verify(policies):
    got = _find_csp_verify(policies)
    if got is None:
        raise PolicyError("no signature policy beneath this meta policy")
    return got


def batch_verifier(policy, verify_many=None):
    """Resolve the `verify_many` callable `evaluate_signed_data` would
    use for this policy object: the given one when set, else the
    policy's own CSP batch path — so batched callers (the staged
    broadcast drainer) dispatch exactly the verifier the one-shot
    path would have."""
    if verify_many is not None:
        return verify_many
    if isinstance(policy, CompiledPolicy):
        return policy._default_verify
    if isinstance(policy, ImplicitMetaPolicyObj):
        return _first_csp_verify(policy._subs)
    raise PolicyError(
        f"no batch verifier for policy type {type(policy).__name__}")


def _find_csp_verify(policies):
    for p in policies:
        if isinstance(p, CompiledPolicy):
            return p._default_verify
        if isinstance(p, ImplicitMetaPolicyObj):
            got = _find_csp_verify(p._subs)
            if got is not None:
                return got
    return None


class PolicyManager:
    """One level of the policy tree (reference: policy.go ManagerImpl)."""

    def __init__(self, name: str = "Channel",
                 policies: Optional[Dict[str, object]] = None,
                 sub_managers: Optional[Dict[str, "PolicyManager"]] = None):
        self.name = name
        self._policies = dict(policies or {})
        self._subs = dict(sub_managers or {})

    # -- construction ----------------------------------------------------
    def add_policy(self, name: str, policy) -> None:
        self._policies[name] = policy

    def add_sub_manager(self, mgr: "PolicyManager") -> None:
        self._subs[mgr.name] = mgr

    def resolve_implicit_meta(self, name: str,
                              meta: m.ImplicitMetaPolicy) -> None:
        """Materialize an implicit meta policy over the current children
        (call after the child managers/policies exist)."""
        subs = [s._policies[meta.sub_policy] for s in self._subs.values()
                if meta.sub_policy in s._policies]
        self._policies[name] = ImplicitMetaPolicyObj(subs, meta.rule)

    # -- lookup ----------------------------------------------------------
    def sub_manager(self, name: str) -> Optional["PolicyManager"]:
        return self._subs.get(name)

    def get_policy(self, path: str):
        """Absolute ("/Channel/Application/Writers") or relative
        ("Writers") lookup; None when absent."""
        if path.startswith("/"):
            parts = [p for p in path.split("/") if p]
            if not parts or parts[0] != self.name:
                return None
            mgr: Optional[PolicyManager] = self
            for part in parts[1:-1]:
                mgr = mgr.sub_manager(part) if mgr else None
            return mgr._policies.get(parts[-1]) if mgr else None
        return self._policies.get(path)


def policy_from_proto(pol: m.Policy, msp_mgr) -> object:
    """Decode a config-tree Policy proto into an evaluator (signature
    policies only here; implicit meta needs the tree context — use
    PolicyManager.resolve_implicit_meta)."""
    if pol.type == m.PolicyType.SIGNATURE:
        return compile_policy_bytes(pol.value, msp_mgr)
    raise PolicyError(f"unsupported policy type {pol.type}")


# ---------------------------------------------------------------------------
# Compiled-policy memo: one CompiledPolicy per (envelope bytes, config
# sequence) per MSP manager.  Before this memo every evaluation SITE
# (each ApplicationPolicyEvaluator instance, each bundle build, each
# gossip eligibility check) re-decoded the envelope and re-compiled
# the closure tree for bytes it had already seen; the memo makes the
# compile a dict hit.  Weak-keyed by the manager so a bundle swap
# (new MspManager) can never serve policies bound to dead trust
# roots, and the sequence key guards any manager mutated in place.
# ---------------------------------------------------------------------------

import threading as _threading
import weakref as _weakref

_COMPILE_MEMO: "_weakref.WeakKeyDictionary" = _weakref.WeakKeyDictionary()
_COMPILE_LOCK = _threading.Lock()  # fmtlint: allow[locks] -- leaf lock guarding a memo dict get-or-create, never nested
_COMPILE_MEMO_CAP = 4096


def compile_policy_bytes(policy_bytes: bytes, msp_mgr,
                         sequence: int = 0) -> CompiledPolicy:
    """SignaturePolicyEnvelope bytes -> CompiledPolicy, memoized."""
    key = (bytes(policy_bytes), sequence)
    with _COMPILE_LOCK:
        per = _COMPILE_MEMO.get(msp_mgr)
        if per is None:
            per = {}
            _COMPILE_MEMO[msp_mgr] = per
        got = per.get(key)
    if got is not None:
        return got
    env = m.SignaturePolicyEnvelope.decode(policy_bytes)
    pol = CompiledPolicy(env, msp_mgr)
    with _COMPILE_LOCK:
        if len(per) >= _COMPILE_MEMO_CAP:
            # the live set (a channel's distinct policies) is tiny
            # next to the bound; overflow means sequence churn, and
            # stale epochs never hit again — reset beats LRU here
            per.clear()
        per[key] = pol
    return pol
