"""L2 signature-policy engine.

Compile SignaturePolicyEnvelope trees to evaluators (cauthdsl), parse
the "AND('Org1.member', ...)" DSL (policydsl), organize policies into
the channel's hierarchical manager (manager), and evaluate application
endorsement policies (application).  Evaluation is two-phase so a
block's worth of policy checks share ONE device batch-verify —
see cauthdsl.py's module docstring — and, with
FABRIC_MOD_TPU_TENSOR_POLICY armed, a whole block's policy verdicts
evaluate as dense tensors in one program fused downstream of that
batch verify (tensorpolicy).
"""
from fabric_mod_tpu.policy.cauthdsl import (  # noqa: F401
    BatchCollector, CompiledPolicy, PendingEval, PolicyError)
from fabric_mod_tpu.policy.policydsl import DslError, from_string  # noqa: F401
from fabric_mod_tpu.policy.manager import (  # noqa: F401
    ImplicitMetaPolicyObj, PolicyManager, compile_policy_bytes,
    policy_from_proto)
from fabric_mod_tpu.policy.application import ApplicationPolicyEvaluator  # noqa: F401
