"""Application (endorsement) policy evaluation — what VSCC consumes.

(reference: core/policy/application.go:115-161
`ApplicationPolicyEvaluator.Evaluate`: an ApplicationPolicy proto is
either an inline SignaturePolicyEnvelope or a named reference into the
channel's policy manager.)
"""
from __future__ import annotations

from typing import Optional, Sequence

from fabric_mod_tpu.policy.cauthdsl import (
    BatchCollector, CompiledPolicy, PolicyError)
from fabric_mod_tpu.policy.manager import PolicyManager
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos.protoutil import SignedData


class ApplicationPolicyEvaluator:
    def __init__(self, msp_mgr, channel_policy_manager: Optional[PolicyManager] = None):
        self._msp_mgr = msp_mgr
        self._channel_mgr = channel_policy_manager
        self._compiled_cache: dict = {}

    def _resolve(self, policy_bytes: bytes):
        """ApplicationPolicy bytes -> two-phase policy object.

        Inline signature policies are compile-cached by their bytes
        (immutable); channel references are re-resolved on every call
        like the reference (core/policy/application.go Evaluate) so a
        config update that replaces the named policy takes effect
        immediately.
        """
        cached = self._compiled_cache.get(policy_bytes)
        if cached is not None:
            return cached
        ap = m.ApplicationPolicy.decode(policy_bytes)
        if ap.signature_policy is not None:
            pol = CompiledPolicy(ap.signature_policy, self._msp_mgr)
            self._compiled_cache[policy_bytes] = pol
            return pol
        if ap.channel_config_policy_reference:
            if self._channel_mgr is None:
                raise PolicyError("no channel policy manager configured")
            pol = self._channel_mgr.get_policy(
                ap.channel_config_policy_reference)
            if pol is None:
                raise PolicyError(
                    f"channel policy "
                    f"{ap.channel_config_policy_reference!r} not found")
            return pol
        raise PolicyError("empty ApplicationPolicy")

    def prepare(self, policy_bytes: bytes,
                signed_datas: Sequence[SignedData],
                collector: BatchCollector):
        return self._resolve(policy_bytes).prepare(signed_datas, collector)

    def evaluate(self, policy_bytes: bytes,
                 signed_datas: Sequence[SignedData],
                 verify_many=None) -> bool:
        return self._resolve(policy_bytes).evaluate_signed_data(
            signed_datas, verify_many)
