"""Application (endorsement) policy evaluation — what VSCC consumes.

(reference: core/policy/application.go:115-161
`ApplicationPolicyEvaluator.Evaluate`: an ApplicationPolicy proto is
either an inline SignaturePolicyEnvelope or a named reference into the
channel's policy manager.)
"""
from __future__ import annotations

from typing import Optional, Sequence

from fabric_mod_tpu.policy.cauthdsl import (
    BatchCollector, CompiledPolicy, PolicyError)
from fabric_mod_tpu.policy.manager import PolicyManager
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos.protoutil import SignedData


class ApplicationPolicyEvaluator:
    # the validator passes its tensor session only to evaluators that
    # declare this — third-party validation plugins keep the 3-arg
    # prepare(policy, sds, collector) contract untouched
    supports_tensor_session = True

    def __init__(self, msp_mgr,
                 channel_policy_manager: Optional[PolicyManager] = None,
                 sequence: int = 0):
        """`sequence` is the owning bundle's config sequence: it keys
        the shared compiled-policy memo (policy/manager.py), so a
        config update can never be answered from a stale compile."""
        self._msp_mgr = msp_mgr
        self._channel_mgr = channel_policy_manager
        self._sequence = sequence
        self._compiled_cache: dict = {}

    def _resolve(self, policy_bytes: bytes):
        """ApplicationPolicy bytes -> two-phase policy object.

        Inline signature policies are compile-cached by their bytes
        (immutable) on this instance, backed by the shared
        (bytes, config sequence)-keyed memo in policy/manager.py so a
        rebuilt evaluator (new validator, bench world, gossip path)
        reuses compiles instead of re-decoding; channel references are
        re-resolved on every call like the reference
        (core/policy/application.go Evaluate) so a config update that
        replaces the named policy takes effect immediately.
        """
        cached = self._compiled_cache.get(policy_bytes)
        if cached is not None:
            return cached
        ap = m.ApplicationPolicy.decode(policy_bytes)
        if ap.signature_policy is not None:
            from fabric_mod_tpu.policy.manager import compile_policy_bytes
            pol = compile_policy_bytes(ap.signature_policy.encode(),
                                       self._msp_mgr, self._sequence)
            self._compiled_cache[policy_bytes] = pol
            return pol
        if ap.channel_config_policy_reference:
            if self._channel_mgr is None:
                raise PolicyError("no channel policy manager configured")
            pol = self._channel_mgr.get_policy(
                ap.channel_config_policy_reference)
            if pol is None:
                raise PolicyError(
                    f"channel policy "
                    f"{ap.channel_config_policy_reference!r} not found")
            return pol
        raise PolicyError("empty ApplicationPolicy")

    def prepare(self, policy_bytes: bytes,
                signed_datas: Sequence[SignedData],
                collector: BatchCollector, session=None):
        return self._resolve(policy_bytes).prepare(
            signed_datas, collector, session)

    def evaluate(self, policy_bytes: bytes,
                 signed_datas: Sequence[SignedData],
                 verify_many=None) -> bool:
        return self._resolve(policy_bytes).evaluate_signed_data(
            signed_datas, verify_many)
