"""Policy DSL: "AND('Org1.member', OR('Org2.admin', ...))" -> proto.

(reference: common/policydsl/policyparser.go `FromString` and the
builders in policydsl_builder.go.)  Grammar:

    expr     := AND '(' args ')' | OR '(' args ')'
              | OUTOF '(' n ',' args ')' | principal
    principal:= 'Msp.role' — role in member|admin|client|peer|orderer

AND = OutOf(len), OR = OutOf(1).  Keywords are case-insensitive like
the reference's regexp-based parser; principals must be quoted.
Identical principals are deduplicated into one identities entry, same
as the reference.
"""
from __future__ import annotations

import re
from typing import List, Tuple

from fabric_mod_tpu.protos import messages as m

_ROLES = {
    "member": m.MSPRoleType.MEMBER,
    "admin": m.MSPRoleType.ADMIN,
    "client": m.MSPRoleType.CLIENT,
    "peer": m.MSPRoleType.PEER,
    "orderer": m.MSPRoleType.ORDERER,
}

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<kw>(?i:and|or|outof))\b
    | (?P<num>\d+)
    | (?P<q>'[^']*'|"[^"]*")
    | (?P<punc>[(),])
    )""", re.VERBOSE)


class DslError(Exception):
    pass


def _tokenize(s: str) -> List[Tuple[str, str]]:
    toks, pos = [], 0
    while pos < len(s):
        mt = _TOKEN.match(s, pos)
        if mt is None:
            if s[pos:].strip() == "":
                break
            raise DslError(f"bad token at {s[pos:pos+20]!r}")
        pos = mt.end()
        for kind in ("kw", "num", "q", "punc"):
            v = mt.group(kind)
            if v is not None:
                toks.append((kind, v.lower() if kind == "kw" else v))
                break
    return toks


class _Parser:
    def __init__(self, toks: List[Tuple[str, str]]):
        self.toks = toks
        self.i = 0
        # principal key -> identities index (dedup, like the reference)
        self.principals: dict = {}

    def _peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def _next(self):
        t = self._peek()
        if t[0] is None:
            raise DslError("unexpected end of policy")
        self.i += 1
        return t

    def _expect(self, val: str):
        kind, v = self._next()
        if v != val:
            raise DslError(f"expected {val!r}, got {v!r}")

    def parse(self) -> m.SignaturePolicy:
        rule = self._expr()
        if self._peek()[0] is not None:
            raise DslError(f"trailing input at token {self.i}")
        return rule

    def _expr(self) -> m.SignaturePolicy:
        kind, v = self._next()
        if kind == "kw":
            self._expect("(")
            if v == "outof":
                nk, nv = self._next()
                if nk != "num":
                    raise DslError("OutOf needs a leading count")
                n = int(nv)
                self._expect(",")
            args = [self._expr()]
            while self._peek()[1] == ",":
                self._next()
                args.append(self._expr())
            self._expect(")")
            if v == "and":
                n = len(args)
            elif v == "or":
                n = 1
            elif not 0 <= n <= len(args):
                raise DslError(f"OutOf({n}) with {len(args)} rules")
            return m.SignaturePolicy(n_out_of=m.NOutOf(n=n, rules=args))
        if kind == "q":
            return self._leaf(v[1:-1])
        raise DslError(f"unexpected token {v!r}")

    def _leaf(self, spec: str) -> m.SignaturePolicy:
        if "." not in spec:
            raise DslError(f"principal {spec!r} is not 'Msp.role'")
        mspid, role = spec.rsplit(".", 1)
        if role not in _ROLES:
            raise DslError(f"unknown role {role!r}")
        key = (mspid, role)
        if key not in self.principals:
            self.principals[key] = len(self.principals)
        return m.SignaturePolicy(signed_by=self.principals[key])


def from_string(policy: str) -> m.SignaturePolicyEnvelope:
    """Parse the DSL into a SignaturePolicyEnvelope
    (reference: policyparser.go FromString)."""
    p = _Parser(_tokenize(policy))
    rule = p.parse()
    identities = [
        m.MSPPrincipal(
            principal_classification=m.PrincipalClassification.ROLE,
            principal=m.MSPRole(msp_identifier=mspid,
                                role=_ROLES[role]).encode())
        for (mspid, role) in p.principals
    ]
    return m.SignaturePolicyEnvelope(version=0, rule=rule,
                                     identities=identities)


# -- builders (reference: policydsl_builder.go) -----------------------------

def signed_by_msp_member(mspid: str) -> m.SignaturePolicyEnvelope:
    return from_string(f"OR('{mspid}.member')")


def signed_by_any_member(mspids) -> m.SignaturePolicyEnvelope:
    inner = ", ".join(f"'{x}.member'" for x in mspids)
    return from_string(f"OR({inner})")


def signed_by_majority_admins(mspids) -> m.SignaturePolicyEnvelope:
    n = len(mspids) // 2 + 1
    inner = ", ".join(f"'{x}.admin'" for x in mspids)
    return from_string(f"OutOf({n}, {inner})")
