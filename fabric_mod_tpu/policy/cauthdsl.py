"""Signature-policy compilation and batch-first evaluation.

The L2 core (reference: common/cauthdsl/cauthdsl.go:24-92 `compile`,
common/cauthdsl/policy.go:87 `EvaluateSignedData`, and
common/policies/policy.go:365-403 `SignatureSetToValidIdentities`).

The reference's evaluation shape is already ideal for a device batch:
it *first* deduplicates identities and eagerly verifies every
signature, *then* runs the combinatorial NOutOf/SignedBy walk over the
set of validated identities.  Here that split is explicit and
two-phase so a block validator can gather the signature sets of every
policy evaluation in a block, fire ONE device batch-verify, and only
then finish each policy decision host-side:

    collector = BatchCollector()
    pending = [pol.prepare(sds, collector) for (pol, sds) in work]
    mask = verifier.verify_many(collector.items)   # one device call
    results = [p.finish(mask) for p in pending]

`CompiledPolicy.evaluate_signed_data` is the standalone convenience
that does all three steps with a single verify call of its own.

Host-side work stays host-side: identity deserialization, cert-chain
validation, and principal matching are pointer-chasing x509 logic the
MSP (with its second-chance caches) already handles; only the ECDSA
math rides the batch.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from fabric_mod_tpu.bccsp.api import VerifyItem
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos.protoutil import SignedData


class PolicyError(Exception):
    pass


class BatchCollector:
    """Accumulates VerifyItems across many policy evaluations so they
    can be verified in one device dispatch.  Identical work items
    (same digest, signature, key) dedup to one batch slot — meta
    policies hand the same signature set to every sub-policy, and
    re-verifying it per sub-policy would multiply the device batch."""

    def __init__(self):
        self.items: List[VerifyItem] = []
        self.requests = 0          # add() calls incl. dedup hits — the
        self._index: dict = {}     # spread vs len(items) is staged work
        #                            the dedup saved (validator metrics)

    def add(self, item: VerifyItem) -> int:
        self.requests += 1
        # message MUST be part of the key: two raw-message items
        # (FABRIC_MOD_TPU_FUSED_HASH) share digest=b"" — deduping on
        # (digest, sig, key) alone would let a replayed signature over
        # a DIFFERENT message share the valid item's verdict slot
        key = (item.digest, item.signature, item.public_xy,
               getattr(item, "message", None))
        got = self._index.get(key)
        if got is not None:
            return got
        self.items.append(item)
        idx = len(self.items) - 1
        self._index[key] = idx
        return idx


class PendingEval:
    """A policy decision waiting on the device verdict mask.

    `slots` pairs each candidate identity with either the index of its
    VerifyItem in the collector batch or a host-computed verdict (for
    non-batchable curves).
    """

    def __init__(self, closure: Callable, idents: List,
                 slots: List[tuple]):
        self._closure = closure
        self._idents = idents
        self._slots = slots                 # (batch_idx | None, host_ok)

    def finish(self, mask) -> bool:
        """Resolve against the batch verdict mask -> policy verdict."""
        valid = []
        for ident, (bidx, host_ok) in zip(self._idents, self._slots):
            ok = bool(mask[bidx]) if bidx is not None else host_ok
            if ok:
                valid.append(ident)
        used = [False] * len(valid)
        return self._closure(valid, used)


def _compile(rule: m.SignaturePolicy,
             principals: Sequence[m.MSPPrincipal],
             msp_mgr) -> Callable:
    """SignaturePolicy proto tree -> closure(idents, used) -> bool
    (reference: cauthdsl.go:24-92 — same greedy used-flag semantics)."""
    if rule.n_out_of is not None:
        n = rule.n_out_of.n
        subs = [_compile(r, principals, msp_mgr) for r in rule.n_out_of.rules]

        def node(idents, used) -> bool:
            # Trial/commit used-flag discipline, no early exit — exactly
            # the reference's loop (cauthdsl.go:45-60): a failed child
            # must not consume identities, and later children still run
            # so the committed used-set matches the reference's.
            verified = 0
            for sub in subs:
                trial = list(used)
                if sub(idents, trial):
                    verified += 1
                    used[:] = trial
            return verified >= n
        return node

    idx = rule.signed_by
    if not 0 <= idx < len(principals):
        raise PolicyError(f"identity index {idx} out of range")
    principal = principals[idx]

    def leaf(idents, used) -> bool:
        for i, ident in enumerate(idents):
            if used[i]:
                continue
            if msp_mgr.satisfies_principal(ident, principal):
                used[i] = True
                return True
        return False
    return leaf


class CompiledPolicy:
    """A compiled SignaturePolicyEnvelope bound to an MSP manager.

    (reference: cauthdsl/policy.go `policy` + the provider at :25)
    """

    # sentinel: tensor compilation not attempted yet (None is a valid
    # outcome meaning "non-tensorizable")
    _TENSOR_UNSET = object()

    def __init__(self, envelope: m.SignaturePolicyEnvelope, msp_mgr):
        if envelope.rule is None:
            raise PolicyError("policy envelope has no rule")
        self._msp_mgr = msp_mgr
        self._closure = _compile(envelope.rule, envelope.identities, msp_mgr)
        self.envelope = envelope
        self._tensor = CompiledPolicy._TENSOR_UNSET

    def tensor_program(self):
        """The policy's flattened tensor form (policy/tensorpolicy.py),
        compiled once and cached; None when the tree is
        non-tensorizable (over the caps) and evaluations must stay on
        the closure path."""
        if self._tensor is CompiledPolicy._TENSOR_UNSET:
            from fabric_mod_tpu.policy.tensorpolicy import (
                compile_tensor_program)
            self._tensor = compile_tensor_program(self.envelope)
        return self._tensor

    # -- phase 1: dedup + validate + stage verifies ----------------------
    def prepare(self, signed_datas: Sequence[SignedData],
                collector: BatchCollector, session=None):
        """Dedup identities, drop undeserializable/invalid ones, stage
        each survivor's signature check into `collector` (reference:
        common/policies/policy.go:365-403, which dedups then verifies
        every signature before the policy walk).

        With a `session` (policy/tensorpolicy.TensorSession) the
        evaluation registers as one row of the block's dense tensors
        and the returned pending resolves from the session's single
        whole-block evaluator pass; without one (or when this policy
        is non-tensorizable) the classic closure PendingEval comes
        back — verdicts are identical either way."""
        idents: List = []
        slots: List[tuple] = []
        seen = set()
        for sd in signed_datas:
            if sd.identity in seen:
                continue                      # duplicate identity: skip
            seen.add(sd.identity)
            try:
                ident = self._msp_mgr.deserialize_identity(sd.identity)
            except Exception:
                continue                      # unknown MSP / bad cert
            try:
                self._msp_mgr.validate(ident)
            except Exception:
                continue                      # expired/revoked/untrusted
            item = ident.verify_item(sd.data, sd.signature)
            if item is not None:
                slots.append((collector.add(item), False))
            else:                             # non-P256: host verify now
                slots.append((None, ident.verify(sd.data, sd.signature)))
            idents.append(ident)
        if session is not None:
            pending = session.stage(self.tensor_program(), idents, slots)
            if pending is not None:
                return pending
        return PendingEval(self._closure, idents, slots)

    def satisfied_by_principals(self, idents: Sequence) -> bool:
        """Principal-only evaluation — no signatures involved (the
        reference's AccessFilter use: is this SET OF IDENTITIES inside
        the policy, e.g. collection membership checks at private-data
        dissemination time)."""
        used = [False] * len(idents)
        return self._closure(list(idents), used)

    # -- phases 1+2+3 standalone -----------------------------------------
    def evaluate_signed_data(self, signed_datas: Sequence[SignedData],
                             verify_many: Optional[Callable] = None) -> bool:
        """One-shot evaluation with its own single batch dispatch.
        `verify_many` defaults to the MSP's CSP batch path."""
        collector = BatchCollector()
        pending = self.prepare(signed_datas, collector)
        mask = (verify_many or self._default_verify)(collector.items)
        return pending.finish(mask)

    def _default_verify(self, items: Sequence[VerifyItem]):
        csp = getattr(self._msp_mgr, "csp", None)
        if csp is None:
            # fall back to any MSP's provider — they share the process CSP
            msps = self._msp_mgr.msps()
            if not msps:
                raise PolicyError("no MSPs configured")
            csp = msps[0]._csp
        return csp.verify_batch(items)
