"""The X.509 MSP: deserialize, validate, classify, match principals.

(reference: msp/mspimpl.go, msp/mspimplvalidate.go, msp/mspimplsetup.go)

Validation builds the issuer chain by subject lookup against the MSP's
root/intermediate CAs and checks each link's signature, validity
window, CA flag, and (for leaves) revocation — the same checks the
reference performs with Go's x509 machinery, done explicitly here so
the trust model is visible and auditable.  Role classification uses
NodeOUs (OU=client/peer/admin/orderer) like reference v1.4.3+
configs, with an explicit admin-cert list as fallback.
"""
from __future__ import annotations

import datetime
from typing import Dict, List, Optional, Sequence

try:
    from cryptography import x509
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric import (ec,
                                                           padding as _pad)
except ImportError:
    # Wheel-less container: minimal DER x509 fallback (see
    # bccsp/_x509fallback.py; bccsp/sw.py logged the downgrade).  RSA
    # chain links cannot occur there — our CA lib only mints EC certs.
    from fabric_mod_tpu.bccsp import _x509fallback as x509
    from fabric_mod_tpu.bccsp._ecfallback import InvalidSignature, ec
    from fabric_mod_tpu.bccsp._ecfallback import _Raiser
    _pad = _Raiser("RSA padding")

from fabric_mod_tpu.bccsp.api import BCCSP
from fabric_mod_tpu.msp.identities import (
    Identity, SigningIdentity, deserialize_cert, cert_fingerprint)
from fabric_mod_tpu.protos import messages as m


class MSPValidationError(Exception):
    pass


def _check_link(child: x509.Certificate, issuer: x509.Certificate) -> bool:
    """Does `issuer` sign `child`?  (EC-only chain links.)"""
    pub = issuer.public_key()
    try:
        if isinstance(pub, ec.EllipticCurvePublicKey):
            pub.verify(child.signature, child.tbs_certificate_bytes,
                       ec.ECDSA(child.signature_hash_algorithm))
        else:                            # RSA CA (not issued by our CA lib)
            pub.verify(child.signature, child.tbs_certificate_bytes,
                       _pad.PKCS1v15(), child.signature_hash_algorithm)
        return True
    except InvalidSignature:
        return False
    except Exception:
        # Attacker-supplied certs can raise far beyond InvalidSignature
        # (UnsupportedAlgorithm on unknown sig-alg OIDs, ValueError /
        # TypeError on malformed params); any failure to verify the
        # link is a non-link, never a crash on the validation path.
        return False


def _is_ca_cert(cert: x509.Certificate) -> bool:
    try:
        bc = cert.extensions.get_extension_for_class(
            x509.BasicConstraints).value
        return bool(bc.ca)
    except x509.ExtensionNotFound:
        return False
    except Exception as e:               # duplicate/malformed extensions
        raise MSPValidationError(f"malformed certificate extensions: {e}")


class NodeOUs:
    """OU-based role classification config (reference:
    msp/configbuilder.go NodeOUs)."""

    def __init__(self, enable: bool = True, client_ou: str = "client",
                 peer_ou: str = "peer", admin_ou: str = "admin",
                 orderer_ou: str = "orderer"):
        self.enable = enable
        self.client_ou, self.peer_ou = client_ou, peer_ou
        self.admin_ou, self.orderer_ou = admin_ou, orderer_ou


class Msp:
    def __init__(self, mspid: str, csp: BCCSP,
                 root_certs: Sequence[x509.Certificate],
                 intermediate_certs: Sequence[x509.Certificate] = (),
                 admin_certs: Sequence[x509.Certificate] = (),
                 revoked_serials: Sequence[int] = (),
                 crls: Sequence[x509.CertificateRevocationList] = (),
                 node_ous: Optional[NodeOUs] = None):
        self.mspid = mspid
        self._csp = csp
        self.roots = list(root_certs)
        self.intermediates = list(intermediate_certs)
        self._by_subject: Dict[bytes, List[x509.Certificate]] = {}
        for c in [*self.roots, *self.intermediates]:
            self._by_subject.setdefault(
                c.subject.public_bytes(), []).append(c)
        self._root_fps = {cert_fingerprint(c) for c in self.roots}
        self._admin_fps = {cert_fingerprint(c) for c in admin_certs}
        self._revoked = set(revoked_serials)
        # CRLs (reference: msp/mspimplvalidate.go isIdentityRevoked):
        # only CRLs verifiably signed by one of our CAs contribute, and
        # each entry revokes (issuer, serial) — serials are only unique
        # per CA, so a CRL from CA1 must not shadow CA2's serial space.
        self._crl_revoked: set = set()   # {(issuer_subject_der, serial)}
        for crl in crls:
            issuer_cands = self._by_subject.get(
                crl.issuer.public_bytes(), [])
            if not any(crl.is_signature_valid(c.public_key())
                       for c in issuer_cands):
                raise MSPValidationError(
                    "CRL not signed by a trusted CA of this MSP")
            for rc in crl:
                self._crl_revoked.add(
                    (crl.issuer.public_bytes(), rc.serial_number))
        self.node_ous = node_ous or NodeOUs()

    # -- identity lifecycle --
    def deserialize_identity(self, serialized: bytes) -> Identity:
        sid = m.SerializedIdentity.decode(serialized)
        if sid.mspid != self.mspid:
            raise MSPValidationError(
                f"identity MSP {sid.mspid!r} != {self.mspid!r}")
        cert = deserialize_cert(sid.id_bytes)
        return Identity(self.mspid, cert, self._csp)

    def validate(self, ident: Identity) -> None:
        """Raise MSPValidationError unless the identity chains to our
        roots and is unexpired/unrevoked.

        CA certificates are not identities (reference:
        msp/mspimpl.go:713-716 'A CA certificate cannot be used
        directly as an identity', chain length >= 2 at
        mspimpl.go:747-749): a leaf with BasicConstraints CA=true — or
        one of the trust anchors themselves — is rejected outright.
        """
        if _is_ca_cert(ident.cert):
            raise MSPValidationError(
                "a CA certificate cannot be used as an identity")
        chain = self._chain_for(ident.cert)
        if len(chain) < 2:
            raise MSPValidationError(
                "identity chain must include at least one CA above the leaf")
        now = datetime.datetime.now(datetime.timezone.utc)
        for cert in chain:
            if now < cert.not_valid_before_utc or now > cert.not_valid_after_utc:
                raise MSPValidationError(
                    f"certificate {cert.subject.rfc4514_string()!r} outside"
                    " validity window")
            # Revocation applies to the whole chain: a revoked
            # intermediate invalidates everything beneath it.
            if (cert.serial_number in self._revoked
                    or (cert.issuer.public_bytes(), cert.serial_number)
                    in self._crl_revoked):
                raise MSPValidationError("certificate revoked")
        self._check_key_usage(ident.cert)

    @staticmethod
    def _check_key_usage(cert: x509.Certificate) -> None:
        """Leaves carrying a KeyUsage extension must allow
        digitalSignature — identities exist to sign."""
        try:
            ku = cert.extensions.get_extension_for_class(x509.KeyUsage).value
        except x509.ExtensionNotFound:
            return
        except Exception as e:           # duplicate/malformed extensions
            raise MSPValidationError(
                f"malformed certificate extensions: {e}")
        if not ku.digital_signature:
            raise MSPValidationError(
                "leaf KeyUsage does not permit digitalSignature")

    def is_valid(self, ident: Identity) -> bool:
        try:
            self.validate(ident)
            return True
        except MSPValidationError:
            return False

    def _chain_for(self, cert: x509.Certificate) -> List[x509.Certificate]:
        """leaf -> ... -> root.  Raises if no path to a root exists."""
        chain = [cert]
        cur = cert
        for _ in range(10):                        # depth bound
            if cert_fingerprint(cur) in self._root_fps:
                return chain
            candidates = self._by_subject.get(
                cur.issuer.public_bytes(), [])
            issuer = next((c for c in candidates if _check_link(cur, c)), None)
            if issuer is None:
                raise MSPValidationError(
                    f"no trusted issuer for {cur.subject.rfc4514_string()!r}")
            try:
                bc = issuer.extensions.get_extension_for_class(
                    x509.BasicConstraints).value
                if not bc.ca:
                    raise MSPValidationError("issuer is not a CA")
            except x509.ExtensionNotFound:
                raise MSPValidationError("issuer lacks BasicConstraints")
            chain.append(issuer)
            cur = issuer
        raise MSPValidationError("chain too deep")

    # -- roles / principals --
    def _has_ou(self, ident: Identity, ou: str) -> bool:
        return ou in ident.organizational_units()

    def is_admin(self, ident: Identity) -> bool:
        if cert_fingerprint(ident.cert) in self._admin_fps:
            return True
        return self.node_ous.enable and self._has_ou(
            ident, self.node_ous.admin_ou)

    def satisfies_principal(self, ident: Identity,
                            principal: m.MSPPrincipal) -> bool:
        """(reference: msp/mspimpl.go SatisfiesPrincipal)"""
        cls = principal.principal_classification
        if cls == m.PrincipalClassification.ROLE:
            role = m.MSPRole.decode(principal.principal)
            if role.msp_identifier != self.mspid:
                return False
            if not self.is_valid(ident):
                return False
            r = role.role
            if r == m.MSPRoleType.MEMBER:
                return True
            if r == m.MSPRoleType.ADMIN:
                return self.is_admin(ident)
            if r == m.MSPRoleType.CLIENT:
                return self._has_ou(ident, self.node_ous.client_ou)
            if r == m.MSPRoleType.PEER:
                return self._has_ou(ident, self.node_ous.peer_ou)
            if r == m.MSPRoleType.ORDERER:
                return self._has_ou(ident, self.node_ous.orderer_ou)
            return False
        if cls == m.PrincipalClassification.IDENTITY:
            return principal.principal == ident.serialize()
        if cls == m.PrincipalClassification.ORGANIZATION_UNIT:
            ou = m.OrganizationUnit.decode(principal.principal)
            return (ou.msp_identifier == self.mspid
                    and self.is_valid(ident)
                    and self._has_ou(ident, ou.organizational_unit_identifier))
        return False

    # -- signing identity construction --
    def signing_identity(self, cert_pem: bytes,
                         key_pem: bytes) -> SigningIdentity:
        cert = deserialize_cert(cert_pem)
        return SigningIdentity(self.mspid, cert, key_pem, self._csp)


class MspManager:
    """Routes serialized identities to the right MSP by mspid
    (reference: msp/mspmgrimpl.go)."""

    def __init__(self, msps: Sequence[Msp] = ()):
        self._msps: Dict[str, Msp] = {m_.mspid: m_ for m_ in msps}

    def add(self, msp: Msp) -> None:
        self._msps[msp.mspid] = msp

    def get(self, mspid: str) -> Optional[Msp]:
        return self._msps.get(mspid)

    def msps(self) -> List[Msp]:
        return list(self._msps.values())

    def deserialize_identity(self, serialized: bytes) -> Identity:
        sid = m.SerializedIdentity.decode(serialized)
        msp = self._msps.get(sid.mspid)
        if msp is None:
            raise MSPValidationError(f"unknown MSP {sid.mspid!r}")
        return msp.deserialize_identity(serialized)

    def validate(self, ident: Identity) -> None:
        msp = self._msps.get(ident.mspid)
        if msp is None:
            raise MSPValidationError(f"unknown MSP {ident.mspid!r}")
        msp.validate(ident)

    def satisfies_principal(self, ident: Identity,
                            principal: m.MSPPrincipal) -> bool:
        msp = self._msps.get(ident.mspid)
        return msp is not None and msp.satisfies_principal(ident, principal)
