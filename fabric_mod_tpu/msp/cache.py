"""Second-chance caches around an MSP (reference: msp/cache/cache.go,
msp/cache/second_chance.go) — the reference's amortization for
repeated deserialize/validate/satisfies-principal on hot identities.
The TPU batch path reduces how much this matters for raw verifies, but
deserialization and chain validation are still host-side and worth
caching.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional
from fabric_mod_tpu.concurrency.locks import RegisteredLock


class SecondChanceCache:
    """Clock (second-chance) eviction, thread-safe."""

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._lock = RegisteredLock("msp.cache._lock")
        self._data: Dict[Any, list] = {}    # key -> [value, referenced]
        self._ring: list = []
        self._hand = 0

    def get(self, key):
        with self._lock:
            ent = self._data.get(key)
            if ent is None:
                return None
            ent[1] = True
            return ent[0]

    def put(self, key, value) -> None:
        with self._lock:
            if key in self._data:
                self._data[key][0] = value
                return
            while len(self._data) >= self.capacity:
                victim = self._ring[self._hand]
                ent = self._data.get(victim)
                if ent is not None and ent[1]:
                    ent[1] = False
                    self._hand = (self._hand + 1) % len(self._ring)
                    continue
                if ent is not None:
                    del self._data[victim]
                self._ring[self._hand] = key
                self._data[key] = [value, False]
                self._hand = (self._hand + 1) % len(self._ring)
                return
            self._ring.append(key)
            self._data[key] = [value, False]


class CachedMsp:
    """Wraps an Msp (or MspManager) with caches on the three hot calls
    (reference: msp/cache/cache.go:42-49)."""

    def __init__(self, msp, capacity: int = 256):
        self._msp = msp
        self._deser = SecondChanceCache(capacity)
        self._valid = SecondChanceCache(capacity)
        self._princ = SecondChanceCache(capacity)

    def __getattr__(self, name):
        return getattr(self._msp, name)

    def deserialize_identity(self, serialized: bytes):
        hit = self._deser.get(serialized)
        if hit is not None:
            return hit
        ident = self._msp.deserialize_identity(serialized)
        self._deser.put(serialized, ident)
        return ident

    def validate(self, ident) -> None:
        key = ident.serialize()
        cached = self._valid.get(key)
        if cached is True:
            return
        if isinstance(cached, Exception):
            raise cached
        try:
            self._msp.validate(ident)
        except Exception as e:
            self._valid.put(key, e)
            raise
        self._valid.put(key, True)

    def satisfies_principal(self, ident, principal) -> bool:
        key = (ident.serialize(), principal.encode())
        cached = self._princ.get(key)
        if cached is not None:
            return cached
        out = self._msp.satisfies_principal(ident, principal)
        self._princ.put(key, out)
        return out


class LocalMspRegistry:
    """Process-global local MSP + per-channel managers
    (reference: msp/mgmt/mspmgmt.go)."""

    def __init__(self):
        self._lock = RegisteredLock("msp.registry._lock")
        self._local: Optional[Any] = None
        self._chains: Dict[str, Any] = {}

    def set_local(self, msp) -> None:
        with self._lock:
            self._local = msp

    def local(self):
        with self._lock:
            if self._local is None:
                raise RuntimeError("local MSP not initialized")
            return self._local

    def manager_for_chain(self, chain_id: str, factory: Callable = None):
        with self._lock:
            mgr = self._chains.get(chain_id)
            if mgr is None and factory is not None:
                mgr = factory()
                self._chains[chain_id] = mgr
            return mgr


REGISTRY = LocalMspRegistry()
