"""Certificate authority helpers — crypto material generation.

The library core of the cryptogen-equivalent CLI (reference:
internal/cryptogen/ca/ca.go, internal/cryptogen/msp/msp.go) and of the
unit-test fixtures (the reference checks in MSP trees under
msp/testdata; we generate them on the fly instead).
"""
from __future__ import annotations

import datetime
from typing import Optional

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID
except ImportError:
    # Wheel-less container: the minimal DER x509 fallback (issue/parse
    # of our self-generated cert shapes — bccsp/_x509fallback.py; the
    # bccsp/sw.py import gate already logged the downgrade).
    from fabric_mod_tpu.bccsp import _x509fallback as x509
    from fabric_mod_tpu.bccsp._ecfallback import (ec, hashes,
                                                  serialization)
    NameOID = x509.NameOID


def _name(cn: str, org: Optional[str] = None, ou: Optional[list] = None):
    attrs = [x509.NameAttribute(NameOID.COMMON_NAME, cn)]
    if org:
        attrs.append(x509.NameAttribute(NameOID.ORGANIZATION_NAME, org))
    for u in ou or []:
        attrs.append(x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME, u))
    return x509.Name(attrs)


class CA:
    """A self-signed signing CA that can issue EC P-256 certs."""

    def __init__(self, name: str, org: str = "org",
                 valid_days: int = 3650):
        self.key = ec.generate_private_key(ec.SECP256R1())
        now = datetime.datetime.now(datetime.timezone.utc)
        subject = _name(name, org)
        self.cert = (
            x509.CertificateBuilder()
            .subject_name(subject).issuer_name(subject)
            .public_key(self.key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=valid_days))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .add_extension(x509.KeyUsage(
                digital_signature=True, key_cert_sign=True, crl_sign=True,
                content_commitment=False, key_encipherment=False,
                data_encipherment=False, key_agreement=False,
                encipher_only=False, decipher_only=False), critical=True)
            .sign(self.key, hashes.SHA256()))

    def issue(self, cn: str, org: Optional[str] = None,
              ous: Optional[list] = None, is_ca: bool = False,
              valid_days: int = 3650, not_after=None, not_before=None,
              key: Optional[ec.EllipticCurvePrivateKey] = None):
        """Issue a cert; returns (cert, private_key).

        An explicit past `not_after` yields a genuinely expired cert:
        `not_valid_before` is pushed before it so builder validation
        holds and the expiry fixture actually exercises the window
        check.
        """
        key = key or ec.generate_private_key(ec.SECP256R1())
        now = datetime.datetime.now(datetime.timezone.utc)
        nva = not_after or now + datetime.timedelta(days=valid_days)
        nvb = not_before or min(now - datetime.timedelta(minutes=5),
                                nva - datetime.timedelta(minutes=1))
        builder = (
            x509.CertificateBuilder()
            .subject_name(_name(cn, org, ous))
            .issuer_name(self.cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(nvb)
            .not_valid_after(nva)
            .add_extension(x509.BasicConstraints(ca=is_ca, path_length=None),
                           critical=True))
        if not is_ca:
            builder = builder.add_extension(x509.KeyUsage(
                digital_signature=True, key_cert_sign=False, crl_sign=False,
                content_commitment=False, key_encipherment=False,
                data_encipherment=False, key_agreement=False,
                encipher_only=False, decipher_only=False), critical=True)
        cert = builder.sign(self.key, hashes.SHA256())
        return cert, key

    def cert_pem(self) -> bytes:
        return self.cert.public_bytes(serialization.Encoding.PEM)


def key_pem(key) -> bytes:
    return key.private_bytes(serialization.Encoding.PEM,
                             serialization.PrivateFormat.PKCS8,
                             serialization.NoEncryption())


def cert_pem(cert) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)
