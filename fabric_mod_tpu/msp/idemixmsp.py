"""Idemix MSP: anonymous, unlinkable membership.

(reference: msp/idemixmsp.go — the MSP implementation over idemix
credentials: DeserializeIdentity decodes a presentation, Validate
checks the credential proof, Verify checks a message signature bound
to the presentation's pseudonym — and bccsp/idemix's signer bridge.)

Identities here are PRESENTATIONS: each serialized identity carries a
fresh BBS+ presentation proof disclosing only the OU + role
attributes, so two transactions by the same user are unlinkable.
Message signing uses the presentation's Fiat-Shamir binding: the
signature is a fresh presentation over the message bytes (the
reference binds a pseudonym key; the spike binds the proof itself —
same unlinkability property, simpler state).

Attribute layout (reference: idemix attributes ou/role/enrollment/
revocation-handle): [0]=OU, [1]=role, [2]=enrollment id, [3]=rh;
presentations disclose {0, 1} only.
"""
from __future__ import annotations

import hashlib
import json
from typing import Dict, Optional, Sequence, Tuple

from fabric_mod_tpu.idemix import credential as idmx
from fabric_mod_tpu.protos import messages as m

ATTR_OU, ATTR_ROLE, ATTR_EID, ATTR_RH = 0, 1, 2, 3
ROLE_MEMBER, ROLE_ADMIN = 1, 2


class IdemixError(Exception):
    pass


def _attr_int(value: str) -> int:
    return int.from_bytes(
        hashlib.sha256(value.encode()).digest(), "big") % idmx.R


class IdemixIssuer:
    """Issuer-side: setup + credential issuance (reference:
    idemixgen's issuer role + msp config generation)."""

    def __init__(self, mspid: str):
        self.mspid = mspid
        self.key = idmx.IssuerKey(["ou", "role", "enrollment", "rh"])

    def issue_user(self, enrollment_id: str, ou: str = "client",
                   role: int = ROLE_MEMBER) -> "IdemixUser":
        sk = idmx._rand_zr()
        attrs = [_attr_int(ou), role, _attr_int(enrollment_id),
                 idmx._rand_zr()]
        cred = idmx.issue(self.key, sk, attrs)
        return IdemixUser(self.mspid, sk, cred, ou, role)


class IdemixUser:
    """Holder-side: creates unlinkable signing identities."""

    def __init__(self, mspid: str, sk: int, cred: idmx.Credential,
                 ou: str, role: int):
        self.mspid = mspid
        self._sk = sk
        self._cred = cred
        self.ou = ou
        self.role = role

    @property
    def revocation_handle(self) -> int:
        return self._cred.attrs[ATTR_RH]

    def _disclosed(self, disclose_rh: bool = False) -> Dict[int, int]:
        out = {ATTR_OU: _attr_int(self.ou), ATTR_ROLE: self.role}
        if disclose_rh:
            # revocation-enforcing verifiers need the handle bound
            # into the proof (see idemix/revocation.py's privacy note)
            out[ATTR_RH] = self.revocation_handle
        return out


class IdemixSigningIdentity:
    """One unlinkable identity: a presentation bound to this session.

    sign_message(msg) creates a fresh proof over msg with the same
    disclosed attributes; verifiers check it against the issuer public
    key carried by the MSP."""

    def __init__(self, user: IdemixUser, issuer_key: idmx.IssuerKey,
                 disclose_rh: bool = False):
        self.mspid = user.mspid
        self._user = user
        self._ik = issuer_key
        self._disclose_rh = disclose_rh

    def serialize(self) -> bytes:
        payload = json.dumps({
            "ou": self._user.ou, "role": self._user.role},
            sort_keys=True).encode()
        return m.SerializedIdentity(mspid=self.mspid,
                                    id_bytes=payload).encode()

    def sign_message(self, msg: bytes) -> bytes:
        disclosed = self._user._disclosed(self._disclose_rh)
        sig = idmx.sign(self._ik, self._user._cred, self._user._sk,
                        msg, disclosed)
        d = _sig_to_dict(sig)
        if self._disclose_rh:
            d["rh"] = str(self._user.revocation_handle)
        return json.dumps(d, sort_keys=True).encode()


def _sig_to_dict(sig: idmx.Signature) -> dict:
    # JSON-safe encoding (hex for group elements/nonce, decimal
    # strings for Zr scalars).  NEVER pickle here: these bytes arrive
    # from untrusted remote clients.
    def g1(p):
        return idmx._g1_bytes(p).hex()
    return {
        "A_prime": g1(sig.A_prime), "A_bar": g1(sig.A_bar),
        "B_prime": g1(sig.B_prime), "c": str(sig.c),
        "z_e": str(sig.z_e), "z_r2": str(sig.z_r2),
        "z_r3": str(sig.z_r3), "z_s": str(sig.z_s),
        "z_sk": str(sig.z_sk),
        "z_attrs": {str(k): str(v) for k, v in sig.z_attrs.items()},
        "nonce": sig.nonce.hex(),
    }


def _sig_from_dict(d: dict) -> idmx.Signature:
    from fabric_mod_tpu.idemix.fp256bn import G1

    def g1(hexs: str) -> Optional[G1]:
        b = bytes.fromhex(hexs)
        if b == b"\x00" * 64:
            return None
        return G1(int.from_bytes(b[:32], "big"),
                  int.from_bytes(b[32:], "big"))
    return idmx.Signature(
        A_prime=g1(d["A_prime"]), A_bar=g1(d["A_bar"]),
        B_prime=g1(d["B_prime"]), c=int(d["c"]), z_e=int(d["z_e"]),
        z_r2=int(d["z_r2"]), z_r3=int(d["z_r3"]), z_s=int(d["z_s"]),
        z_sk=int(d["z_sk"]),
        z_attrs={int(k): int(v) for k, v in d["z_attrs"].items()},
        nonce=bytes.fromhex(d["nonce"]))


class IdemixIdentity:
    """Verifier-side view of a deserialized idemix identity."""

    def __init__(self, mspid: str, ou: str, role: int,
                 issuer_key: idmx.IssuerKey, cri_fn=None):
        self.mspid = mspid
        self.ou = ou
        self.role = role
        self._ik = issuer_key
        self._cri_fn = cri_fn              # () -> CRI | None

    def serialize(self) -> bytes:
        payload = json.dumps({"ou": self.ou, "role": self.role},
                             sort_keys=True).encode()
        return m.SerializedIdentity(mspid=self.mspid,
                                    id_bytes=payload).encode()

    def verify(self, msg: bytes, sig_bytes: bytes) -> bool:
        try:
            d = json.loads(sig_bytes)
            sig = _sig_from_dict(d)
        except Exception:
            return False
        disclosed = {ATTR_OU: _attr_int(self.ou),
                     ATTR_ROLE: self.role}
        cri = self._cri_fn() if self._cri_fn is not None else None
        if cri is not None:
            # revocation enforced: the presentation must disclose its
            # handle (binding it into the credential via the ordinary
            # disclosed-attribute relation) and the handle must not be
            # in the CRI (reference: signature.go:243 Ver's
            # non-revocation check).  The field is attacker
            # controlled: any malformed/out-of-range value is a
            # verification failure, never an exception (one crafted
            # signature must not abort block validation).
            try:
                rh = int(d["rh"])
                if not 0 <= rh < (1 << 256):
                    return False
            except (KeyError, ValueError, TypeError):
                return False
            if cri.is_revoked(rh):
                return False
            disclosed[ATTR_RH] = rh
        return idmx.verify(self._ik, sig, msg, disclosed)

    def verify_item(self, msg: bytes, sig: bytes):
        """No device batch path yet (KERNEL_PLAN.md R4.4): idemix
        verifies host-side, so policy staging falls back to the host
        verdict."""
        return None


class IdemixMsp:
    """(reference: msp/idemixmsp.go)"""

    def __init__(self, mspid: str, issuer_key: idmx.IssuerKey,
                 revocation_pk_pem: Optional[bytes] = None):
        self.mspid = mspid
        self._ik = issuer_key
        self._revocation_pk = revocation_pk_pem
        self._cri = None
        if not issuer_key.check_pok():
            raise IdemixError("issuer key proof of knowledge fails")

    def set_cri(self, cri, expected_epoch: Optional[int] = None) -> None:
        """Adopt a CRI after verifying the RA signature + epoch pin
        (reference: the CRI refresh of idemixmsp Setup/Validate).
        Requires the MSP to have been configured with the RA public
        key; a CRI that fails verification is refused."""
        from fabric_mod_tpu.idemix.revocation import verify_cri
        if self._revocation_pk is None:
            raise IdemixError("this MSP has no revocation authority "
                              "public key configured")
        if not verify_cri(cri, self._revocation_pk, expected_epoch):
            raise IdemixError("CRI verification failed")
        if self._cri is not None and cri.epoch < self._cri.epoch:
            raise IdemixError("CRI epoch regression")
        self._cri = cri

    def deserialize_identity(self, serialized: bytes) -> IdemixIdentity:
        sid = m.SerializedIdentity.decode(serialized)
        if sid.mspid != self.mspid:
            raise IdemixError(f"identity for {sid.mspid!r}, "
                              f"not {self.mspid!r}")
        try:
            d = json.loads(sid.id_bytes)
            ou, role = str(d["ou"]), int(d["role"])
        except Exception as e:
            raise IdemixError(f"bad idemix identity: {e}") from e
        return IdemixIdentity(self.mspid, ou, role, self._ik,
                              cri_fn=lambda: self._cri)

    def validate(self, ident: IdemixIdentity) -> None:
        if ident.mspid != self.mspid:
            raise IdemixError("wrong msp")

    def satisfies_principal(self, ident: IdemixIdentity,
                            principal: m.MSPPrincipal) -> bool:
        """(reference: idemixmsp.go SatisfiesPrincipal — role and OU
        principals over the DISCLOSED attributes)"""
        if principal.principal_classification == \
                m.PrincipalClassification.ROLE:
            role = m.MSPRole.decode(principal.principal)
            if role.msp_identifier != self.mspid:
                return False
            if role.role == m.MSPRoleType.MEMBER:
                return True
            if role.role == m.MSPRoleType.ADMIN:
                return ident.role == ROLE_ADMIN
            if role.role == m.MSPRoleType.CLIENT:
                return ident.ou == "client"
            if role.role == m.MSPRoleType.PEER:
                return ident.ou == "peer"
            return False
        if principal.principal_classification == \
                m.PrincipalClassification.ORGANIZATION_UNIT:
            ou = m.OrganizationUnit.decode(principal.principal)
            return (ou.msp_identifier == self.mspid and
                    ou.organizational_unit_identifier == ident.ou)
        return False
