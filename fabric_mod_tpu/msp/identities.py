"""X.509 identities (reference: msp/identities.go).

An Identity wraps a certificate; `verify(msg, sig)` is hash-then-
BCCSP-verify exactly like the reference (msp/identities.go:169-196),
which is what lets the TPU batch provider take over every identity
signature check in the framework.  `verify_item` exposes the same
check as a VerifyItem so callers can batch instead.
"""
from __future__ import annotations

import hashlib
from typing import Optional

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import serialization
except ImportError:
    # Wheel-less container: minimal DER x509 fallback (see
    # bccsp/_x509fallback.py; bccsp/sw.py logged the downgrade).
    from fabric_mod_tpu.bccsp import _x509fallback as x509
    from fabric_mod_tpu.bccsp._ecfallback import serialization

from fabric_mod_tpu.bccsp.api import BCCSP, VerifyItem
from fabric_mod_tpu.bccsp import sw as swlib
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.utils import knobs


def fused_hash_enabled() -> bool:
    """FABRIC_MOD_TPU_FUSED_HASH=1 moves the e = H(m) of batched
    verifies onto the device: `verify_item` emits raw-MESSAGE items
    and the TPU provider hashes them in the same jitted program as the
    ECDSA verify (ops/p256.batch_verify_raw) — no host digest loop on
    the block-commit path.  Read per call on purpose (cheap), so tests
    and bench A/B can flip it without rebuilding identities."""
    return knobs.get_bool("FABRIC_MOD_TPU_FUSED_HASH")


class Identity:
    def __init__(self, mspid: str, cert: x509.Certificate, csp: BCCSP):
        self.mspid = mspid
        self.cert = cert
        self._csp = csp
        self._key = csp.key_import(
            cert.public_key().public_bytes(
                serialization.Encoding.PEM,
                serialization.PublicFormat.SubjectPublicKeyInfo),
            "pem-pub")

    # -- serialization --
    def cert_pem(self) -> bytes:
        return self.cert.public_bytes(serialization.Encoding.PEM)

    def serialize(self) -> bytes:
        return m.SerializedIdentity(mspid=self.mspid,
                                    id_bytes=self.cert_pem()).encode()

    def ski(self) -> bytes:
        return self._key.ski()

    # -- attributes --
    def expires_at(self):
        return self.cert.not_valid_after_utc

    def organizational_units(self) -> list:
        return [ou.value for ou in self.cert.subject.get_attributes_for_oid(
            x509.NameOID.ORGANIZATIONAL_UNIT_NAME)]

    def common_name(self) -> str:
        cns = self.cert.subject.get_attributes_for_oid(x509.NameOID.COMMON_NAME)
        return cns[0].value if cns else ""

    # -- crypto --
    def digest_for(self, msg: bytes) -> bytes:
        alg = "SHA256" if self._key.curve == "P256" else "SHA384"
        return self._csp.hash(msg, alg)

    def verify(self, msg: bytes, sig: bytes) -> bool:
        """Hash-then-verify (reference: msp/identities.go:169)."""
        return self._csp.verify(self._key, sig, self.digest_for(msg))

    def verify_item(self, msg: bytes, sig: bytes) -> Optional[VerifyItem]:
        """The same check as a batchable work item (P-256 only).

        Under FABRIC_MOD_TPU_FUSED_HASH the item carries the RAW
        message instead of a host-computed digest — the TPU provider
        then computes e = H(m) on device inside the verify program
        (one dispatch for hash + ladder), which removes this method
        from the per-message hashlib loop the reference's
        hash-then-verify shape implies (msp/identities.go:169)."""
        if self._key.curve != "P256":
            return None
        if fused_hash_enabled():
            return VerifyItem(b"", sig, self._key.public_xy(),
                              message=msg)
        return VerifyItem(self.digest_for(msg), sig, self._key.public_xy())


class SigningIdentity(Identity):
    def __init__(self, mspid: str, cert: x509.Certificate,
                 private_key_pem: bytes, csp: BCCSP):
        super().__init__(mspid, cert, csp)
        self._priv = csp.key_import(private_key_pem, "pem-priv")

    def sign_message(self, msg: bytes) -> bytes:
        return self._csp.sign(self._priv, self.digest_for(msg))


def deserialize_cert(id_bytes: bytes) -> x509.Certificate:
    if id_bytes.lstrip().startswith(b"-----BEGIN"):
        return x509.load_pem_x509_certificate(id_bytes)
    return x509.load_der_x509_certificate(id_bytes)


def cert_fingerprint(cert: x509.Certificate) -> bytes:
    return hashlib.sha256(cert.public_bytes(
        serialization.Encoding.DER)).digest()
