"""Ledger snapshots: deterministic export + bootstrap import.

(reference: core/ledger/kvledger/snapshot.go:31-97 — the
generateSnapshot files (state data, txids, metadata + a signable
metadata summary with file hashes) — and the CreateFromSnapshot
bootstrap path of kv_ledger_provider.go:764: a new peer joins at
height H with the state but without blocks 0..H-1.)

File layout under <out>/:
  state.dat   checksummed (ns, key, value, version) records, sorted
  txids.dat   checksummed sorted txid list
  _snapshot_signable_metadata.json
              {channel, height, last_block_hash, files: {name: sha256}}
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import struct
from typing import Dict

from fabric_mod_tpu.ledger.blkstorage import BlockStore
from fabric_mod_tpu.ledger.statedb import UpdateBatch
from fabric_mod_tpu.protos import protoutil

METADATA_FILE = "_snapshot_signable_metadata.json"


class SnapshotError(Exception):
    pass


def _write_sealed(path: str, body: bytes) -> str:
    digest = hashlib.sha256(body).hexdigest()
    with open(path, "wb") as f:
        f.write(body)
        f.flush()
        os.fsync(f.fileno())
    return digest


def _pack(out: io.BytesIO, b: bytes) -> None:
    out.write(struct.pack("<I", len(b)))
    out.write(b)


def generate_snapshot(ledger, out_dir: str) -> Dict:
    """Export the ledger's state at its current height
    (reference: snapshot.go generateSnapshot)."""
    os.makedirs(out_dir, exist_ok=True)
    height = ledger.height
    if height == 0:
        raise SnapshotError("cannot snapshot an empty ledger")
    tip = ledger.get_block_by_number(height - 1)
    last_hash = protoutil.block_header_hash(tip.header)

    state = io.BytesIO()
    count = 0
    for ns, key, value, (bn, tn) in ledger.state.iter_state():
        _pack(state, ns.encode())
        _pack(state, key.encode())
        _pack(state, value)
        state.write(struct.pack("<qq", bn, tn))
        # key metadata rides along (state-based endorsement policies
        # must survive a snapshot join)
        meta = ledger.state.get_metadata(ns, key) or {}
        state.write(struct.pack("<I", len(meta)))
        for name, val in sorted(meta.items()):
            _pack(state, name.encode())
            _pack(state, val)
        count += 1
    txids = io.BytesIO()
    for txid in sorted(ledger.blockstore.all_txids()):
        _pack(txids, txid.encode())

    files = {
        "state.dat": _write_sealed(
            os.path.join(out_dir, "state.dat"), state.getvalue()),
        "txids.dat": _write_sealed(
            os.path.join(out_dir, "txids.dat"), txids.getvalue()),
    }
    meta = {
        "channel": ledger.ledger_id,
        "height": height,
        "last_block_hash": last_hash.hex(),
        "state_entries": count,
        "files": files,
    }
    with open(os.path.join(out_dir, METADATA_FILE), "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
    return meta


def verify_snapshot(snap_dir: str) -> Dict:
    """Checksum-verify a snapshot directory; returns its metadata."""
    with open(os.path.join(snap_dir, METADATA_FILE)) as f:
        meta = json.load(f)
    for name, want in meta["files"].items():
        raw = open(os.path.join(snap_dir, name), "rb").read()
        if hashlib.sha256(raw).hexdigest() != want:
            raise SnapshotError(f"checksum mismatch in {name}")
    return meta


def bootstrap_from_snapshot(snap_dir: str, ledger_dir: str,
                            durable: bool = True):
    """Create a new ledger at the snapshot height: state seeded, block
    store based above the pruned range (reference:
    kv_ledger_provider.go CreateFromSnapshot)."""
    from fabric_mod_tpu.ledger.kvledger import KvLedger
    meta = verify_snapshot(snap_dir)
    if os.path.exists(os.path.join(ledger_dir, "chains")):
        raise SnapshotError(f"{ledger_dir} already holds a ledger")
    height = meta["height"]
    chains = os.path.join(ledger_dir, "chains")
    BlockStore.write_base_marker(
        chains, height, bytes.fromhex(meta["last_block_hash"]))
    # seed the pruned-range txid index so duplicate-txid detection
    # still works on the joined peer
    raw_tx = open(os.path.join(snap_dir, "txids.dat"), "rb").read()
    txids = []
    pos = 0
    while pos < len(raw_tx):
        (ln,) = struct.unpack_from("<I", raw_tx, pos)
        pos += 4
        txids.append(raw_tx[pos:pos + ln].decode())
        pos += ln
    BlockStore.write_pruned_txids(chains, txids)
    led = KvLedger(ledger_dir, meta["channel"], durable=durable)
    # seed state at savepoint height-1 so recovery never replays the
    # pruned range
    raw = open(os.path.join(snap_dir, "state.dat"), "rb").read()
    batch = UpdateBatch()
    pos = 0
    while pos < len(raw):
        parts = []
        for _ in range(3):
            (ln,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            parts.append(raw[pos:pos + ln])
            pos += ln
        bn, tn = struct.unpack_from("<qq", raw, pos)
        pos += 16
        ns, key = parts[0].decode(), parts[1].decode()
        batch.put(ns, key, parts[2], (bn, tn))
        (n_meta,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        entries = {}
        for _ in range(n_meta):
            (ln,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            name = raw[pos:pos + ln].decode()
            pos += ln
            (ln,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            entries[name] = raw[pos:pos + ln]
            pos += ln
        if entries:
            batch.put_metadata(ns, key, entries, (bn, tn))
    led.state.apply_updates(batch, height - 1)
    return led
