"""Versioned key-value state DB.

(reference: core/ledger/kvledger/txmgmt/statedb/statedb.go —
`VersionedDB`, `UpdateBatch`, `CompositeKey`; the goleveldb
implementation in stateleveldb/stateleveldb.go.)

The store is an in-memory versioned map with a maintained sorted key
index per namespace (range queries are first-class because MVCC
phantom detection re-executes them) and a savepoint, exactly the
recovery contract the reference uses: state is always derivable from
the block store, so on open the ledger replays blocks past the
savepoint rather than trusting partial writes
(kv_ledger.go:228-341 recoverDBs).  Durability is a whole-DB
snapshot file written atomically every `snapshot_interval` blocks.
"""
from __future__ import annotations

import bisect
import hashlib
import io
import os
import struct
from typing import Dict, Iterator, List, Optional, Tuple

Version = Tuple[int, int]               # (block_num, tx_num)


class UpdateBatch:
    """Pending writes of one block (reference: statedb.go UpdateBatch,
    incl. the metadata writes key-level endorsement rides on)."""

    def __init__(self):
        self.updates: Dict[Tuple[str, str], Tuple[Optional[bytes], Version]] = {}
        self.meta_updates: Dict[Tuple[str, str],
                                Tuple[Dict[str, bytes], Version]] = {}

    def put(self, ns: str, key: str, value: bytes, version: Version) -> None:
        self.updates[(ns, key)] = (value, version)

    def delete(self, ns: str, key: str, version: Version) -> None:
        self.updates[(ns, key)] = (None, version)

    def put_metadata(self, ns: str, key: str, entries: Dict[str, bytes],
                     version: Version) -> None:
        self.meta_updates[(ns, key)] = (dict(entries), version)

    def get(self, ns: str, key: str):
        return self.updates.get((ns, key))

    def __len__(self) -> int:
        return len(self.updates) + len(self.meta_updates)


class VersionedDB:
    """In-memory versioned KV with per-namespace sorted key index."""

    def __init__(self):
        self._data: Dict[Tuple[str, str], Tuple[bytes, Version]] = {}
        self._metadata: Dict[Tuple[str, str], Dict[str, bytes]] = {}
        self._keys: Dict[str, List[str]] = {}       # ns -> sorted keys
        self._savepoint: int = -1                   # last committed block

    # -- reads -----------------------------------------------------------
    def get_state(self, ns: str, key: str):
        """-> (value, version) or None."""
        return self._data.get((ns, key))

    def get_version(self, ns: str, key: str) -> Optional[Version]:
        got = self._data.get((ns, key))
        return got[1] if got else None

    def get_versions_many(self, pairs) -> List[Optional[Version]]:
        """Bulk committed-version lookup for the vectorized MVCC
        hash-join: one call resolves every (ns, key) a block touches,
        so the per-key interface cost is paid once per BLOCK instead
        of once per read (reference: statedb.BulkOptimizable
        LoadCommittedVersions)."""
        data = self._data
        out = []
        for pair in pairs:
            got = data.get(pair)
            out.append(got[1] if got else None)
        return out

    def get_metadata(self, ns: str, key: str) -> Optional[Dict[str, bytes]]:
        """Key metadata (e.g. the VALIDATION_PARAMETER endorsement
        override) — reference: statedb VersionedValue.Metadata."""
        got = self._metadata.get((ns, key))
        return dict(got) if got else None

    def iter_state(self):
        """Deterministic full scan: (ns, key, value, version) sorted —
        the snapshot generator's input (reference: the stateleveldb
        full-range iterator behind snapshot export)."""
        for (ns, key) in sorted(self._data):
            value, ver = self._data[(ns, key)]
            yield ns, key, value, ver

    def iter_metadata(self):
        """Deterministic full metadata scan: (ns, key, {name: value})
        sorted — the state-fingerprint oracle's input (part of the DB
        interface so a storage change can't silently drop metadata
        from the digest)."""
        for (ns, key) in sorted(self._metadata):
            yield ns, key, dict(self._metadata[(ns, key)])

    def get_state_range(self, ns: str, start: str,
                        end: str) -> List[Tuple[str, bytes, Version]]:
        """(key, value, version) list, start <= key < end ('' end =
        unbounded), in key order.  Materialized so readers get a
        snapshot: a concurrent commit_block (which mutates _keys/_data
        under the ledger lock) cannot invalidate a half-consumed
        iterator."""
        keys = self._keys.get(ns, [])
        i = bisect.bisect_left(keys, start)
        out = []
        while i < len(keys):
            k = keys[i]
            if end and k >= end:
                break
            v, ver = self._data[(ns, k)]
            out.append((k, v, ver))
            i += 1
        return out

    @property
    def savepoint(self) -> int:
        return self._savepoint

    # -- writes ----------------------------------------------------------
    def apply_updates(self, batch: UpdateBatch, block_num: int) -> None:
        for (ns, key), (value, version) in batch.updates.items():
            keys = self._keys.setdefault(ns, [])
            exists = (ns, key) in self._data
            if value is None:
                if exists:
                    del self._data[(ns, key)]
                    self._metadata.pop((ns, key), None)
                    keys.pop(bisect.bisect_left(keys, key))
            else:
                self._data[(ns, key)] = (value, version)
                if not exists:
                    bisect.insort(keys, key)
        for (ns, key), (entries, version) in batch.meta_updates.items():
            got = self._data.get((ns, key))
            if got is None:
                continue        # metadata without a key is a no-op
            # metadata writes bump the key version (MVCC visibility)
            self._data[(ns, key)] = (got[0], version)
            if entries:
                self._metadata[(ns, key)] = dict(entries)
            else:
                self._metadata.pop((ns, key), None)
        self._savepoint = block_num

    # -- durability ------------------------------------------------------
    MAGIC = b"FMTSDB2\n"

    def snapshot(self, path: str) -> None:
        """Atomic whole-DB snapshot (write-temp + rename)."""
        buf = io.BytesIO()
        buf.write(self.MAGIC)
        buf.write(struct.pack("<q", self._savepoint))
        buf.write(struct.pack("<I", len(self._data)))
        for (ns, key), (value, (bn, tn)) in sorted(self._data.items()):
            for part in (ns.encode(), key.encode(), value):
                buf.write(struct.pack("<I", len(part)))
                buf.write(part)
            buf.write(struct.pack("<QQ", bn, tn))
        buf.write(struct.pack("<I", len(self._metadata)))
        for (ns, key), entries in sorted(self._metadata.items()):
            for part in (ns.encode(), key.encode()):
                buf.write(struct.pack("<I", len(part)))
                buf.write(part)
            buf.write(struct.pack("<I", len(entries)))
            for name, val in sorted(entries.items()):
                for part in (name.encode(), val):
                    buf.write(struct.pack("<I", len(part)))
                    buf.write(part)
        payload = buf.getvalue()
        payload += hashlib.sha256(payload).digest()
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "VersionedDB":
        db = cls()
        if not os.path.exists(path):
            return db
        raw = open(path, "rb").read()
        if len(raw) < 32 + len(cls.MAGIC):
            return db                       # torn snapshot: start empty
        body, digest = raw[:-32], raw[-32:]
        if hashlib.sha256(body).digest() != digest or \
                not body.startswith(cls.MAGIC):
            return db                       # corrupt: rebuild from blocks
        pos = len(cls.MAGIC)
        (db._savepoint,) = struct.unpack_from("<q", body, pos)
        pos += 8
        (count,) = struct.unpack_from("<I", body, pos)
        pos += 4
        for _ in range(count):
            parts = []
            for _ in range(3):
                (ln,) = struct.unpack_from("<I", body, pos)
                pos += 4
                parts.append(body[pos:pos + ln])
                pos += ln
            bn, tn = struct.unpack_from("<QQ", body, pos)
            pos += 16
            ns, key = parts[0].decode(), parts[1].decode()
            db._data[(ns, key)] = (parts[2], (bn, tn))
            db._keys.setdefault(ns, []).append(key)
        for keys in db._keys.values():     # bulk-sort, not insort^2
            keys.sort()
        (mcount,) = struct.unpack_from("<I", body, pos)
        pos += 4
        for _ in range(mcount):
            parts = []
            for _ in range(2):
                (ln,) = struct.unpack_from("<I", body, pos)
                pos += 4
                parts.append(body[pos:pos + ln])
                pos += ln
            (n_entries,) = struct.unpack_from("<I", body, pos)
            pos += 4
            entries = {}
            for _ in range(n_entries):
                pair = []
                for _ in range(2):
                    (ln,) = struct.unpack_from("<I", body, pos)
                    pos += 4
                    pair.append(body[pos:pos + ln])
                    pos += ln
                entries[pair[0].decode()] = pair[1]
            db._metadata[(parts[0].decode(), parts[1].decode())] = entries
        return db
