"""Private data: transient staging, per-block pvt store, BTL expiry.

(reference: core/transientstore/store.go — endorsement-time staging of
private write-sets keyed by txid, purged below a block height — and
core/ledger/pvtdatastorage/store.go — committed per-block private
data with block-to-live expiry — plus the hash-consistency gate of
gossip/privdata/coordinator.go:498's StoreBlock.)

The model: private values never enter blocks; blocks carry per-
collection HASHED read/write sets (kvrwset hashed variants).  The
plaintext travels out-of-band (transient store now, gossip
distribution later), and commit verifies sha256(key)/sha256(value)
against the block's hashes before applying the private writes to the
ns$$collection state namespace.
"""
from __future__ import annotations

import base64
import hashlib
import json
import os
import threading

from fabric_mod_tpu.utils.racecheck import OrderedLock
from typing import Dict, Iterator, List, Optional, Tuple

from fabric_mod_tpu.protos import messages as m


class _OpLog:
    """Tiny durable op-log + checkpoint for the pvt/transient stores
    (the durable.py log-structured pattern at JSON granularity — these
    stores hold orders of magnitude less data than the state DB, so
    debuggability wins over byte-packing; reference:
    core/ledger/pvtdatastorage/store.go and core/transientstore/
    store.go are leveldb instances).

    Records are length+crc framed JSON objects; recovery loads the
    newest intact checkpoint then replays the log, cropping a torn
    tail.  `append` keeps the file handle open; `fsync=True` records
    (per-block pvt commits) are durable at return."""

    CKPT_EVERY = 4096                     # records between checkpoints

    def __init__(self, dir_path: str, name: str):
        from fabric_mod_tpu.ledger.durable import _LogStore
        self._store = _LogStore(dir_path, name)
        self._fh = None
        self._pending = 0

    def recover(self, load_checkpoint, apply_record) -> None:
        from fabric_mod_tpu.ledger.durable import _iter_records
        gens = self._store.generations()
        gen = gens[-1] if gens else 0
        self._gen = gen
        body = self._store.read_checkpoint(gen)
        if body is not None:
            load_checkpoint(json.loads(body.decode()))
        path = self._store._path("log", gen)
        good_end = 0
        if os.path.exists(path):
            buf = open(path, "rb").read()
            for end, payload in _iter_records(buf, 0):
                apply_record(json.loads(payload.decode()))
                good_end = end
                self._pending += 1
            if good_end < len(buf):        # crop torn tail
                with open(path, "r+b") as f:
                    f.truncate(good_end)
        self._fh = open(path, "ab")

    def append(self, rec: dict, fsync: bool = False) -> None:
        from fabric_mod_tpu.ledger.durable import _frame
        self._fh.write(_frame(json.dumps(rec).encode()))
        self._fh.flush()
        if fsync:
            os.fsync(self._fh.fileno())
        self._pending += 1

    def sync(self) -> None:
        """Durability barrier: everything appended so far is on disk."""
        if self._fh is not None:
            self._fh.flush()
            os.fsync(self._fh.fileno())

    def maybe_checkpoint(self, dump_checkpoint) -> None:
        if self._pending < self.CKPT_EVERY:
            return
        self.checkpoint(dump_checkpoint)

    def checkpoint(self, dump_checkpoint) -> None:
        new_gen = self._gen + 1
        self._store.write_checkpoint(
            new_gen, json.dumps(dump_checkpoint()).encode())
        self._fh.close()
        old = self._store._path("log", self._gen)
        old_ckpt = self._store._path("ckpt", self._gen)
        self._fh = open(self._store._path("log", new_gen), "ab")
        for path in (old, old_ckpt):
            if os.path.exists(path):
                os.remove(path)
        self._gen = new_gen
        self._pending = 0

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def pvt_namespace(ns: str, collection: str) -> str:
    """The state-DB namespace private writes land in (reference:
    privacyenabledstate's ns/collection composite namespaces)."""
    return f"{ns}$$p{collection}"


def hash_key(key: str) -> bytes:
    return hashlib.sha256(key.encode()).digest()


def hash_value(value: bytes) -> bytes:
    return hashlib.sha256(value).digest()


class TransientStore:
    """Endorsement-time private write-set staging (reference:
    core/transientstore/store.go — Persist/GetTxPvtRWSetByTxid/
    PurgeBelowHeight).  Bounded: gossip-delivered plaintext is
    attacker-influenceable, so growth past `max_entries` drops new
    arrivals (commit-time reconciliation recovers them later) instead
    of growing without bound."""

    MAX_ENTRIES = 10_000

    def __init__(self, max_entries: int = MAX_ENTRIES,
                 dir_path: Optional[str] = None):
        """`dir_path` makes the store durable: pending private
        plaintext survives a peer restart (reference: the leveldb
        transientstore) — without it, endorsement-time staging is lost
        on crash and must be re-reconciled from peers."""
        self._lock = OrderedLock(20, "transientstore")
        self._max = max_entries
        self._count = 0
        # txid -> [(received_at_block, TxPvtReadWriteSet bytes)]
        self._data: Dict[str, List[Tuple[int, bytes]]] = {}
        self._log: Optional[_OpLog] = None
        if dir_path is not None:
            self._log = _OpLog(dir_path, "transient")
            self._log.recover(self._load_ckpt, self._apply)

    # -- durability plumbing ----------------------------------------------
    def _load_ckpt(self, ck: dict) -> None:
        self._data = {t: [(h, _unb64(r)) for h, r in entries]
                      for t, entries in ck["data"].items()}
        self._count = sum(len(v) for v in self._data.values())

    def _dump_ckpt(self) -> dict:
        return {"data": {t: [[h, _b64(r)] for h, r in entries]
                         for t, entries in self._data.items()}}

    def _apply(self, rec: dict) -> None:
        op = rec["op"]
        if op == "persist":
            self._persist_mem(rec["txid"], rec["h"], _unb64(rec["raw"]))
        elif op == "purge_txids":
            self._purge_txids_mem(rec["txids"])
        elif op == "purge_below":
            self._purge_below_mem(rec["h"])

    def _record(self, rec: dict) -> None:
        if self._log is not None:
            self._log.append(rec)
            self._log.maybe_checkpoint(self._dump_ckpt)

    # -- operations --------------------------------------------------------
    def _persist_mem(self, txid: str, received_at_block: int,
                     raw: bytes) -> bool:
        entries = self._data.setdefault(txid, [])
        if any(r == raw for _, r in entries):
            return False                  # N endorsers, one copy
        if self._count >= self._max:
            if not entries:
                del self._data[txid]
            return False                  # flood guard: drop new
        entries.append((received_at_block, raw))
        self._count += 1
        return True

    def persist(self, txid: str, received_at_block: int,
                pvt_rwset: m.TxPvtReadWriteSet) -> None:
        raw = pvt_rwset.encode()
        with self._lock:
            if self._persist_mem(txid, received_at_block, raw):
                self._record({"op": "persist", "txid": txid,
                              "h": received_at_block, "raw": _b64(raw)})

    def get_by_txid(self, txid: str) -> List[m.TxPvtReadWriteSet]:
        with self._lock:
            return [m.TxPvtReadWriteSet.decode(raw)
                    for _, raw in self._data.get(txid, [])]

    def _purge_txids_mem(self, txids) -> None:
        for t in txids:
            gone = self._data.pop(t, None)
            if gone:
                self._count -= len(gone)

    def purge_by_txids(self, txids) -> None:
        with self._lock:
            self._purge_txids_mem(txids)
            self._record({"op": "purge_txids", "txids": list(txids)})

    def _purge_below_mem(self, height: int) -> None:
        for txid in list(self._data):
            kept = [(h, raw) for h, raw in self._data[txid]
                    if h >= height]
            self._count -= len(self._data[txid]) - len(kept)
            if kept:
                self._data[txid] = kept
            else:
                del self._data[txid]

    def purge_below_height(self, height: int) -> None:
        """(reference: PurgeBelowHeight — endorsement leftovers)"""
        with self._lock:
            self._purge_below_mem(height)
            self._record({"op": "purge_below", "h": height})

    def close(self) -> None:
        if self._log is not None:
            self._log.close()


class PvtDataStore:
    """Committed private data per (block, tx, ns, collection) with
    BTL-based expiry (reference: pvtdatastorage/store.go +
    pvtstatepurgemgmt).  In-memory index; the authoritative private
    STATE lives in the (durable) state DB's pvt namespaces — this
    store serves history/retrieval and drives purges."""

    def __init__(self, dir_path: Optional[str] = None):
        """`dir_path` makes the store durable: committed private
        plaintext AND the pending-reconciliation (missing-digest) index
        survive a peer restart (reference: the leveldb-backed
        pvtdatastorage/store.go); without it the plaintext must be
        re-reconciled from peers after a crash."""
        self._lock = OrderedLock(30, "pvtdatastore")
        # (block, tx) -> [(ns, collection, KVRWSet bytes)]
        self._by_block: Dict[Tuple[int, int],
                             List[Tuple[str, str, bytes]]] = {}
        # expiry_block -> [(block, tx, ns, collection, [keys])]
        self._expiries: Dict[int, List] = {}
        # hashed writes committed WITHOUT plaintext — the reconciler's
        # work list (reference: pvtdatastorage's missing-data index +
        # reconcile.go:339)
        self._missing: set = set()   # (block, tx, ns, collection)
        self._log: Optional[_OpLog] = None
        if dir_path is not None:
            self._log = _OpLog(dir_path, "pvtdata")
            self._log.recover(self._load_ckpt, self._apply)

    # -- durability plumbing ----------------------------------------------
    def _load_ckpt(self, ck: dict) -> None:
        self._by_block = {
            (bn, tn): [(n, c, _unb64(r)) for n, c, r in entries]
            for (bn, tn), entries in
            ((tuple(json.loads(k)), v)
             for k, v in ck["by_block"].items())}
        self._expiries = {int(k): [tuple(e[:4]) + (e[4],) for e in v]
                          for k, v in ck["expiries"].items()}
        self._missing = {tuple(d) for d in ck["missing"]}

    def _dump_ckpt(self) -> dict:
        return {
            "by_block": {json.dumps(list(bt)): [[n, c, _b64(r)]
                                                for n, c, r in entries]
                         for bt, entries in self._by_block.items()},
            "expiries": {str(k): [list(e[:4]) + [list(e[4])] for e in v]
                         for k, v in self._expiries.items()},
            "missing": [list(d) for d in sorted(self._missing)],
        }

    def _apply(self, rec: dict) -> None:
        op = rec["op"]
        if op == "commit":
            self._commit_mem(rec["bn"], rec["tn"], rec["ns"], rec["c"],
                             _unb64(rec["kv"]), rec["btl"])
        elif op == "missing":
            self._missing.add((rec["bn"], rec["tn"], rec["ns"],
                               rec["c"]))
        elif op == "drop_missing":
            self._missing.discard((rec["bn"], rec["tn"], rec["ns"],
                                   rec["c"]))
        elif op == "purge":
            self._purge_mem(rec["bn"])

    def _record(self, rec: dict, fsync: bool = False) -> None:
        if self._log is not None:
            self._log.append(rec, fsync=fsync)
            self._log.maybe_checkpoint(self._dump_ckpt)

    def _commit_mem(self, block_num: int, tx_num: int, ns: str,
                    collection: str, raw: bytes, btl: int) -> None:
        self._by_block.setdefault((block_num, tx_num), []).append(
            (ns, collection, raw))
        self._missing.discard((block_num, tx_num, ns, collection))
        if btl > 0:
            keys = [w.key for w in m.KVRWSet.decode(raw).writes]
            self._expiries.setdefault(block_num + btl + 1, []).append(
                (block_num, tx_num, ns, collection, keys))

    def commit(self, block_num: int, tx_num: int, ns: str,
               collection: str, kv: m.KVRWSet, btl: int) -> None:
        raw = kv.encode()
        with self._lock:
            self._commit_mem(block_num, tx_num, ns, collection, raw, btl)
            # no per-record fsync: the ledger calls sync() ONCE per
            # block after all collections are processed (committed
            # plaintext must survive restarts — it may no longer be
            # reconcilable if peers purged by BTL — but one barrier
            # per block is enough)
            self._record({"op": "commit", "bn": block_num,
                          "tn": tx_num, "ns": ns, "c": collection,
                          "kv": _b64(raw), "btl": btl})

    # -- missing-data index (reconciler work list) ------------------------
    def report_missing(self, block_num: int, tx_num: int, ns: str,
                       collection: str) -> None:
        with self._lock:
            self._missing.add((block_num, tx_num, ns, collection))
            self._record({"op": "missing", "bn": block_num,
                          "tn": tx_num, "ns": ns, "c": collection})

    def missing(self, limit: int = 50) -> List[Tuple[int, int, str, str]]:
        """Oldest-first batch of unreconciled digests."""
        with self._lock:
            return sorted(self._missing)[:limit]

    def missing_count(self) -> int:
        """Total reconciliation backlog (the observability answer to
        'is a long outage draining at 50 digests/tick?' — exported as
        a gauge by the gossip reconciler)."""
        with self._lock:
            return len(self._missing)

    def drop_missing(self, block_num: int, tx_num: int, ns: str,
                     collection: str) -> None:
        """Give up on a digest (e.g. its BTL lapsed before any peer
        supplied the data)."""
        with self._lock:
            self._missing.discard((block_num, tx_num, ns, collection))
            self._record({"op": "drop_missing", "bn": block_num,
                          "tn": tx_num, "ns": ns, "c": collection})

    def is_missing(self, block_num: int, tx_num: int, ns: str,
                   collection: str) -> bool:
        with self._lock:
            return (block_num, tx_num, ns, collection) in self._missing

    def get(self, block_num: int, tx_num: int
            ) -> List[Tuple[str, str, m.KVRWSet]]:
        with self._lock:
            return [(ns, coll, m.KVRWSet.decode(raw))
                    for ns, coll, raw in
                    self._by_block.get((block_num, tx_num), [])]

    def later_written_keys(self, block_num: int, tx_num: int, ns: str,
                           collection: str) -> set:
        """Keys touched by committed private write-sets NEWER than
        (block_num, tx_num) in this collection — deletes leave no
        version in the state DB, so the reconciler must consult this
        before backfilling old writes (else it would resurrect deleted
        keys).  One scan serves every key of a backfilled set."""
        keys: set = set()
        with self._lock:
            for (bn, tn), entries in self._by_block.items():
                if (bn, tn) <= (block_num, tx_num):
                    continue
                for n, c, raw in entries:
                    if n == ns and c == collection:
                        kv = m.KVRWSet.decode(raw)
                        keys.update(w.key for w in kv.writes)
        return keys

    def expiring_at(self, block_num: int) -> List:
        """[(block, tx, ns, collection, keys)] whose BTL lapses when
        `block_num` commits (the purge manager's work list)."""
        with self._lock:
            return list(self._expiries.get(block_num, []))

    def _purge_mem(self, block_num: int) -> None:
        for bn, tn, ns, coll, _keys in \
                self._expiries.pop(block_num, []):
            entries = self._by_block.get((bn, tn))
            if not entries:
                continue
            kept = [(n, c, raw) for n, c, raw in entries
                    if not (n == ns and c == coll)]
            if kept:
                self._by_block[(bn, tn)] = kept
            else:
                del self._by_block[(bn, tn)]

    def purge(self, block_num: int) -> None:
        with self._lock:
            had = block_num in self._expiries
            self._purge_mem(block_num)
            if had:
                self._record({"op": "purge", "bn": block_num})

    def sync(self) -> None:
        """Per-block durability barrier (called by the ledger after a
        block's private data is fully processed)."""
        if self._log is not None:
            self._log.sync()

    def close(self) -> None:
        if self._log is not None:
            self._log.close()


class PvtDataMismatchError(Exception):
    pass


def verify_pvt_against_hashes(hashed: m.HashedRWSet,
                              pvt_kv: m.KVRWSet) -> None:
    """The commit gate: plaintext private writes must match the
    block's hashed write-set exactly (reference: the coordinator's
    hash checks before StorePvtData)."""
    want = {(w.key_hash, w.value_hash, w.is_delete)
            for w in hashed.hashed_writes}
    got = {(hash_key(w.key),
            b"" if w.is_delete else hash_value(w.value),
            w.is_delete)
           for w in pvt_kv.writes}
    if want != got:
        raise PvtDataMismatchError(
            "private write-set does not match block hashes")
