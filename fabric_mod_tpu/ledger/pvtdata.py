"""Private data: transient staging, per-block pvt store, BTL expiry.

(reference: core/transientstore/store.go — endorsement-time staging of
private write-sets keyed by txid, purged below a block height — and
core/ledger/pvtdatastorage/store.go — committed per-block private
data with block-to-live expiry — plus the hash-consistency gate of
gossip/privdata/coordinator.go:498's StoreBlock.)

The model: private values never enter blocks; blocks carry per-
collection HASHED read/write sets (kvrwset hashed variants).  The
plaintext travels out-of-band (transient store now, gossip
distribution later), and commit verifies sha256(key)/sha256(value)
against the block's hashes before applying the private writes to the
ns$$collection state namespace.
"""
from __future__ import annotations

import hashlib
import threading
from typing import Dict, Iterator, List, Optional, Tuple

from fabric_mod_tpu.protos import messages as m


def pvt_namespace(ns: str, collection: str) -> str:
    """The state-DB namespace private writes land in (reference:
    privacyenabledstate's ns/collection composite namespaces)."""
    return f"{ns}$$p{collection}"


def hash_key(key: str) -> bytes:
    return hashlib.sha256(key.encode()).digest()


def hash_value(value: bytes) -> bytes:
    return hashlib.sha256(value).digest()


class TransientStore:
    """Endorsement-time private write-set staging (reference:
    core/transientstore/store.go — Persist/GetTxPvtRWSetByTxid/
    PurgeBelowHeight).  Bounded: gossip-delivered plaintext is
    attacker-influenceable, so growth past `max_entries` drops new
    arrivals (commit-time reconciliation recovers them later) instead
    of growing without bound."""

    MAX_ENTRIES = 10_000

    def __init__(self, max_entries: int = MAX_ENTRIES):
        self._lock = threading.Lock()
        self._max = max_entries
        self._count = 0
        # txid -> [(received_at_block, TxPvtReadWriteSet bytes)]
        self._data: Dict[str, List[Tuple[int, bytes]]] = {}

    def persist(self, txid: str, received_at_block: int,
                pvt_rwset: m.TxPvtReadWriteSet) -> None:
        raw = pvt_rwset.encode()
        with self._lock:
            entries = self._data.setdefault(txid, [])
            if any(r == raw for _, r in entries):
                return                    # N endorsers, one copy
            if self._count >= self._max:
                if not entries:
                    del self._data[txid]
                return                    # flood guard: drop new
            entries.append((received_at_block, raw))
            self._count += 1

    def get_by_txid(self, txid: str) -> List[m.TxPvtReadWriteSet]:
        with self._lock:
            return [m.TxPvtReadWriteSet.decode(raw)
                    for _, raw in self._data.get(txid, [])]

    def purge_by_txids(self, txids) -> None:
        with self._lock:
            for t in txids:
                gone = self._data.pop(t, None)
                if gone:
                    self._count -= len(gone)

    def purge_below_height(self, height: int) -> None:
        """(reference: PurgeBelowHeight — endorsement leftovers)"""
        with self._lock:
            for txid in list(self._data):
                kept = [(h, raw) for h, raw in self._data[txid]
                        if h >= height]
                self._count -= len(self._data[txid]) - len(kept)
                if kept:
                    self._data[txid] = kept
                else:
                    del self._data[txid]


class PvtDataStore:
    """Committed private data per (block, tx, ns, collection) with
    BTL-based expiry (reference: pvtdatastorage/store.go +
    pvtstatepurgemgmt).  In-memory index; the authoritative private
    STATE lives in the (durable) state DB's pvt namespaces — this
    store serves history/retrieval and drives purges."""

    def __init__(self):
        self._lock = threading.Lock()
        # (block, tx) -> [(ns, collection, KVRWSet bytes)]
        self._by_block: Dict[Tuple[int, int],
                             List[Tuple[str, str, bytes]]] = {}
        # expiry_block -> [(block, tx, ns, collection, [keys])]
        self._expiries: Dict[int, List] = {}
        # hashed writes committed WITHOUT plaintext — the reconciler's
        # work list (reference: pvtdatastorage's missing-data index +
        # reconcile.go:339)
        self._missing: set = set()   # (block, tx, ns, collection)

    def commit(self, block_num: int, tx_num: int, ns: str,
               collection: str, kv: m.KVRWSet, btl: int) -> None:
        with self._lock:
            self._by_block.setdefault((block_num, tx_num), []).append(
                (ns, collection, kv.encode()))
            self._missing.discard((block_num, tx_num, ns, collection))
            if btl > 0:
                keys = [w.key for w in kv.writes]
                self._expiries.setdefault(block_num + btl + 1, []).append(
                    (block_num, tx_num, ns, collection, keys))

    # -- missing-data index (reconciler work list) ------------------------
    def report_missing(self, block_num: int, tx_num: int, ns: str,
                       collection: str) -> None:
        with self._lock:
            self._missing.add((block_num, tx_num, ns, collection))

    def missing(self, limit: int = 50) -> List[Tuple[int, int, str, str]]:
        """Oldest-first batch of unreconciled digests."""
        with self._lock:
            return sorted(self._missing)[:limit]

    def drop_missing(self, block_num: int, tx_num: int, ns: str,
                     collection: str) -> None:
        """Give up on a digest (e.g. its BTL lapsed before any peer
        supplied the data)."""
        with self._lock:
            self._missing.discard((block_num, tx_num, ns, collection))

    def is_missing(self, block_num: int, tx_num: int, ns: str,
                   collection: str) -> bool:
        with self._lock:
            return (block_num, tx_num, ns, collection) in self._missing

    def get(self, block_num: int, tx_num: int
            ) -> List[Tuple[str, str, m.KVRWSet]]:
        with self._lock:
            return [(ns, coll, m.KVRWSet.decode(raw))
                    for ns, coll, raw in
                    self._by_block.get((block_num, tx_num), [])]

    def later_written_keys(self, block_num: int, tx_num: int, ns: str,
                           collection: str) -> set:
        """Keys touched by committed private write-sets NEWER than
        (block_num, tx_num) in this collection — deletes leave no
        version in the state DB, so the reconciler must consult this
        before backfilling old writes (else it would resurrect deleted
        keys).  One scan serves every key of a backfilled set."""
        keys: set = set()
        with self._lock:
            for (bn, tn), entries in self._by_block.items():
                if (bn, tn) <= (block_num, tx_num):
                    continue
                for n, c, raw in entries:
                    if n == ns and c == collection:
                        kv = m.KVRWSet.decode(raw)
                        keys.update(w.key for w in kv.writes)
        return keys

    def expiring_at(self, block_num: int) -> List:
        """[(block, tx, ns, collection, keys)] whose BTL lapses when
        `block_num` commits (the purge manager's work list)."""
        with self._lock:
            return list(self._expiries.get(block_num, []))

    def purge(self, block_num: int) -> None:
        with self._lock:
            for bn, tn, ns, coll, _keys in \
                    self._expiries.pop(block_num, []):
                entries = self._by_block.get((bn, tn))
                if not entries:
                    continue
                kept = [(n, c, raw) for n, c, raw in entries
                        if not (n == ns and c == coll)]
                if kept:
                    self._by_block[(bn, tn)] = kept
                else:
                    del self._by_block[(bn, tn)]


class PvtDataMismatchError(Exception):
    pass


def verify_pvt_against_hashes(hashed: m.HashedRWSet,
                              pvt_kv: m.KVRWSet) -> None:
    """The commit gate: plaintext private writes must match the
    block's hashed write-set exactly (reference: the coordinator's
    hash checks before StorePvtData)."""
    want = {(w.key_hash, w.value_hash, w.is_delete)
            for w in hashed.hashed_writes}
    got = {(hash_key(w.key),
            b"" if w.is_delete else hash_value(w.value),
            w.is_delete)
           for w in pvt_kv.writes}
    if want != got:
        raise PvtDataMismatchError(
            "private write-set does not match block hashes")
