"""The kv ledger: block store + versioned state + history, with
simulation, MVCC commit, and crash recovery.

(reference: core/ledger/kvledger/kv_ledger.go — `CommitLegacy` at
:457-541, recovery at :228-341 `recoverDBs`; the lock-based tx manager
in txmgmt/txmgr/lockbased_txmgr.go; query executors in
query_executor.go; history in kvledger/history/db.go.)

Commit pipeline stage order matches the reference: MVCC validate ->
append block (+flags in metadata) -> apply state batch -> history ->
snapshot/savepoint.  State and history are derivable from the block
store, so on open any gap between the state savepoint and the block
height is replayed — the ledger *is* the checkpoint (SURVEY.md §5.4).
"""
from __future__ import annotations

import hashlib
import os
import threading

from fabric_mod_tpu.utils.racecheck import OrderedLock
from typing import Dict, Iterator, List, Optional, Tuple

from fabric_mod_tpu import faults
from fabric_mod_tpu.ledger.blkstorage import BlockStore
from fabric_mod_tpu.ledger.mvcc import (
    COLUMNAR, validate_and_prepare_batch,
    validate_and_prepare_batch_vectorized, vector_mvcc_enabled)
from fabric_mod_tpu.ledger.rwsetutil import RWSetBuilder, parse_tx_rwset
from fabric_mod_tpu.ledger.statedb import UpdateBatch, VersionedDB
from fabric_mod_tpu.observability import tracing
from fabric_mod_tpu.observability.metrics import (
    MetricOpts, default_provider)
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil

Version = Tuple[int, int]

# Per-block commit timing split (reference: kv_ledger.go:525-539's
# state_validation / block_and_pvtdata_commit / state_commit log line
# + metrics.go histograms)
_mp = default_provider()
H_STATE_VALIDATION = _mp.new_histogram(MetricOpts(
    "ledger", "", "block_processing_state_validation_seconds",
    "MVCC validation time per block"))
H_BLOCK_COMMIT = _mp.new_histogram(MetricOpts(
    "ledger", "", "block_commit_seconds",
    "Block store append time per block"))
H_STATE_COMMIT = _mp.new_histogram(MetricOpts(
    "ledger", "", "state_commit_seconds",
    "State+history apply time per block"))
G_HEIGHT = _mp.new_gauge(MetricOpts(
    "ledger", "", "blockchain_height", "Committed chain height",
    ("channel",)))


class LedgerError(Exception):
    pass


class QueryExecutor:
    """Read-only state access (reference: query_executor.go)."""

    def __init__(self, db: VersionedDB):
        self._db = db

    def get_state(self, ns: str, key: str) -> Optional[bytes]:
        got = self._db.get_state(ns, key)
        return got[0] if got else None

    def get_state_range(self, ns: str, start: str, end: str):
        for key, value, _ in self._db.get_state_range(ns, start, end):
            yield key, value

    def get_private_data(self, ns: str, collection: str,
                         key: str) -> Optional[bytes]:
        from fabric_mod_tpu.ledger.pvtdata import pvt_namespace
        got = self._db.get_state(pvt_namespace(ns, collection), key)
        return got[0] if got else None

    def _execute_query_versioned(self, ns: str, query):
        """Shared rich-query core: ([(key, doc, version)], bookmark).
        A bookmark bounds the scan start (execute skips the boundary
        key itself), so each page costs what remains, not the whole
        namespace."""
        from fabric_mod_tpu.ledger import richquery
        q = richquery.RichQuery.parse(query)
        start = q.bookmark if (q.bookmark and not q.sort) else ""
        rows = self._db.get_state_range(ns, start, "")
        return richquery.execute(rows, q)

    def execute_query(self, ns: str, query):
        """Rich JSON-selector query over a namespace (reference:
        statecouchdb.go:1230 ExecuteQuery).  Returns
        ([(key, doc)], bookmark)."""
        matches, bookmark = self._execute_query_versioned(ns, query)
        return [(k, doc) for k, doc, _ver in matches], bookmark


class TxSimulator(QueryExecutor):
    """Records reads/writes into an RWSetBuilder
    (reference: lockbased_txmgr.go NewTxSimulator + rwset_builder)."""

    def __init__(self, db: VersionedDB, txid: str):
        super().__init__(db)
        self.txid = txid
        self._rw = RWSetBuilder()
        self._writes: Dict[Tuple[str, str], Optional[bytes]] = {}

    def get_state(self, ns: str, key: str) -> Optional[bytes]:
        if (ns, key) in self._writes:       # read-your-writes
            return self._writes[(ns, key)]
        got = self._db.get_state(ns, key)
        self._rw.add_read(ns, key, got[1] if got else None)
        return got[0] if got else None

    def get_state_range(self, ns: str, start: str, end: str):
        """Range over committed state merged with this simulation's own
        writes (read-your-writes, consistent with get_state).  The
        phantom fingerprint records committed results only: at
        validation time the re-executed range sees earlier txs' writes
        but never this tx's own."""
        results = []
        merged = {}
        for key, value, ver in self._db.get_state_range(ns, start, end):
            results.append((key, ver))
            merged[key] = value
        self._rw.add_range_query(ns, start, end, True, results)
        for (wns, key), value in self._writes.items():
            if wns != ns or not (start <= key and (not end or key < end)):
                continue
            if value is None:
                merged.pop(key, None)
            else:
                merged[key] = value
        return iter(sorted(merged.items()))

    def execute_query(self, ns: str, query):
        """Rich query during simulation: each returned key joins the
        read set, but — exactly like the reference — the query itself
        is NOT re-executed at validation (no phantom protection for
        rich queries; statecouchdb documents the same limitation)."""
        matches, bookmark = self._execute_query_versioned(ns, query)
        out = []
        for key, doc, ver in matches:
            self._rw.add_read(ns, key, ver)
            out.append((key, doc))
        return out, bookmark

    def set_state(self, ns: str, key: str, value: bytes) -> None:
        self._writes[(ns, key)] = value
        self._rw.add_write(ns, key, value)

    def delete_state(self, ns: str, key: str) -> None:
        self._writes[(ns, key)] = None
        self._rw.add_write(ns, key, None)

    def set_state_metadata(self, ns: str, key: str, name: str,
                           value: bytes) -> None:
        """Key metadata write — e.g. the VALIDATION_PARAMETER
        endorsement override key-level validation reads (reference:
        the shim's PutStateMetadata -> rwset metadata writes)."""
        self._rw.add_metadata_write(ns, key, name, value)

    # -- private data (reference: the shim's PutPrivateData path) -----
    def set_private_data(self, ns: str, collection: str, key: str,
                         value: bytes) -> None:
        from fabric_mod_tpu.ledger.pvtdata import pvt_namespace
        self._writes[(pvt_namespace(ns, collection), key)] = value
        self._rw.add_pvt_write(ns, collection, key, value)

    def delete_private_data(self, ns: str, collection: str,
                            key: str) -> None:
        from fabric_mod_tpu.ledger.pvtdata import pvt_namespace
        self._writes[(pvt_namespace(ns, collection), key)] = None
        self._rw.add_pvt_write(ns, collection, key, None)

    def get_private_data(self, ns: str, collection: str,
                         key: str) -> Optional[bytes]:
        from fabric_mod_tpu.ledger.pvtdata import pvt_namespace
        pns = pvt_namespace(ns, collection)
        if (pns, key) in self._writes:      # read-your-writes
            return self._writes[(pns, key)]
        got = self._db.get_state(pns, key)
        # private reads are NOT recorded in the public read set (the
        # reference keys hashed reads; omitted — write-only MVCC here)
        return got[0] if got else None

    def done(self) -> m.TxReadWriteSet:
        return self._rw.build()

    def done_pvt(self) -> Optional[m.TxPvtReadWriteSet]:
        """The plaintext private write-sets for transient staging."""
        return self._rw.build_pvt()


class HistoryDB:
    """(ns, key) -> [(block, tx), ...] — rebuildable from blocks
    (reference: kvledger/history/db.go)."""

    def __init__(self):
        self._hist: Dict[Tuple[str, str], List[Version]] = {}

    def commit(self, block_num: int,
               tx_writes: List[Tuple[int, str, str]]) -> None:
        for tx_num, ns, key in tx_writes:
            self._hist.setdefault((ns, key), []).append((block_num, tx_num))

    def get_history_for_key(self, ns: str, key: str) -> List[Version]:
        return list(self._hist.get((ns, key), []))


def tx_rwset_from_envelope(env: m.Envelope) -> Optional[m.TxReadWriteSet]:
    """Envelope -> TxReadWriteSet of its (first) endorser action, or
    None when absent/malformed (reference: rwsetutil on the
    ChaincodeAction.results path)."""
    try:
        payload = protoutil.unmarshal_envelope_payload(env)
        tx = protoutil.extract_endorser_tx(payload)
        cca, _prp, _ends = protoutil.tx_rwset_and_endorsements(tx.actions[0])
        return m.TxReadWriteSet.decode(cca.results)
    except Exception:
        return None


class KvLedger:
    """One channel's ledger (reference: kv_ledger.go kvLedger)."""

    SNAPSHOT_EVERY = 64
    TRANSIENT_RETENTION_BLOCKS = 100

    def __init__(self, ledger_dir: str, ledger_id: str = "ch",
                 durable: bool = True):
        self.ledger_id = ledger_id
        self.dir = ledger_dir
        self._durable = durable
        os.makedirs(ledger_dir, exist_ok=True)
        # rank 10 in the lock hierarchy (utils/racecheck.py): the
        # commit path nests transient (20) / pvt (30) store locks
        # inside this one; an inversion anywhere raises instead of
        # deadlocking (the -race analog, SURVEY Â§5.2)
        self._lock = OrderedLock(10, "kvledger")
        # commit notification for event deliver streams (reference:
        # the ledger's CommitNotifier consumed by deliverevents.go)
        self.height_changed = threading.Condition()
        self.blockstore = BlockStore(os.path.join(ledger_dir, "chains"))
        self._state_path = os.path.join(ledger_dir, "state.snap")
        if durable:
            # log-structured disk stores: O(delta) recovery, values on
            # disk (reference contract: stateleveldb.go:379 + history/db.go)
            from fabric_mod_tpu.ledger.durable import (
                DurableHistoryDB, DurableStateDB)
            self.state = DurableStateDB(os.path.join(ledger_dir, "state"))
            self.history = DurableHistoryDB(
                os.path.join(ledger_dir, "history"))
        else:
            self.state = VersionedDB.load(self._state_path)
            self.history = HistoryDB()
        # private data machinery (attach_pvt wires the live stores;
        # absent, hashed collections commit without plaintext — the
        # reference's "missing pvt data, reconcile later" stance)
        self._transient = None
        self._pvtstore = None
        self._btl_fn = None
        # cached state-fingerprint accumulator (XOR of per-entry
        # hashes): None until the first fingerprint seeds it with a
        # full scan, then maintained incrementally by every state
        # mutation through _apply_state_updates
        self._fp_acc: Optional[int] = None
        # lifecycle deploy events + historical collection configs
        # (reference: cceventmgmt + confighistory) — file-backed, fed
        # by both commit and recovery replay below
        from fabric_mod_tpu.ledger.confighistory import (
            ConfigHistoryManager)
        self.confighistory = ConfigHistoryManager(
            os.path.join(ledger_dir, "confighistory.jsonl"))
        self._recover()

    def attach_pvt(self, transient_store, pvtdata_store,
                   btl_fn=None) -> None:
        """Wire the transient + pvt stores (reference: the coordinator
        binding of gossip/privdata/coordinator.go:498)."""
        self._transient = transient_store
        self._pvtstore = pvtdata_store
        self._btl_fn = btl_fn or (lambda ns, coll: 0)

    def _reset_state_db(self):
        """State ran ahead of a cropped block store: rebuild from
        genesis (reference: kv_ledger.go recovery edge)."""
        if self._durable:
            import shutil
            from fabric_mod_tpu.ledger.durable import DurableStateDB
            self.state.close()
            shutil.rmtree(os.path.join(self.dir, "state"))
            self.state = DurableStateDB(os.path.join(self.dir, "state"))
        else:
            self.state = VersionedDB()
        self._fp_acc = None

    # -- recovery --------------------------------------------------------
    def _recover(self) -> None:
        """Replay blocks past the savepoints (reference:
        kv_ledger.go:239 syncStateAndHistoryDBWithBlockstore).  With
        durable stores both state and history resume from their own
        savepoints — O(delta), not O(chain) (VERDICT r2 weak #6)."""
        height = self.blockstore.height
        if self.state.savepoint >= height:
            self._reset_state_db()
        hist_sp = getattr(self.history, "savepoint", -1)
        if hist_sp >= height and self._durable:
            import shutil
            from fabric_mod_tpu.ledger.durable import DurableHistoryDB
            self.history.close()
            shutil.rmtree(os.path.join(self.dir, "history"))
            self.history = DurableHistoryDB(
                os.path.join(self.dir, "history"))
            hist_sp = -1
        # confighistory writes AFTER state commit, so its savepoint can
        # trail state's by one block after a crash: include it in the
        # replay floor (commit/replay are idempotent per store)
        start = min(self.state.savepoint, hist_sp,
                    self.confighistory.savepoint) + 1
        for block in self.blockstore.iter_blocks(max(0, start)):
            num = block.header.number
            replay_state = num > self.state.savepoint
            self._apply_block_effects(block, replay_state=replay_state)

    def _apply_block_effects(self, block: m.Block,
                             replay_state: bool) -> None:
        """Re-derive state/history updates of a committed block from
        its stored txflags (no re-validation on replay)."""
        flags = protoutil.block_txflags(block)
        num = block.header.number
        batch = UpdateBatch()
        hist: List[Tuple[int, str, str]] = []
        for tx_num, env in enumerate(protoutil.get_envelopes(block)):
            if flags[tx_num] != m.TxValidationCode.VALID:
                continue
            rwset = tx_rwset_from_envelope(env)
            if rwset is None:
                continue
            for ns, kv in parse_tx_rwset(rwset):
                for w in kv.writes:
                    if w.is_delete:
                        batch.delete(ns, w.key, (num, tx_num))
                    else:
                        batch.put(ns, w.key, w.value, (num, tx_num))
                    hist.append((tx_num, ns, w.key))
                for mw in kv.metadata_writes:
                    batch.put_metadata(
                        ns, mw.key,
                        {e.name: e.value for e in mw.entries},
                        (num, tx_num))
        if replay_state:
            self._apply_state_updates(batch, num)
        self.history.commit(num, hist)
        self.confighistory.handle_block_writes(
            num, [(ns, key, value)
                  for (ns, key), (value, _v) in batch.updates.items()])

    # -- simulation ------------------------------------------------------
    def new_tx_simulator(self, txid: str) -> TxSimulator:
        return TxSimulator(self.state, txid)

    def new_query_executor(self) -> QueryExecutor:
        return QueryExecutor(self.state)

    # -- commit ----------------------------------------------------------
    def commit_block(self, block: m.Block,
                     incoming_flags: Optional[List[int]] = None,
                     rwsets=None) -> List[int]:
        """MVCC-validate + commit a block whose signature/policy
        verdicts are `incoming_flags` (defaults to the flags already in
        the block metadata, e.g. from the validator).  Returns final
        flags.  `rwsets` (batchdecode.BlockRWSets | None) is the
        validator's stage-time columnar body decode riding the
        staged→commit handoff: header facts (txid/type) are reused
        instead of re-decoded, and with FABRIC_MOD_TPU_VECTOR_MVCC
        armed the accepted rows take the vectorized MVCC over the
        columnar planes (bit-identical flags, one bulk statedb call).
        (reference: kv_ledger.go:457 CommitLegacy)"""
        with self._lock:
            num = block.header.number
            if num != self.blockstore.height:
                raise LedgerError(
                    f"commit out of order: {num} at height "
                    f"{self.blockstore.height}")
            envs = protoutil.get_envelopes(block)
            if incoming_flags is None:
                # fail closed: absent metadata flags decode to
                # NOT_VALIDATED, never to VALID
                incoming_flags = list(protoutil.block_txflags(block))
            elif len(incoming_flags) != len(envs):
                raise LedgerError(
                    f"flags length {len(incoming_flags)} != "
                    f"{len(envs)} txs")
            # "mvcc" covers the commit-side host unpack (rwset
            # extraction) + the version compares — together the
            # conflict-detection cost the vectorized-MVCC roadmap
            # item targets
            vec = rwsets is not None and vector_mvcc_enabled()
            with tracing.span("mvcc", block=num):
                txs = []
                any_col = False
                for tx_num, (env, flag) in enumerate(
                        zip(envs, incoming_flags)):
                    if rwsets is not None and \
                            rwsets.txids[tx_num] is not None:
                        # stage-time spine facts, value-identical to
                        # the generic header decode below
                        txid = rwsets.txids[tx_num]
                        ch_type = rwsets.types[tx_num]
                    else:
                        try:
                            ch = protoutil.envelope_channel_header(env)
                            txid, ch_type = ch.tx_id, ch.type
                        except Exception:
                            txs.append(
                                ("", None,
                                 m.TxValidationCode.BAD_PAYLOAD))
                            continue
                    if ch_type != m.HeaderType.ENDORSER_TRANSACTION:
                        # config/control txs carry no rwset; they
                        # commit with no state effects (their effect is
                        # the bundle swap done by the channel machinery
                        # upstream)
                        txs.append((txid, m.TxReadWriteSet(), flag))
                    elif vec and rwsets.bodies[tx_num] is not None and \
                            (self._transient is None
                             or not rwsets.bodies[tx_num].has_pvt):
                        # pvt-bearing txs keep the materialized rwset
                        # when a transient store is wired — _commit_pvt
                        # walks its collection hashes
                        txs.append((txid, COLUMNAR, flag))
                        any_col = True
                    else:
                        txs.append(
                            (txid, tx_rwset_from_envelope(env), flag))
                with H_STATE_VALIDATION.time():
                    if any_col:
                        flags, batch, tx_writes = \
                            validate_and_prepare_batch_vectorized(
                                txs, self.state, num, rwsets)
                    else:
                        flags, batch, tx_writes = \
                            validate_and_prepare_batch(
                                txs, self.state, num)
            protoutil.set_block_txflags(block, bytes(flags))
            with tracing.span("ledger_write", block=num):
                with H_BLOCK_COMMIT.time():
                    self.blockstore.add_block(block)
                # the crash seam of the recovery contract: an armed
                # error-mode rule kills the commit AFTER the block is
                # durable in the block store but BEFORE any statedb /
                # history / pvt effect — exactly the statedb-behind-
                # blockstore window _recover() must replay on reopen
                faults.point("peer.ledger.crash")
                with H_STATE_COMMIT.time():
                    self._apply_state_updates(batch, num)
                    # per-tx writes (not the deduped batch) so commit
                    # and recovery replay record identical history
                    self.history.commit(num, tx_writes)
                    self._commit_pvt(num, txs, flags)
                    self.confighistory.handle_block_writes(
                        num, [(ns, key, value)
                              for (ns, key), (value, _v)
                              in batch.updates.items()])
            G_HEIGHT.with_labels(self.ledger_id).set(
                self.blockstore.height)
            if not self._durable and (num + 1) % self.SNAPSHOT_EVERY == 0:
                self.state.snapshot(self._state_path)
        with self.height_changed:
            self.height_changed.notify_all()
        return flags

    def _commit_pvt(self, num: int, txs, flags) -> None:
        """Apply plaintext private writes for VALID txs whose hashes
        the block carries, pulled from the transient store and
        hash-verified; then run BTL purges (reference:
        coordinator.go:498 StoreBlock + pvtstatepurgemgmt)."""
        if self._transient is None:
            return
        from fabric_mod_tpu.ledger.pvtdata import (
            PvtDataMismatchError, pvt_namespace, verify_pvt_against_hashes)
        batch = UpdateBatch()
        consumed = []
        for tx_num, (txid, rwset, _flag) in enumerate(txs):
            if flags[tx_num] != m.TxValidationCode.VALID or rwset is None:
                continue
            if rwset is COLUMNAR:
                # columnar rows are only taken for bodies without
                # collection hashes — same as the empty-`hashed` skip
                continue
            hashed = {}                    # (ns, coll) -> HashedRWSet
            for ns_entry in rwset.ns_rwset:
                for ch in ns_entry.collection_hashed_rwset:
                    hashed[(ns_entry.namespace, ch.collection_name)] = \
                        m.HashedRWSet.decode(ch.hashed_rwset)
            if not hashed:
                continue
            candidates = self._transient.get_by_txid(txid)
            for (ns, coll), hset in hashed.items():
                kv = self._find_matching_pvt(candidates, ns, coll, hset)
                if kv is None:
                    # missing: record the digest so the reconciler can
                    # pull it from an eligible peer later
                    self._pvtstore.report_missing(num, tx_num, ns, coll)
                    continue
                for w in kv.writes:
                    pns = pvt_namespace(ns, coll)
                    if w.is_delete:
                        batch.delete(pns, w.key, (num, tx_num))
                    else:
                        batch.put(pns, w.key, w.value, (num, tx_num))
                self._pvtstore.commit(num, tx_num, ns, coll, kv,
                                      self._btl_fn(ns, coll))
            consumed.append(txid)
        if len(batch):
            self._apply_state_updates(batch, num)
        # purge ALL txids this block carried (valid or not — an
        # invalidated private tx would otherwise leak its plaintext in
        # the transient store forever), plus endorsement leftovers
        # older than the retention window (reference: the commit-path
        # PurgeBelowHeight)
        self._transient.purge_by_txids(
            [txid for txid, _r, _f in txs if txid])
        self._transient.purge_below_height(
            max(0, num - self.TRANSIENT_RETENTION_BLOCKS))
        # BTL expiry: delete only keys whose committed version still
        # IS the expiring write — a later rewrite has its own expiry
        # (reference: pvtstatepurgemgmt's version-matched purge)
        purge_batch = UpdateBatch()
        for bn, tn, ns, coll, keys in self._pvtstore.expiring_at(num):
            pns = pvt_namespace(ns, coll)
            for key in keys:
                if self.state.get_version(pns, key) == (bn, tn):
                    purge_batch.delete(pns, key, (num, 0))
        if len(purge_batch):
            self._apply_state_updates(purge_batch, num)
        self._pvtstore.purge(num)
        # ONE durability barrier for the whole block's private data —
        # per-collection fsyncs would multiply commit latency by the
        # number of collections (the blockstore also syncs per block)
        if hasattr(self._pvtstore, "sync"):
            self._pvtstore.sync()

    # -- reconciliation (reference: gossip/privdata/reconcile.go:339) ----
    def get_pvt(self, block_num: int, tx_num: int):
        """Committed plaintext private write-sets for one tx:
        [(ns, collection, KVRWSet)] — the public surface reconciliation
        responders serve from."""
        if self._pvtstore is None:
            return []
        return self._pvtstore.get(block_num, tx_num)

    def missing_pvt_count(self) -> int:
        """Total reconciliation backlog (exported as a gauge by the
        gossip reconciler — the 'is the queue draining?' signal)."""
        if self._pvtstore is None or not hasattr(self._pvtstore,
                                                 "missing_count"):
            return 0
        return self._pvtstore.missing_count()

    def missing_pvt(self, limit: int = 50):
        """Unreconciled (block, tx, ns, collection) digests, dropping
        any whose BTL already lapsed (no longer needed or wanted)."""
        if self._pvtstore is None:
            return []
        out = []
        for bn, tn, ns, coll in self._pvtstore.missing(limit):
            if self._pvt_expired(bn, ns, coll):
                self._pvtstore.drop_missing(bn, tn, ns, coll)
                continue
            out.append((bn, tn, ns, coll))
        return out

    def _pvt_expired(self, block_num: int, ns: str, coll: str) -> bool:
        """BTL lapse check aligned with the purge schedule: data from
        `block_num` is purged while committing block block_num+btl+1,
        i.e. it is dead once height ≥ block_num+btl+2 — before that,
        eligible peers still serve it and backfills are welcome."""
        btl = self._btl_fn(ns, coll)
        return btl > 0 and block_num + btl + 2 <= self.height

    def reconcile_pvt(self, block_num: int, tx_num: int, ns: str,
                      coll: str, kv: m.KVRWSet) -> bool:
        """Backfill a previously-missing private write-set obtained
        from a peer: re-verify it against the hashes the committed
        block carries, then apply writes version-aware (a key already
        rewritten by a LATER block keeps the newer value).  Returns
        True when the digest was resolved."""
        from fabric_mod_tpu.ledger.pvtdata import (
            PvtDataMismatchError, pvt_namespace, verify_pvt_against_hashes)
        with self._lock:
            if self._pvtstore is None or \
                    not self._pvtstore.is_missing(block_num, tx_num,
                                                  ns, coll):
                return False
            if self._pvt_expired(block_num, ns, coll):
                self._pvtstore.drop_missing(block_num, tx_num, ns, coll)
                return False               # expired while missing
            block = self.blockstore.get_block_by_number(block_num)
            if block is None:
                return False
            flags = protoutil.block_txflags(block)
            envs = protoutil.get_envelopes(block)
            if tx_num >= len(envs) or \
                    flags[tx_num] != m.TxValidationCode.VALID:
                self._pvtstore.drop_missing(block_num, tx_num, ns, coll)
                return False
            rwset = tx_rwset_from_envelope(envs[tx_num])
            hset = None
            if rwset is not None:
                for ns_entry in rwset.ns_rwset:
                    if ns_entry.namespace != ns:
                        continue
                    for ch in ns_entry.collection_hashed_rwset:
                        if ch.collection_name == coll:
                            hset = m.HashedRWSet.decode(ch.hashed_rwset)
            if hset is None:
                self._pvtstore.drop_missing(block_num, tx_num, ns, coll)
                return False               # block never hashed this coll
            try:
                verify_pvt_against_hashes(hset, kv)
            except PvtDataMismatchError:
                return False               # forged response; keep waiting
            batch = UpdateBatch()
            pns = pvt_namespace(ns, coll)
            later_keys = self._pvtstore.later_written_keys(
                block_num, tx_num, ns, coll)
            for w in kv.writes:
                cur = self.state.get_version(pns, w.key)
                if cur is not None and cur >= (block_num, tx_num):
                    continue               # a later tx already wrote it
                if w.key in later_keys:
                    continue               # later delete left no version
                if w.is_delete:
                    batch.delete(pns, w.key, (block_num, tx_num))
                else:
                    batch.put(pns, w.key, w.value, (block_num, tx_num))
            if len(batch):
                # keep the savepoint where it is: this backfills an old
                # block, it does not advance commit progress
                self._apply_state_updates(batch, self.state.savepoint)
            self._pvtstore.commit(block_num, tx_num, ns, coll, kv,
                                  self._btl_fn(ns, coll))
            return True

    @staticmethod
    def _find_matching_pvt(candidates, ns, coll, hset):
        from fabric_mod_tpu.ledger.pvtdata import (
            PvtDataMismatchError, verify_pvt_against_hashes)
        for cand in candidates:
            for ns_pvt in cand.ns_pvt_rwset:
                if ns_pvt.namespace != ns:
                    continue
                for cp in ns_pvt.collection_pvt_rwset:
                    if cp.collection_name != coll:
                        continue
                    kv = m.KVRWSet.decode(cp.rwset)
                    try:
                        verify_pvt_against_hashes(hset, kv)
                        return kv
                    except PvtDataMismatchError:
                        continue           # forged/stale candidate
        return None

    # -- state fingerprint -----------------------------------------------
    # The digest is height ‖ an XOR of independent per-entry hashes
    # (one per state row, one per key's metadata dict).  XOR is the
    # point: it makes the accumulator ORDER-FREE and INVERTIBLE, so a
    # commit folds its UpdateBatch in O(batch) — remove the old
    # entry's hash, add the new one — instead of re-scanning a
    # million-key state per block.  Each entry hash is an injective
    # length-prefixed encoding under a domain tag ("S" rows, "M"
    # metadata), so colliding entries would need a sha256 collision.

    @staticmethod
    def _fp_entry(tag: bytes, ns: str, key: str, tail: bytes) -> int:
        h = hashlib.sha256(tag)
        for part in (ns.encode(), key.encode()):
            h.update(len(part).to_bytes(4, "big"))
            h.update(part)
        h.update(tail)
        return int.from_bytes(h.digest(), "big")

    @classmethod
    def _fp_row(cls, ns: str, key: str, value: bytes,
                ver: Version) -> int:
        tail = (len(value).to_bytes(4, "big") + value
                + ver[0].to_bytes(8, "big") + ver[1].to_bytes(8, "big"))
        return cls._fp_entry(b"S", ns, key, tail)

    @classmethod
    def _fp_meta(cls, ns: str, key: str,
                 entries: Dict[str, bytes]) -> int:
        parts = [len(entries).to_bytes(4, "big")]
        for name in sorted(entries):
            for part in (name.encode(), entries[name]):
                parts.append(len(part).to_bytes(4, "big"))
                parts.append(part)
        return cls._fp_entry(b"M", ns, key, b"".join(parts))

    def _fp_scan_acc(self) -> int:
        acc = 0
        for ns, key, value, ver in self.state.iter_state():
            acc ^= self._fp_row(ns, key, value, ver)
        for ns, key, entries in self.state.iter_metadata():
            acc ^= self._fp_meta(ns, key, entries)
        return acc

    def _fp_fold(self, batch: UpdateBatch) -> None:
        """Fold one UpdateBatch into the cached accumulator — the
        exact delta statedb.apply_updates is about to make (put keeps
        metadata, delete drops it, metadata writes bump the row
        version and skip rows absent after the value pass).  Called
        BEFORE the apply so the old entries are still readable."""
        acc = self._fp_acc
        state = self.state
        for (ns, key), (value, version) in batch.updates.items():
            old = state.get_state(ns, key)
            if old is not None:
                acc ^= self._fp_row(ns, key, old[0], old[1])
                if value is None:
                    oldm = state.get_metadata(ns, key)
                    if oldm:
                        acc ^= self._fp_meta(ns, key, oldm)
            if value is not None:
                acc ^= self._fp_row(ns, key, value, version)
        for (ns, key), (entries, version) in batch.meta_updates.items():
            upd = batch.updates.get((ns, key))
            if upd is not None:
                value, ver = upd
                if value is None:
                    continue          # row gone after the value pass
            else:
                got = state.get_state(ns, key)
                if got is None:
                    continue          # metadata without a key: no-op
                value, ver = got
            acc ^= self._fp_row(ns, key, value, ver)
            acc ^= self._fp_row(ns, key, value, version)
            oldm = state.get_metadata(ns, key)
            if oldm:
                acc ^= self._fp_meta(ns, key, oldm)
            if entries:
                acc ^= self._fp_meta(ns, key, dict(entries))
        self._fp_acc = acc

    def _apply_state_updates(self, batch: UpdateBatch,
                             height: int) -> None:
        """EVERY state mutation funnels through here (commit, pvt
        plaintext, BTL purge, reconciliation backfill, recovery
        replay) so the fingerprint accumulator can never silently
        drift from the statedb it summarizes."""
        if self._fp_acc is not None and len(batch):
            self._fp_fold(batch)
        self.state.apply_updates(batch, height)

    # -- queries ---------------------------------------------------------
    def state_fingerprint(self) -> str:
        """Deterministic digest of the ENTIRE committed state: every
        (ns, key, value, version) row plus every key-metadata entry
        (VALIDATION_PARAMETER included) plus the chain height.  Two
        ledgers that committed the same blocks with the same verdicts
        agree bit-for-bit — the commit-pipeline differential's
        equality oracle (bench.py --metric commitpipe/statescale,
        tests/test_commitpipe.py).  The first call full-scans to seed
        the accumulator; later calls are O(1) because every commit
        folded its own delta (state_fingerprint_full stays as the
        scan-from-scratch oracle).

        Taken under the COMMIT lock: commit_block advances the block
        store before applying state, so an unlocked scan racing an
        in-flight commit would hash height N+1 with block N's writes
        missing — a phantom divergence that is pure read timing (the
        soak harness's convergence checker hit exactly this on the
        freshest block of whichever peer committed last)."""
        with tracing.span("fingerprint", channel=self.ledger_id):
            with self._lock:
                if self._fp_acc is None:
                    self._fp_acc = self._fp_scan_acc()
                h = hashlib.sha256(self.height.to_bytes(8, "big"))
                h.update(self._fp_acc.to_bytes(32, "big"))
                return h.hexdigest()

    def state_fingerprint_full(self) -> str:
        """Scan-from-scratch recompute, bypassing the cached
        accumulator — the incremental path's differential oracle
        (tests assert it equals state_fingerprint after arbitrary
        commit/pvt/reconcile histories)."""
        with self._lock:
            h = hashlib.sha256(self.height.to_bytes(8, "big"))
            h.update(self._fp_scan_acc().to_bytes(32, "big"))
            return h.hexdigest()

    @property
    def height(self) -> int:
        return self.blockstore.height

    def get_block_by_number(self, num: int) -> Optional[m.Block]:
        return self.blockstore.get_block_by_number(num)

    def get_transaction_by_id(self, txid: str) -> Optional[m.ProcessedTransaction]:
        loc = self.blockstore.get_tx_loc(txid)
        if loc is None:
            return None
        block = self.blockstore.get_block_by_number(loc[0])
        if block is None:
            return None                    # known txid, pruned block
        flags = protoutil.block_txflags(block)
        return m.ProcessedTransaction(
            transaction_envelope=protoutil.get_envelopes(block)[loc[1]],
            validation_code=flags[loc[1]])

    def tx_id_exists(self, txid: str) -> bool:
        return self.blockstore.get_tx_loc(txid) is not None

    def snapshot_to(self, out_dir: str) -> dict:
        """Consistent snapshot export: ledger/snapshot.generate_snapshot
        under the commit lock, so no block lands mid-iteration of the
        state it seals."""
        from fabric_mod_tpu.ledger.snapshot import generate_snapshot
        with self._lock:
            return generate_snapshot(self, out_dir)

    def close(self) -> None:
        with self._lock:
            if self._durable:
                self.state.close()
                self.history.close()
            else:
                self.state.snapshot(self._state_path)
            # attached pvt/transient stores may hold open op-logs
            for store in (self._transient, self._pvtstore):
                if store is not None and hasattr(store, "close"):
                    store.close()
            self.blockstore.close()


class LedgerManager:
    """Open/create ledgers by id (reference: ledgermgmt/ledger_mgmt.go)."""

    def __init__(self, root_dir: str):
        self.root = root_dir
        os.makedirs(root_dir, exist_ok=True)
        self._ledgers: Dict[str, KvLedger] = {}

    def create_or_open(self, ledger_id: str) -> KvLedger:
        if ledger_id not in self._ledgers:
            self._ledgers[ledger_id] = KvLedger(
                os.path.join(self.root, ledger_id), ledger_id)
        return self._ledgers[ledger_id]

    def ledger_ids(self) -> List[str]:
        existing = set(self._ledgers)
        if os.path.isdir(self.root):
            existing.update(os.listdir(self.root))
        return sorted(existing)

    def close(self) -> None:
        for led in self._ledgers.values():
            led.close()
