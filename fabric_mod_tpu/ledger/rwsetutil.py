"""Read-write set building and parsing.

(reference: core/ledger/kvledger/txmgmt/rwsetutil/rwset_builder.go and
rwset_proto_util.go.)  A simulation collects (key, version) reads,
range-query fingerprints, and (key, value) writes per namespace; the
builder renders them into the deterministic TxReadWriteSet proto the
validator re-parses at commit time.

Range-query results are fingerprinted with a running SHA-256 over the
sorted (key, version) pairs (stored in RangeQueryInfo.reads_merkle_hash)
— MVCC phantom detection re-executes the range at validation time and
compares fingerprints, the same equality the reference gets from its
merkle summaries.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

from fabric_mod_tpu.protos import messages as m

Version = Tuple[int, int]


def version_proto(v: Optional[Version]) -> Optional[m.Version]:
    if v is None:
        return None
    return m.Version(block_num=v[0], tx_num=v[1])


def version_tuple(v: Optional[m.Version]) -> Optional[Version]:
    if v is None:
        return None
    return (v.block_num, v.tx_num)


def range_fingerprint(results: List[Tuple[str, Version]]) -> bytes:
    """Deterministic digest of a range-query result set."""
    h = hashlib.sha256()
    for key, ver in results:
        kb = key.encode()
        h.update(len(kb).to_bytes(4, "big"))
        h.update(kb)
        h.update(ver[0].to_bytes(8, "big"))
        h.update(ver[1].to_bytes(8, "big"))
    return h.digest()


class RWSetBuilder:
    """Collects one transaction's simulation effects."""

    def __init__(self):
        self._reads: Dict[str, Dict[str, Optional[Version]]] = {}
        self._writes: Dict[str, Dict[str, Optional[bytes]]] = {}
        self._ranges: Dict[str, List[m.RangeQueryInfo]] = {}
        self._meta: Dict[str, Dict[str, Dict[str, bytes]]] = {}
        self._pvt: Dict[Tuple[str, str], Dict[str, Optional[bytes]]] = {}

    def add_read(self, ns: str, key: str, version: Optional[Version]) -> None:
        self._reads.setdefault(ns, {}).setdefault(key, version)

    def add_write(self, ns: str, key: str, value: Optional[bytes]) -> None:
        self._writes.setdefault(ns, {})[key] = value

    def add_metadata_write(self, ns: str, key: str, name: str,
                           value: bytes) -> None:
        """(reference: rwset_builder.go AddToMetadataWriteSet — key
        metadata like the VALIDATION_PARAMETER endorsement override)"""
        self._meta.setdefault(ns, {}).setdefault(key, {})[name] = value

    def add_pvt_write(self, ns: str, collection: str, key: str,
                      value: Optional[bytes]) -> None:
        """Private write: plaintext goes to the pvt rwset (transient
        distribution), sha256 hashes go into the PUBLIC rwset's
        hashed collection section (reference: rwset_builder.go's
        pvt/hashed dual bookkeeping)."""
        self._pvt.setdefault((ns, collection), {})[key] = value

    def build_pvt(self) -> Optional[m.TxPvtReadWriteSet]:
        """The plaintext private write-sets (None when no pvt writes)
        — what the endorser stages into the transient store."""
        if not self._pvt:
            return None
        by_ns: Dict[str, List[m.CollectionPvtReadWriteSet]] = {}
        for (ns, coll), writes in sorted(self._pvt.items()):
            kv = m.KVRWSet(writes=[
                m.KVWrite(key=k, is_delete=int(v is None),
                          value=v or b"")
                for k, v in sorted(writes.items())])
            by_ns.setdefault(ns, []).append(
                m.CollectionPvtReadWriteSet(collection_name=coll,
                                            rwset=kv.encode()))
        return m.TxPvtReadWriteSet(ns_pvt_rwset=[
            m.NsPvtReadWriteSet(namespace=ns, collection_pvt_rwset=colls)
            for ns, colls in sorted(by_ns.items())])

    def add_range_query(self, ns: str, start: str, end: str,
                        exhausted: bool,
                        results: List[Tuple[str, Version]]) -> None:
        self._ranges.setdefault(ns, []).append(m.RangeQueryInfo(
            start_key=start, end_key=end, itr_exhausted=int(exhausted),
            reads_merkle_hash=range_fingerprint(results)))

    def build(self) -> m.TxReadWriteSet:
        from fabric_mod_tpu.ledger.pvtdata import hash_key, hash_value
        hashed_by_ns: Dict[str, List[m.CollectionHashedReadWriteSet]] = {}
        for (ns, coll), writes in sorted(self._pvt.items()):
            hset = m.HashedRWSet(hashed_writes=[
                m.KVWriteHash(key_hash=hash_key(k),
                              is_delete=int(v is None),
                              value_hash=b"" if v is None
                              else hash_value(v))
                for k, v in sorted(writes.items())])
            hashed_by_ns.setdefault(ns, []).append(
                m.CollectionHashedReadWriteSet(
                    collection_name=coll, hashed_rwset=hset.encode()))
        ns_sets = []
        for ns in sorted(set(self._reads) | set(self._writes)
                         | set(self._ranges) | set(self._meta)
                         | set(hashed_by_ns)):
            kv = m.KVRWSet(
                reads=[m.KVRead(key=k, version=version_proto(v))
                       for k, v in sorted(
                           self._reads.get(ns, {}).items())],
                range_queries_info=self._ranges.get(ns, []),
                writes=[m.KVWrite(key=k,
                                  is_delete=int(val is None),
                                  value=val or b"")
                        for k, val in sorted(
                            self._writes.get(ns, {}).items())],
                metadata_writes=[
                    m.KVMetadataWrite(key=k, entries=[
                        m.KVMetadataEntry(name=n, value=v)
                        for n, v in sorted(entries.items())])
                    for k, entries in sorted(
                        self._meta.get(ns, {}).items())])
            ns_sets.append(m.NsReadWriteSet(
                namespace=ns, rwset=kv.encode(),
                collection_hashed_rwset=hashed_by_ns.get(ns, [])))
        return m.TxReadWriteSet(data_model=0, ns_rwset=ns_sets)


def parse_tx_rwset(rwset: m.TxReadWriteSet) -> List[Tuple[str, m.KVRWSet]]:
    return [(ns.namespace, m.KVRWSet.decode(ns.rwset))
            for ns in rwset.ns_rwset]
