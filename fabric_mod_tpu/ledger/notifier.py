"""CommitNotifier: one thread fans a ledger's commit signal out.

(reference: core/peer/gossip + deliver's CommitNotifier role — block
commit is observed ONCE and every standing deliver stream is handed
the signal, instead of each stream polling the tip.)

Before this module, every Deliver/DeliverFiltered stream parked inside
``cond.wait(timeout=1.0)`` on the ledger's ``height_changed``
condition: 10k parked subscribers generated 10k wakeups per second of
pure tick traffic.  The CommitNotifier replaces the per-stream tick
with ONE RegisteredThread parked (untimed) on the source condition;
when the height advances it first runs the registered on-commit
callbacks (the fan-out engine materializes the new frames here, so
frames are ready BEFORE any subscriber wakes) and then sets each
parked waiter's private Event — one wakeup per (commit, waiter),
zero wakeups while idle.

Waiters never touch the source condition: a stream waits on its own
``CommitWaiter`` Event, which a client cancellation (a
``CancellationEvent`` hook), server close, or the notifier itself can
set — so stop()/close() latency stays bounded without ticks.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Set

from fabric_mod_tpu.concurrency import RegisteredThread, assert_joined
from fabric_mod_tpu.concurrency.locks import RegisteredLock


class CommitWaiter:
    """One parked stream's wake handle (see CommitNotifier)."""

    __slots__ = ("event", "cancelled", "wakes")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.cancelled = False
        self.wakes = 0          # commit signals received (test surface)

    def cancel(self) -> None:
        """Wake the waiter out of any pending wait (idempotent); the
        hook side of a stream's CancellationEvent."""
        self.cancelled = True
        self.event.set()


class CommitNotifier:
    """Fan one commit condition out to N parked waiters.

    `cond` is the source's commit condition (``notify_all`` on every
    commit: KvLedger.height_changed / BlockWriter.height_changed) and
    `height_fn` reads its current height.  Both are safe to call with
    `cond` held (the committers notify OUTSIDE their store locks).
    """

    def __init__(self, cond: threading.Condition,
                 height_fn: Callable[[], int], name: str = "commit"):
        self._cond = cond
        self._height = height_fn
        self._name = name
        self._lock = RegisteredLock(f"ledger.notifier.{name}._lock")
        self._waiters: Set[CommitWaiter] = set()
        self._callbacks: List[Callable[[int], None]] = []
        self._closed = False
        self._started = False
        self._thread: Optional[RegisteredThread] = None

    # -- lifecycle --------------------------------------------------------

    def ensure_started(self) -> None:
        """Start the relay thread on first demand (a server with no
        parked streams never spawns it)."""
        with self._lock:
            if self._started or self._closed:
                return
            self._started = True
            self._thread = RegisteredThread(
                self._run, name=f"notifier-{self._name}",
                structure="CommitNotifier")
            self._thread.start()

    def close(self) -> None:
        """Stop the relay and wake every parked waiter (idempotent).
        Bounded: the relay parks untimed but close() notifies the
        source condition, so the join is prompt."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            thread = self._thread
            waiters = list(self._waiters)
        with self._cond:
            self._cond.notify_all()
        for w in waiters:
            w.event.set()
        if thread is not None:
            assert_joined([thread], owner=f"CommitNotifier({self._name})")

    @property
    def closed(self) -> bool:
        return self._closed

    # -- registration ------------------------------------------------------

    def on_commit(self, callback: Callable[[int], None]) -> None:
        """Run `callback(height)` on the relay thread after each height
        advance, BEFORE waiters wake (frame materialization hook)."""
        with self._lock:
            self._callbacks.append(callback)

    def waiter(self) -> CommitWaiter:
        self.ensure_started()
        w = CommitWaiter()
        with self._lock:
            self._waiters.add(w)
            if self._closed:
                w.event.set()
        return w

    def release(self, w: CommitWaiter) -> None:
        with self._lock:
            self._waiters.discard(w)

    # -- the wait (stream side) -------------------------------------------

    def wait_above(self, num: int, w: CommitWaiter,
                   timeout_s: Optional[float] = None) -> str:
        """Park until height > num: "commit", or "cancelled" /
        "closed" / "timeout".  Safe against lost wakeups: the height
        is re-read before every wait, and a commit signal arriving
        between the read and the wait sets the (still-uncleared)
        event."""
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        while True:
            if self._height() > num:
                return "commit"
            if w.cancelled:
                return "cancelled"
            if self._closed:
                return "closed"
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return "timeout"
                ok = w.event.wait(timeout=remaining)
            else:
                ok = w.event.wait()
            if ok:
                w.event.clear()

    # -- the relay (notifier thread) ---------------------------------------

    def _run(self) -> None:
        cond = self._cond
        last = self._height()
        while True:
            with cond:
                # reading the height under the source cond is the same
                # ordering every pre-fanout stream used (commit paths
                # notify OUTSIDE their store locks, so no inversion)
                while not self._closed and self._height() == last:
                    cond.wait()
                if self._closed:
                    break
                h = self._height()
            last = h
            with self._lock:
                callbacks = list(self._callbacks)
                waiters = list(self._waiters)
            for cb in callbacks:
                try:
                    cb(h)
                except Exception:  # fmtlint: allow[swallowed-exceptions] -- a materialization hook failure must not kill the relay; streams fall back to ledger re-read
                    pass
            for w in waiters:
                w.wakes += 1
                w.event.set()
        # closing: hand every parked waiter the final wake
        with self._lock:
            waiters = list(self._waiters)
        for w in waiters:
            w.event.set()
