"""L3 ledger: append-only block store, versioned state DB with MVCC,
history, simulation, and crash recovery.

The commit path is SURVEY.md §3.3's serialization point: blocks
arrive signature/policy-validated (flags from the device batch), MVCC
runs serially, state/history are derived — and re-derivable — from
the block store (the ledger *is* the checkpoint, §5.4).
"""
from fabric_mod_tpu.ledger.blkstorage import BlockStore, BlockStoreError  # noqa: F401
from fabric_mod_tpu.ledger.statedb import UpdateBatch, VersionedDB  # noqa: F401
from fabric_mod_tpu.ledger.rwsetutil import RWSetBuilder, parse_tx_rwset  # noqa: F401
from fabric_mod_tpu.ledger.mvcc import validate_and_prepare_batch  # noqa: F401
from fabric_mod_tpu.ledger.kvledger import (  # noqa: F401
    HistoryDB, KvLedger, LedgerError, LedgerManager, QueryExecutor,
    TxSimulator, tx_rwset_from_envelope)
