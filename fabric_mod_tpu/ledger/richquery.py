"""Rich (JSON-document) state queries.

(reference: core/ledger/kvledger/txmgmt/statedb/statecouchdb/
statecouchdb.go:1230 ExecuteQuery — Fabric delegates selector
evaluation to CouchDB's Mango engine; this module implements the same
query surface natively so rich queries work against our versioned
state DBs without an external document store.)

Semantics mirrored from the reference:
* Values that are not JSON objects simply never match a selector
  (CouchDB indexes only JSON documents).
* Rich query results are NOT protected against phantoms at validation
  time — like the reference, which documents that chaincode rich
  queries are not re-executed at commit; the individual returned keys
  ARE added to the read set (statecouchdb query executor behavior).
* Pagination via `limit` + an opaque `bookmark` that continues after
  the last returned key (statecouchdb.go's bookmark contract).

Selector language (the Mango core): implicit equality
`{"owner": "alice"}`, operators `$eq $ne $gt $gte $lt $lte $in $nin
$exists $not $and $or $nor`, nested fields via dotted paths.
`use_index` is accepted and ignored (our scan is the index); `fields`
projects the returned documents; `sort` orders by dotted field paths.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Tuple


class QueryError(Exception):
    pass


_OPS = frozenset(("$eq", "$ne", "$gt", "$gte", "$lt", "$lte", "$in",
                  "$nin", "$exists", "$not", "$and", "$or", "$nor"))


def _field(doc: Any, path: str):
    """Resolve a dotted path; (found, value)."""
    cur = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return False, None
        cur = cur[part]
    return True, cur


def _cmp_ok(a, b) -> bool:
    """CouchDB compares only like types; cross-type comparisons never
    match rather than raising."""
    if isinstance(a, bool) or isinstance(b, bool):
        return isinstance(a, bool) and isinstance(b, bool)
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return True
    return type(a) is type(b)


def _match_cond(value_found: bool, value, cond) -> bool:
    """One field condition: either a bare value (equality) or an
    operator object like {"$gt": 5}."""
    if isinstance(cond, dict) and \
            any(isinstance(k, str) and k.startswith("$") for k in cond):
        for op, operand in cond.items():
            if op == "$exists":
                if bool(operand) != value_found:
                    return False
            elif op == "$not":
                if _match_cond(value_found, value, operand):
                    return False
            elif op == "$eq":
                if not (value_found and value == operand):
                    return False
            elif op == "$ne":
                if value_found and value == operand:
                    return False
            elif op in ("$gt", "$gte", "$lt", "$lte"):
                if not value_found or not _cmp_ok(value, operand):
                    return False
                if op == "$gt" and not value > operand:
                    return False
                if op == "$gte" and not value >= operand:
                    return False
                if op == "$lt" and not value < operand:
                    return False
                if op == "$lte" and not value <= operand:
                    return False
            elif op == "$in":
                if not (value_found and isinstance(operand, list)
                        and value in operand):
                    return False
            elif op == "$nin":
                if value_found and isinstance(operand, list) and \
                        value in operand:
                    return False
            else:
                raise QueryError(f"unsupported operator {op!r}")
        return True
    return value_found and value == cond


def match_selector(doc: Any, selector: Dict) -> bool:
    """Does `doc` satisfy the Mango selector?"""
    if not isinstance(selector, dict):
        raise QueryError("selector must be an object")
    for key, cond in selector.items():
        if key == "$and":
            if not all(match_selector(doc, s) for s in cond):
                return False
        elif key == "$or":
            if not any(match_selector(doc, s) for s in cond):
                return False
        elif key == "$nor":
            if any(match_selector(doc, s) for s in cond):
                return False
        elif key == "$not":
            if match_selector(doc, cond):
                return False
        elif key.startswith("$"):
            raise QueryError(f"unsupported operator {key!r}")
        else:
            found, value = _field(doc, key)
            if not _match_cond(found, value, cond):
                return False
    return True


def _sort_key(doc, sort_spec: List):
    parts = []
    for entry in sort_spec:
        if isinstance(entry, dict):
            [(path, _direction)] = entry.items()
        else:
            path = entry
        found, v = _field(doc, path)
        # sort missing fields first, group values by type name so
        # heterogeneous values order deterministically
        parts.append((not found,
                      type(v).__name__ if found else "",
                      v if found and not isinstance(v, (dict, list))
                      else json.dumps(v, sort_keys=True) if found else ""))
    return tuple(parts)


def _project(doc, fields: Optional[List[str]]):
    if not fields:
        return doc
    out: Dict = {}
    for path in fields:
        found, v = _field(doc, path)
        if not found:
            continue
        cur = out
        parts = path.split(".")
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = v
    return out


class RichQuery:
    """A parsed query: selector + sort/limit/bookmark/fields."""

    def __init__(self, selector: Dict, sort: Optional[List] = None,
                 limit: Optional[int] = None, bookmark: str = "",
                 fields: Optional[List[str]] = None):
        self.selector = selector
        self.sort = sort
        self.limit = limit
        self.bookmark = bookmark
        self.fields = fields

    @classmethod
    def parse(cls, query) -> "RichQuery":
        if isinstance(query, (bytes, str)):
            try:
                query = json.loads(query)
            except Exception as e:
                raise QueryError(f"bad query JSON: {e}") from e
        if not isinstance(query, dict) or "selector" not in query:
            raise QueryError("query must carry a 'selector'")
        limit = query.get("limit")
        if limit is not None and (not isinstance(limit, int) or limit < 0):
            raise QueryError("limit must be a non-negative integer")
        sort = query.get("sort")
        if sort is not None and not isinstance(sort, list):
            raise QueryError("sort must be a list")
        fields = query.get("fields")
        if fields is not None and not isinstance(fields, list):
            raise QueryError("fields must be a list")
        # use_index accepted and ignored (scan IS the index here)
        return cls(query["selector"], sort, limit,
                   str(query.get("bookmark", "") or ""), fields)


def execute(rows: Iterable[Tuple[str, bytes, tuple]], query: RichQuery
            ) -> Tuple[List[Tuple[str, Any, tuple]], str]:
    """Run a parsed query over (key, value_bytes, version) rows in key
    order.  Returns (matches as (key, projected_doc, version), next
    bookmark).  The bookmark is the last returned key; passing it back
    continues strictly after it — only valid for unsorted queries
    (sorted pagination would need the full result anyway, matching
    CouchDB's stable-sort bookmark limits)."""
    if query.sort and query.bookmark:
        raise QueryError("bookmark pagination requires an unsorted query")
    matches: List[Tuple[str, Any, tuple]] = []
    limit = query.limit
    if limit == 0:
        return [], ""
    for key, raw, ver in rows:
        if query.bookmark and key <= query.bookmark:
            continue
        try:
            doc = json.loads(raw)
        except Exception:
            continue                       # non-JSON values never match
        if not match_selector(doc, query.selector):
            continue
        matches.append((key, doc, ver))
        if limit is not None and not query.sort and \
                len(matches) >= limit:
            break                          # early exit: scan no further
    if query.sort:
        directions = {list(e.values())[0] if isinstance(e, dict)
                      else "asc" for e in query.sort}
        if len(directions) > 1:
            # CouchDB's same rule: one direction for the whole sort
            raise QueryError("sort fields must share one direction")
        matches.sort(key=lambda kv: _sort_key(kv[1], query.sort),
                     reverse=(directions == {"desc"}))
        if limit is not None:
            matches = matches[:limit]
    # sorted queries cannot be continued (passing a bookmark back is
    # rejected above): return an empty bookmark so clients can detect
    # pagination is unavailable instead of erroring on page 2
    bookmark = matches[-1][0] if matches and not query.sort else ""
    if query.fields:
        matches = [(k, _project(d, query.fields), v)
                   for k, d, v in matches]
    return matches, bookmark
