"""Durable, log-structured state + history storage.

(reference contracts: kvledger/txmgmt/statedb/stateleveldb/
stateleveldb.go:379 — a disk-backed versioned KV with savepoint — and
kvledger/history/db.go — a persisted key-history index.  Own design,
not a leveldb port: a single append log per store with CRC-framed
records, per-block savepoint markers, a checkpointed in-memory index,
and whole-log compaction.)

Layout per store directory:

  log-<gen>.dat    CRC32-framed records, appended per block, fsynced
                   once per block; ends (logically) at the last
                   complete SAVEPOINT record — a torn tail past it is
                   cropped on open (same crash model as blkstorage)
  ckpt-<gen>.dat   sha256-sealed index checkpoint: (savepoint,
                   log offset watermark, index entries).  Open = load
                   checkpoint + replay the log tail after the
                   watermark — O(delta since checkpoint), never
                   O(chain) (VERDICT r2 weak #5/#6)

Compaction (state store only) rewrites live records into gen+1 and
drops the old generation; values live on disk, the in-memory keydir
holds only (offset, length, version) pointers, so resident memory is
O(#keys), not O(total value bytes).
"""
from __future__ import annotations

import bisect
import functools
import hashlib
import io
import os
import struct
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

from fabric_mod_tpu.ledger.statedb import UpdateBatch, Version
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)

_BATCH_WRITES_OPTS = MetricOpts(
    "fabric", "durable", "update_batch_writes",
    help="apply_updates calls: each is ONE buffered log write + one "
         "flush/fsync for the whole block's frames.")
_BATCH_FRAMES_OPTS = MetricOpts(
    "fabric", "durable", "update_batch_frames",
    help="Framed records carried by those batched writes (put/del/"
         "meta/savepoint) — frames-per-write is the batching ratio.")


@functools.lru_cache(maxsize=None)
def _durable_write_metrics():
    prov = default_provider()
    return (prov.counter(_BATCH_WRITES_OPTS),
            prov.counter(_BATCH_FRAMES_OPTS))

_PUT, _DEL, _SAVE, _POST, _META = 0, 1, 2, 3, 4


def _pack_str(out: io.BytesIO, s: bytes) -> None:
    out.write(struct.pack("<I", len(s)))
    out.write(s)


def _frame(payload: bytes) -> bytes:
    return struct.pack("<II", len(payload), zlib.crc32(payload)) + payload


def _iter_records(buf: bytes, start: int):
    """Yield (offset_after, payload) for each intact record; stops at
    the first torn/corrupt frame."""
    pos = start
    n = len(buf)
    while pos + 8 <= n:
        ln, crc = struct.unpack_from("<II", buf, pos)
        end = pos + 8 + ln
        if end > n:
            return
        payload = buf[pos + 8:end]
        if zlib.crc32(payload) != crc:
            return
        yield end, payload
        pos = end


class _LogStore:
    """Shared append-log + checkpoint machinery."""

    def __init__(self, dir_path: str, name: str):
        self.dir = dir_path
        self.name = name
        os.makedirs(dir_path, exist_ok=True)

    def _path(self, kind: str, gen: int) -> str:
        return os.path.join(self.dir, f"{self.name}-{kind}-{gen:08d}.dat")

    def generations(self) -> List[int]:
        out = []
        prefix = f"{self.name}-log-"
        for fn in os.listdir(self.dir):
            if fn.startswith(prefix) and fn.endswith(".dat"):
                out.append(int(fn[len(prefix):-4]))
        return sorted(out)

    def write_checkpoint(self, gen: int, body: bytes) -> None:
        sealed = body + hashlib.sha256(body).digest()
        tmp = self._path("ckpt", gen) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(sealed)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path("ckpt", gen))

    def read_checkpoint(self, gen: int) -> Optional[bytes]:
        path = self._path("ckpt", gen)
        if not os.path.exists(path):
            return None
        raw = open(path, "rb").read()
        if len(raw) < 32:
            return None
        body, digest = raw[:-32], raw[-32:]
        if hashlib.sha256(body).digest() != digest:
            return None
        return body


class DurableStateDB:
    """Disk-backed versioned KV matching statedb.VersionedDB's API."""

    CKPT_EVERY = 64                     # blocks between index checkpoints
    COMPACT_MIN_BYTES = 8 * 1024 * 1024
    COMPACT_DEAD_RATIO = 0.5

    def __init__(self, dir_path: str):
        self._store = _LogStore(dir_path, "state")
        # keydir: (ns, key) -> (offset_of_value, value_len, Version)
        self._keydir: Dict[Tuple[str, str], Tuple[int, int, Version]] = {}
        # key metadata lives in RAM (small: endorsement overrides etc.)
        self._metadata: Dict[Tuple[str, str], Dict[str, bytes]] = {}
        self._keys: Dict[str, List[str]] = {}
        self._savepoint = -1
        self._dead_bytes = 0
        self._blocks_since_ckpt = 0
        self._open()

    # -- open / recovery ---------------------------------------------------
    def _open(self) -> None:
        gens = self._store.generations()
        self._gen = gens[-1] if gens else 0
        path = self._store._path("log", self._gen)
        if not os.path.exists(path):
            open(path, "wb").close()
        raw = open(path, "rb").read()

        start = 0
        ckpt = self._store.read_checkpoint(self._gen)
        if ckpt is not None:
            start = self._load_checkpoint(ckpt)
            if start > len(raw):            # log shorter than watermark
                start = 0
                self._keydir.clear()
                self._keys.clear()
                self._metadata.clear()
                self._savepoint = -1

        # replay the tail; remember the offset after the last SAVEPOINT
        committed_end = start
        pending: Dict[Tuple[str, str], Tuple[int, int, Optional[Version]]] = {}
        pending_meta: List[Tuple[str, str, Dict[str, bytes], Version]] = []
        sp = self._savepoint
        for end, payload in _iter_records(raw, start):
            kind = payload[0]
            if kind == _SAVE:
                (blk,) = struct.unpack_from("<q", payload, 1)
                for (ns, key), (off, vlen, ver) in pending.items():
                    self._apply_mem(ns, key, off, vlen, ver)
                pending.clear()
                for ns, key, entries, ver in pending_meta:
                    self._apply_meta_mem(ns, key, entries, ver)
                pending_meta.clear()
                sp = blk
                committed_end = end
            elif kind in (_PUT, _DEL):
                # payload begins at end - len(payload) in the file
                ns, key, off, vlen, ver = self._parse_put_del(
                    payload, end - len(payload))
                pending[(ns, key)] = (off, vlen, ver)
            elif kind == _META:
                pending_meta.append(self._parse_meta(payload))
        self._savepoint = sp
        if committed_end < len(raw):        # crop torn tail
            with open(path, "r+b") as f:
                f.truncate(committed_end)
        self._f = open(path, "a+b")
        self._fr = open(path, "rb")
        self._log_size = committed_end

    def _parse_put_del(self, payload: bytes, frame_payload_off: int):
        kind = payload[0]
        pos = 1
        (nl,) = struct.unpack_from("<I", payload, pos); pos += 4
        ns = payload[pos:pos + nl].decode(); pos += nl
        (kl,) = struct.unpack_from("<I", payload, pos); pos += 4
        key = payload[pos:pos + kl].decode(); pos += kl
        bn, tn = struct.unpack_from("<qq", payload, pos); pos += 16
        if kind == _DEL:
            return ns, key, -1, -1, None
        (vl,) = struct.unpack_from("<I", payload, pos); pos += 4
        # offset of the value within the whole log file
        val_off = frame_payload_off + pos
        return ns, key, val_off, vl, (bn, tn)

    def _parse_meta(self, payload: bytes):
        pos = 1
        (nl,) = struct.unpack_from("<I", payload, pos); pos += 4
        ns = payload[pos:pos + nl].decode(); pos += nl
        (kl,) = struct.unpack_from("<I", payload, pos); pos += 4
        key = payload[pos:pos + kl].decode(); pos += kl
        bn, tn = struct.unpack_from("<qq", payload, pos); pos += 16
        (n,) = struct.unpack_from("<I", payload, pos); pos += 4
        entries = {}
        for _ in range(n):
            (ml,) = struct.unpack_from("<I", payload, pos); pos += 4
            name = payload[pos:pos + ml].decode(); pos += ml
            (vl,) = struct.unpack_from("<I", payload, pos); pos += 4
            entries[name] = payload[pos:pos + vl]; pos += vl
        return ns, key, entries, (bn, tn)

    def _apply_meta_mem(self, ns: str, key: str,
                        entries: Dict[str, bytes], ver: Version) -> None:
        got = self._keydir.get((ns, key))
        if got is None:
            return                          # metadata without key: no-op
        self._keydir[(ns, key)] = (got[0], got[1], ver)  # version bump
        if entries:
            self._metadata[(ns, key)] = dict(entries)
        else:
            self._metadata.pop((ns, key), None)

    def _apply_mem(self, ns: str, key: str, off: int, vlen: int,
                   ver: Optional[Version]) -> None:
        keys = self._keys.setdefault(ns, [])
        exists = (ns, key) in self._keydir
        if ver is None:                     # delete
            if exists:
                self._dead_bytes += self._keydir[(ns, key)][1]
                del self._keydir[(ns, key)]
                self._metadata.pop((ns, key), None)
                keys.pop(bisect.bisect_left(keys, key))
        else:
            if exists:
                self._dead_bytes += self._keydir[(ns, key)][1]
            self._keydir[(ns, key)] = (off, vlen, ver)
            if not exists:
                bisect.insort(keys, key)

    # -- checkpoint format --------------------------------------------------
    def _load_checkpoint(self, body: bytes) -> int:
        pos = 0
        self._savepoint, watermark, count = struct.unpack_from("<qqq", body, pos)
        pos += 24
        for _ in range(count):
            (nl,) = struct.unpack_from("<I", body, pos); pos += 4
            ns = body[pos:pos + nl].decode(); pos += nl
            (kl,) = struct.unpack_from("<I", body, pos); pos += 4
            key = body[pos:pos + kl].decode(); pos += kl
            off, vlen, bn, tn = struct.unpack_from("<qqqq", body, pos)
            pos += 32
            self._keydir[(ns, key)] = (off, vlen, (bn, tn))
            self._keys.setdefault(ns, []).append(key)
        # bulk-sort once: O(n log n), not per-key insort O(n^2)
        for keys in self._keys.values():
            keys.sort()
        if pos < len(body):                 # metadata section (v2)
            (mcount,) = struct.unpack_from("<q", body, pos)
            pos += 8
            for _ in range(mcount):
                (nl,) = struct.unpack_from("<I", body, pos); pos += 4
                ns = body[pos:pos + nl].decode(); pos += nl
                (kl,) = struct.unpack_from("<I", body, pos); pos += 4
                key = body[pos:pos + kl].decode(); pos += kl
                (n,) = struct.unpack_from("<I", body, pos); pos += 4
                entries = {}
                for _ in range(n):
                    (ml,) = struct.unpack_from("<I", body, pos); pos += 4
                    name = body[pos:pos + ml].decode(); pos += ml
                    (vl,) = struct.unpack_from("<I", body, pos); pos += 4
                    entries[name] = body[pos:pos + vl]; pos += vl
                self._metadata[(ns, key)] = entries
        return watermark

    def _write_checkpoint(self) -> None:
        buf = io.BytesIO()
        buf.write(struct.pack("<qqq", self._savepoint, self._log_size,
                              len(self._keydir)))
        for (ns, key), (off, vlen, (bn, tn)) in self._keydir.items():
            _pack_str(buf, ns.encode())
            _pack_str(buf, key.encode())
            buf.write(struct.pack("<qqqq", off, vlen, bn, tn))
        buf.write(struct.pack("<q", len(self._metadata)))
        for (ns, key), entries in self._metadata.items():
            _pack_str(buf, ns.encode())
            _pack_str(buf, key.encode())
            buf.write(struct.pack("<I", len(entries)))
            for name, val in sorted(entries.items()):
                _pack_str(buf, name.encode())
                _pack_str(buf, val)
        self._store.write_checkpoint(self._gen, buf.getvalue())

    # -- reads --------------------------------------------------------------
    def _read_value(self, off: int, vlen: int) -> bytes:
        self._fr.seek(off)
        return self._fr.read(vlen)

    def get_state(self, ns: str, key: str):
        got = self._keydir.get((ns, key))
        if got is None:
            return None
        off, vlen, ver = got
        return self._read_value(off, vlen), ver

    def get_version(self, ns: str, key: str) -> Optional[Version]:
        got = self._keydir.get((ns, key))
        return got[2] if got else None

    def get_versions_many(self, pairs) -> List[Optional[Version]]:
        """Bulk committed-version lookup (vectorized MVCC hash-join):
        pure keydir probes — no value reads, no log I/O — so a block's
        whole version resolution is one call even on the durable arm
        (reference: statedb.BulkOptimizable LoadCommittedVersions)."""
        keydir = self._keydir
        out = []
        for pair in pairs:
            got = keydir.get(pair)
            out.append(got[2] if got else None)
        return out

    def get_metadata(self, ns: str, key: str) -> Optional[Dict[str, bytes]]:
        got = self._metadata.get((ns, key))
        return dict(got) if got else None

    def iter_state(self):
        """Deterministic full scan: (ns, key, value, version) sorted."""
        for (ns, key) in sorted(self._keydir):
            off, vlen, ver = self._keydir[(ns, key)]
            yield ns, key, self._read_value(off, vlen), ver

    def iter_metadata(self):
        """Deterministic full metadata scan: (ns, key, {name: value})
        sorted (same contract as VersionedDB.iter_metadata)."""
        for (ns, key) in sorted(self._metadata):
            yield ns, key, dict(self._metadata[(ns, key)])

    def get_state_range(self, ns: str, start: str,
                        end: str) -> List[Tuple[str, bytes, Version]]:
        keys = self._keys.get(ns, [])
        i = bisect.bisect_left(keys, start)
        out = []
        while i < len(keys):
            k = keys[i]
            if end and k >= end:
                break
            off, vlen, ver = self._keydir[(ns, k)]
            out.append((k, self._read_value(off, vlen), ver))
            i += 1
        return out

    @property
    def savepoint(self) -> int:
        return self._savepoint

    # -- writes ---------------------------------------------------------
    def apply_updates(self, batch: UpdateBatch, block_num: int) -> None:
        # the whole block's frames build into ONE bytearray -> one
        # buffered write + one flush/fsync (counted): frame headers
        # are patched in place after each body lands, so nothing is
        # allocated or syscalled per record
        blob = bytearray()
        n_frames = 0

        def begin() -> int:
            hdr = len(blob)
            blob.extend(b"\x00" * 8)
            return hdr

        def end(hdr: int) -> None:
            mv = memoryview(blob)[hdr + 8:]
            crc = zlib.crc32(mv)
            mv.release()
            struct.pack_into("<II", blob, hdr, len(blob) - hdr - 8, crc)

        def pack_str(s: bytes) -> None:
            blob.extend(struct.pack("<I", len(s)))
            blob.extend(s)

        staged = []                       # (ns, key, rel_val_off, vlen, ver)
        base = self._log_size
        for (ns, key), (value, version) in sorted(batch.updates.items()):
            hdr = begin()
            if value is None:
                blob.append(_DEL)
                pack_str(ns.encode())
                pack_str(key.encode())
                blob.extend(struct.pack("<qq", *version))
                staged.append((ns, key, -1, -1, None))
            else:
                blob.append(_PUT)
                pack_str(ns.encode())
                pack_str(key.encode())
                blob.extend(struct.pack("<qq", *version))
                blob.extend(struct.pack("<I", len(value)))
                staged.append((ns, key, len(blob), len(value), version))
                blob.extend(value)
            end(hdr)
            n_frames += 1
        staged_meta = []
        for (ns, key), (entries, version) in sorted(
                batch.meta_updates.items()):
            hdr = begin()
            blob.append(_META)
            pack_str(ns.encode())
            pack_str(key.encode())
            blob.extend(struct.pack("<qq", *version))
            blob.extend(struct.pack("<I", len(entries)))
            for name, val in sorted(entries.items()):
                pack_str(name.encode())
                pack_str(val)
            end(hdr)
            staged_meta.append((ns, key, entries, version))
            n_frames += 1
        hdr = begin()
        blob.append(_SAVE)
        blob.extend(struct.pack("<q", block_num))
        end(hdr)
        n_frames += 1
        writes_ctr, frames_ctr = _durable_write_metrics()
        writes_ctr.add(1)
        frames_ctr.add(n_frames)
        self._f.write(blob)
        self._f.flush()
        os.fsync(self._f.fileno())
        for ns, key, rel, vlen, ver in staged:
            self._apply_mem(ns, key, base + rel if rel >= 0 else -1,
                            vlen, ver)
        for ns, key, entries, ver in staged_meta:
            self._apply_meta_mem(ns, key, entries, ver)
        self._log_size += len(blob)
        self._savepoint = block_num
        self._blocks_since_ckpt += 1
        if self._blocks_since_ckpt >= self.CKPT_EVERY:
            self._write_checkpoint()
            self._blocks_since_ckpt = 0
        if (self._log_size > self.COMPACT_MIN_BYTES and
                self._dead_bytes > self._log_size * self.COMPACT_DEAD_RATIO):
            self._compact()

    # -- compaction -------------------------------------------------------
    def _compact(self) -> None:
        """Rewrite live records into generation+1, drop the old log."""
        new_gen = self._gen + 1
        path = self._store._path("log", new_gen)
        new_keydir: Dict[Tuple[str, str], Tuple[int, int, Version]] = {}
        with open(path, "wb") as f:
            size = 0
            for (ns, key) in sorted(self._keydir):
                off, vlen, ver = self._keydir[(ns, key)]
                value = self._read_value(off, vlen)
                payload = io.BytesIO()
                payload.write(bytes([_PUT]))
                _pack_str(payload, ns.encode())
                _pack_str(payload, key.encode())
                payload.write(struct.pack("<qq", *ver))
                payload.write(struct.pack("<I", len(value)))
                val_off = size + 8 + payload.tell()
                payload.write(value)
                blob = _frame(payload.getvalue())
                f.write(blob)
                new_keydir[(ns, key)] = (val_off, len(value), ver)
                size += len(blob)
            for (ns, key), entries in sorted(self._metadata.items()):
                if (ns, key) not in new_keydir:
                    continue
                ver = new_keydir[(ns, key)][2]
                payload = io.BytesIO()
                payload.write(bytes([_META]))
                _pack_str(payload, ns.encode())
                _pack_str(payload, key.encode())
                payload.write(struct.pack("<qq", *ver))
                payload.write(struct.pack("<I", len(entries)))
                for name, val in sorted(entries.items()):
                    _pack_str(payload, name.encode())
                    _pack_str(payload, val)
                blob = _frame(payload.getvalue())
                f.write(blob)
                size += len(blob)
            f.write(_frame(bytes([_SAVE]) +
                           struct.pack("<q", self._savepoint)))
            size += 8 + 9
            f.flush()
            os.fsync(f.fileno())
        old_gen = self._gen
        self._gen = new_gen
        self._keydir = new_keydir
        self._log_size = size
        self._dead_bytes = 0
        self._f.close()
        self._fr.close()
        self._f = open(path, "a+b")
        self._fr = open(path, "rb")
        self._write_checkpoint()
        for kind in ("log", "ckpt"):
            old = self._store._path(kind, old_gen)
            if os.path.exists(old):
                os.remove(old)

    def close(self) -> None:
        self._write_checkpoint()
        self._f.close()
        self._fr.close()


class DurableHistoryDB:
    """Persisted key-history index (reference: kvledger/history/db.go):
    an append log of (block, tx, ns, key) postings + an index
    checkpoint, recovering in O(delta since checkpoint)."""

    CKPT_EVERY = 256

    def __init__(self, dir_path: str):
        self._store = _LogStore(dir_path, "hist")
        self._hist: Dict[Tuple[str, str], List[Version]] = {}
        self._savepoint = -1
        self._blocks_since_ckpt = 0
        self._open()

    def _open(self) -> None:
        path = self._store._path("log", 0)
        if not os.path.exists(path):
            open(path, "wb").close()
        raw = open(path, "rb").read()
        start = 0
        ckpt = self._store.read_checkpoint(0)
        if ckpt is not None:
            start = self._load_checkpoint(ckpt)
            if start > len(raw):
                start = 0
                self._hist.clear()
                self._savepoint = -1
        committed_end = start
        pending: List[Tuple[str, str, Version]] = []
        for end, payload in _iter_records(raw, start):
            kind = payload[0]
            if kind == _SAVE:
                (blk,) = struct.unpack_from("<q", payload, 1)
                for ns, key, ver in pending:
                    self._hist.setdefault((ns, key), []).append(ver)
                pending.clear()
                self._savepoint = blk
                committed_end = end
            elif kind == _POST:
                pos = 1
                (nl,) = struct.unpack_from("<I", payload, pos); pos += 4
                ns = payload[pos:pos + nl].decode(); pos += nl
                (kl,) = struct.unpack_from("<I", payload, pos); pos += 4
                key = payload[pos:pos + kl].decode(); pos += kl
                bn, tn = struct.unpack_from("<qq", payload, pos)
                pending.append((ns, key, (bn, tn)))
        if committed_end < len(raw):
            with open(path, "r+b") as f:
                f.truncate(committed_end)
        self._f = open(path, "a+b")
        self._log_size = committed_end

    def _load_checkpoint(self, body: bytes) -> int:
        pos = 0
        self._savepoint, watermark, count = struct.unpack_from(
            "<qqq", body, pos)
        pos += 24
        for _ in range(count):
            (nl,) = struct.unpack_from("<I", body, pos); pos += 4
            ns = body[pos:pos + nl].decode(); pos += nl
            (kl,) = struct.unpack_from("<I", body, pos); pos += 4
            key = body[pos:pos + kl].decode(); pos += kl
            (n,) = struct.unpack_from("<I", body, pos); pos += 4
            vers = []
            for _ in range(n):
                bn, tn = struct.unpack_from("<qq", body, pos)
                pos += 16
                vers.append((bn, tn))
            self._hist[(ns, key)] = vers
        return watermark

    def _write_checkpoint(self) -> None:
        buf = io.BytesIO()
        buf.write(struct.pack("<qqq", self._savepoint, self._log_size,
                              len(self._hist)))
        for (ns, key), vers in self._hist.items():
            _pack_str(buf, ns.encode())
            _pack_str(buf, key.encode())
            buf.write(struct.pack("<I", len(vers)))
            for bn, tn in vers:
                buf.write(struct.pack("<qq", bn, tn))
        self._store.write_checkpoint(0, buf.getvalue())

    @property
    def savepoint(self) -> int:
        return self._savepoint

    def commit(self, block_num: int,
               tx_writes: List[Tuple[int, str, str]]) -> None:
        if block_num <= self._savepoint:
            return                        # replay overlap: already have it
        frames = io.BytesIO()
        for tx_num, ns, key in tx_writes:
            payload = io.BytesIO()
            payload.write(bytes([_POST]))
            _pack_str(payload, ns.encode())
            _pack_str(payload, key.encode())
            payload.write(struct.pack("<qq", block_num, tx_num))
            frames.write(_frame(payload.getvalue()))
        frames.write(_frame(bytes([_SAVE]) + struct.pack("<q", block_num)))
        blob = frames.getvalue()
        self._f.write(blob)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._log_size += len(blob)
        for tx_num, ns, key in tx_writes:
            self._hist.setdefault((ns, key), []).append((block_num, tx_num))
        self._savepoint = block_num
        self._blocks_since_ckpt += 1
        if self._blocks_since_ckpt >= self.CKPT_EVERY:
            self._write_checkpoint()
            self._blocks_since_ckpt = 0

    def get_history_for_key(self, ns: str, key: str) -> List[Version]:
        return list(self._hist.get((ns, key), []))

    def close(self) -> None:
        self._write_checkpoint()
        self._f.close()
