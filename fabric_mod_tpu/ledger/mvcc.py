"""MVCC validation: serial read-set version checks + phantom detection.

(reference: core/ledger/kvledger/txmgmt/validation/validator.go:82
`validateAndPrepareBatch`, `validateKVRead` at :173, range-query
re-execution for phantom reads.)  Runs after signature/policy
validation (which the device batch already decided); this stage is
inherently serial because each transaction's reads must be checked
against the writes of every earlier valid transaction in the same
block — the reference keeps it on one goroutine, we keep it on host.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from fabric_mod_tpu.ledger.rwsetutil import (
    parse_tx_rwset, range_fingerprint, version_tuple)
from fabric_mod_tpu.ledger.statedb import UpdateBatch, VersionedDB
from fabric_mod_tpu.protos import messages as m

Version = Tuple[int, int]


def _read_conflicts(db: VersionedDB, batch: UpdateBatch,
                    ns: str, read: m.KVRead) -> bool:
    """A read conflicts if the key was touched earlier in this block —
    including deletes — or its committed version moved (reference:
    validator.go:173 validateKVRead: any key present in the update
    batch conflicts outright)."""
    if batch.get(ns, read.key) is not None:
        return True
    return db.get_version(ns, read.key) != version_tuple(read.version)


def _combined_range(db: VersionedDB, batch: UpdateBatch,
                    ns: str, start: str, end: str):
    """Merge committed state with the in-block pending batch, key order."""
    pending = {key: val for (n, key), val in batch.updates.items()
               if n == ns and start <= key and (not end or key < end)}
    out = []
    for key, value, ver in db.get_state_range(ns, start, end):
        if key in pending:
            continue                        # overridden by this block
        out.append((key, ver))
    for key, (value, ver) in pending.items():
        if value is not None:
            out.append((key, ver))
    out.sort(key=lambda kv: kv[0])
    return out


def validate_kv_read(db: VersionedDB, batch: UpdateBatch,
                     ns: str, read: m.KVRead) -> bool:
    return not _read_conflicts(db, batch, ns, read)


def validate_range_query(db: VersionedDB, batch: UpdateBatch, ns: str,
                         rq: m.RangeQueryInfo) -> bool:
    results = _combined_range(db, batch, ns, rq.start_key, rq.end_key)
    return range_fingerprint(results) == rq.reads_merkle_hash


def validate_and_prepare_batch(
        txs: List[Tuple[str, Optional[m.TxReadWriteSet], int]],
        db: VersionedDB, block_num: int
) -> Tuple[List[int], UpdateBatch, List[Tuple[int, str, str]]]:
    """Serial MVCC pass over a block.

    `txs` is [(tx_id, rwset | None, incoming_flag)] in block order;
    incoming flags carry upstream verdicts (signature/policy/dup) —
    only VALID transactions are MVCC-checked.  Returns the final
    per-tx validation codes, the state UpdateBatch of the surviving
    writes versioned (block_num, tx_num), and the per-tx write list
    [(tx_num, ns, key)] for the history DB (parsed once here so the
    commit path never re-decodes rwsets).
    """
    flags: List[int] = []
    batch = UpdateBatch()
    tx_writes: List[Tuple[int, str, str]] = []
    for tx_num, (txid, rwset, incoming) in enumerate(txs):
        if incoming != m.TxValidationCode.VALID:
            flags.append(incoming)
            continue
        if rwset is None:
            flags.append(m.TxValidationCode.BAD_RWSET)
            continue
        try:
            ns_sets = parse_tx_rwset(rwset)
        except Exception:
            flags.append(m.TxValidationCode.BAD_RWSET)
            continue
        verdict = m.TxValidationCode.VALID
        for ns, kv in ns_sets:
            for read in kv.reads:
                if not validate_kv_read(db, batch, ns, read):
                    verdict = m.TxValidationCode.MVCC_READ_CONFLICT
                    break
            if verdict != m.TxValidationCode.VALID:
                break
            for rq in kv.range_queries_info:
                if not validate_range_query(db, batch, ns, rq):
                    verdict = m.TxValidationCode.PHANTOM_READ_CONFLICT
                    break
            if verdict != m.TxValidationCode.VALID:
                break
        if verdict != m.TxValidationCode.VALID:
            flags.append(verdict)
            continue
        for ns, kv in ns_sets:
            for w in kv.writes:
                if w.is_delete:
                    batch.delete(ns, w.key, (block_num, tx_num))
                else:
                    batch.put(ns, w.key, w.value, (block_num, tx_num))
                tx_writes.append((tx_num, ns, w.key))
            for mw in kv.metadata_writes:
                batch.put_metadata(
                    ns, mw.key,
                    {e.name: e.value for e in mw.entries},
                    (block_num, tx_num))
        flags.append(m.TxValidationCode.VALID)
    return flags, batch, tx_writes
