"""MVCC validation: serial read-set version checks + phantom detection.

(reference: core/ledger/kvledger/txmgmt/validation/validator.go:82
`validateAndPrepareBatch`, `validateKVRead` at :173, range-query
re-execution for phantom reads.)  Runs after signature/policy
validation (which the device batch already decided); this stage is
inherently serial because each transaction's reads must be checked
against the writes of every earlier valid transaction in the same
block — the reference keeps it on one goroutine, we keep it on host.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from fabric_mod_tpu.ledger.rwsetutil import (
    parse_tx_rwset, range_fingerprint, version_tuple)
from fabric_mod_tpu.ledger.statedb import UpdateBatch, VersionedDB
from fabric_mod_tpu.protos import messages as m

Version = Tuple[int, int]


def _read_conflicts(db: VersionedDB, batch: UpdateBatch,
                    ns: str, read: m.KVRead) -> bool:
    """A read conflicts if the key was touched earlier in this block —
    including deletes — or its committed version moved (reference:
    validator.go:173 validateKVRead: any key present in the update
    batch conflicts outright)."""
    if batch.get(ns, read.key) is not None:
        return True
    return db.get_version(ns, read.key) != version_tuple(read.version)


def _combined_range(db: VersionedDB, batch: UpdateBatch,
                    ns: str, start: str, end: str):
    """Merge committed state with the in-block pending batch, key order."""
    pending = {key: val for (n, key), val in batch.updates.items()
               if n == ns and start <= key and (not end or key < end)}
    out = []
    for key, value, ver in db.get_state_range(ns, start, end):
        if key in pending:
            continue                        # overridden by this block
        out.append((key, ver))
    for key, (value, ver) in pending.items():
        if value is not None:
            out.append((key, ver))
    out.sort(key=lambda kv: kv[0])
    return out


def validate_kv_read(db: VersionedDB, batch: UpdateBatch,
                     ns: str, read: m.KVRead) -> bool:
    return not _read_conflicts(db, batch, ns, read)


def validate_range_query(db: VersionedDB, batch: UpdateBatch, ns: str,
                         rq: m.RangeQueryInfo) -> bool:
    results = _combined_range(db, batch, ns, rq.start_key, rq.end_key)
    return range_fingerprint(results) == rq.reads_merkle_hash


def validate_and_prepare_batch(
        txs: List[Tuple[str, Optional[m.TxReadWriteSet], int]],
        db: VersionedDB, block_num: int
) -> Tuple[List[int], UpdateBatch, List[Tuple[int, str, str]]]:
    """Serial MVCC pass over a block.

    `txs` is [(tx_id, rwset | None, incoming_flag)] in block order;
    incoming flags carry upstream verdicts (signature/policy/dup) —
    only VALID transactions are MVCC-checked.  Returns the final
    per-tx validation codes, the state UpdateBatch of the surviving
    writes versioned (block_num, tx_num), and the per-tx write list
    [(tx_num, ns, key)] for the history DB (parsed once here so the
    commit path never re-decodes rwsets).
    """
    flags: List[int] = []
    batch = UpdateBatch()
    tx_writes: List[Tuple[int, str, str]] = []
    for tx_num, (txid, rwset, incoming) in enumerate(txs):
        if incoming != m.TxValidationCode.VALID:
            flags.append(incoming)
            continue
        if rwset is None:
            flags.append(m.TxValidationCode.BAD_RWSET)
            continue
        try:
            ns_sets = parse_tx_rwset(rwset)
        except Exception:
            flags.append(m.TxValidationCode.BAD_RWSET)
            continue
        verdict = m.TxValidationCode.VALID
        for ns, kv in ns_sets:
            for read in kv.reads:
                if not validate_kv_read(db, batch, ns, read):
                    verdict = m.TxValidationCode.MVCC_READ_CONFLICT
                    break
            if verdict != m.TxValidationCode.VALID:
                break
            for rq in kv.range_queries_info:
                if not validate_range_query(db, batch, ns, rq):
                    verdict = m.TxValidationCode.PHANTOM_READ_CONFLICT
                    break
            if verdict != m.TxValidationCode.VALID:
                break
        if verdict != m.TxValidationCode.VALID:
            flags.append(verdict)
            continue
        for ns, kv in ns_sets:
            for w in kv.writes:
                if w.is_delete:
                    batch.delete(ns, w.key, (block_num, tx_num))
                else:
                    batch.put(ns, w.key, w.value, (block_num, tx_num))
                tx_writes.append((tx_num, ns, w.key))
            for mw in kv.metadata_writes:
                batch.put_metadata(
                    ns, mw.key,
                    {e.name: e.value for e in mw.entries},
                    (block_num, tx_num))
        flags.append(m.TxValidationCode.VALID)
    return flags, batch, tx_writes


# ---------------------------------------------------------------------------
# Vectorized MVCC (ISSUE 18, FABRIC_MOD_TPU_VECTOR_MVCC): the serial
# per-key python probes above replaced by one bulk get_versions_many
# call (hash-join over the block's columnar key plane) + numpy version
# compares.  The per-tx loop stays — MVCC is inherently serial in the
# in-block write dependency — but its body collapses to slice
# reductions over precomputed conflict masks.  Rows the batch scanner
# could not prove (fallback txs) are parsed generically and merged
# into the same planes, so the two paths share one verdict engine and
# the flags/batch/tx_writes triple is bit-identical to
# validate_and_prepare_batch by construction of the same check order:
# per ns occurrence, reads (first conflict -> MVCC_READ_CONFLICT) then
# range re-execution (-> PHANTOM_READ_CONFLICT).
# ---------------------------------------------------------------------------

# sentinel rwset marker: this tx's rows live in the columnar planes
COLUMNAR = object()


def vector_mvcc_enabled() -> bool:
    from fabric_mod_tpu.utils import knobs
    return knobs.get_bool("FABRIC_MOD_TPU_VECTOR_MVCC")


def validate_and_prepare_batch_vectorized(
        txs, db, block_num: int, planes
) -> Tuple[List[int], UpdateBatch, List[Tuple[int, str, str]]]:
    """Vectorized twin of :func:`validate_and_prepare_batch`.

    `txs` as the generic pass, except a tx whose rwset is the
    :data:`COLUMNAR` sentinel reads its rows from `planes` (a
    batchdecode.BlockRWSets); any other rwset (fallback rows,
    non-endorser empties) is parsed generically and merged.  One
    `db.get_versions_many` call resolves every committed version the
    block touches; read conflicts become numpy compares against that
    join plus a `touched` bitmap standing in for `batch.get`.
    """
    import numpy as np

    from fabric_mod_tpu import faults
    from fabric_mod_tpu.observability import tracing

    faults.point("peer.mvcc.vector")
    n = len(txs)
    VALID = m.TxValidationCode.VALID

    with tracing.span("mvcc_vector", block=block_num, txs=n):
        # -- gather rows: columnar planes + generically-parsed extras --
        col = np.zeros(n, bool)
        bad_rwset = [False] * n
        g_rtx, g_rnsi, g_rns, g_rkey, g_rver = [], [], [], [], []
        g_wtx, g_wns, g_wkey, g_wdel, g_wval = [], [], [], [], []
        g_qtx, g_qnsi, g_qns, g_qrqi = [], [], [], []
        g_mtx, g_mns, g_mkey, g_ment = [], [], [], []
        for tx_num, (txid, rwset, incoming) in enumerate(txs):
            if incoming != VALID:
                # planes may carry rows for upstream-invalid txs; the
                # per-tx loop below never consumes them
                col[tx_num] = rwset is COLUMNAR
                continue
            if rwset is COLUMNAR:
                col[tx_num] = True
                continue
            if rwset is None:
                bad_rwset[tx_num] = True
                continue
            try:
                ns_sets = parse_tx_rwset(rwset)
            except Exception:
                bad_rwset[tx_num] = True
                continue
            for nsi, (ns, kv) in enumerate(ns_sets):
                for read in kv.reads:
                    g_rtx.append(tx_num)
                    g_rnsi.append(nsi)
                    g_rns.append(ns)
                    g_rkey.append(read.key)
                    g_rver.append(version_tuple(read.version))
                for rq in kv.range_queries_info:
                    g_qtx.append(tx_num)
                    g_qnsi.append(nsi)
                    g_qns.append(ns)
                    g_qrqi.append(rq)
                for w in kv.writes:
                    g_wtx.append(tx_num)
                    g_wns.append(ns)
                    g_wkey.append(w.key)
                    g_wdel.append(bool(w.is_delete))
                    g_wval.append(w.value)
                for mw in kv.metadata_writes:
                    g_mtx.append(tx_num)
                    g_mns.append(ns)
                    g_mkey.append(mw.key)
                    g_ment.append({e.name: e.value for e in mw.entries})

        # -- plane row filter: only sentinel-marked txs' rows ----------
        # commit may route an accepted-body tx generically (e.g. a
        # pvt-bearing tx whose materialized rwset the pvt path needs);
        # its plane rows must not double-count
        def _filter(tx_arr, arrs, lists):
            tx_arr = np.asarray(tx_arr, np.int64)
            if tx_arr.size == 0:
                return tx_arr, arrs, lists
            keep = col[tx_arr]
            if keep.all():
                return tx_arr, arrs, lists
            kl = keep.tolist()
            return (tx_arr[keep],
                    [np.asarray(a)[keep] for a in arrs],
                    [[v for v, k in zip(lst, kl) if k]
                     for lst in lists])

        if planes is not None:
            pr_tx, (pr_nsi, pr_has, pr_vb, pr_vt), (pr_ns, pr_key) = \
                _filter(planes.read_tx,
                        [planes.read_nsi, planes.read_has_ver,
                         planes.read_vb, planes.read_vt],
                        [planes.read_ns, planes.read_key])
            pw_tx, _, (pw_ns, pw_key, pw_del, pw_val) = _filter(
                planes.write_tx, [],
                [planes.write_ns, planes.write_key,
                 planes.write_del, planes.write_val])
            pq_tx, (pq_nsi,), (pq_ns, pq_rqi) = _filter(
                planes.range_tx, [planes.range_nsi],
                [planes.range_ns, planes.range_rqi])
            pm_tx, _, (pm_ns, pm_key, pm_ent) = _filter(
                planes.meta_tx, [],
                [planes.meta_ns, planes.meta_key, planes.meta_entries])
        else:
            e = np.zeros(0, np.int64)
            pr_tx = pw_tx = pq_tx = pm_tx = e
            pr_nsi = pq_nsi = pr_vb = pr_vt = e
            pr_has = np.zeros(0, bool)
            pr_ns = pr_key = pw_ns = pw_key = pw_del = pw_val = []
            pq_ns = pq_rqi = pm_ns = pm_key = pm_ent = []

        # -- hash-join every (ns, key) the block touches ---------------
        key_ids: dict = {}

        def kid(ns, key):
            t = (ns, key)
            got = key_ids.get(t)
            if got is None:
                got = len(key_ids)
                key_ids[t] = got
            return got

        p_rkid = np.fromiter(
            (kid(ns, k) for ns, k in zip(pr_ns, pr_key)),
            np.int64, len(pr_key))
        p_wkid = np.fromiter(
            (kid(ns, k) for ns, k in zip(pw_ns, pw_key)),
            np.int64, len(pw_key))
        g_rkid = [kid(ns, k) for ns, k in zip(g_rns, g_rkey)]
        g_wkid = [kid(ns, k) for ns, k in zip(g_wns, g_wkey)]

        # ONE statedb interface call for the whole block
        committed = db.get_versions_many(list(key_ids.keys()))
        nk = len(committed)
        c_has = np.fromiter((v is not None for v in committed), bool, nk)
        c_vb = np.fromiter((v[0] if v is not None else 0
                            for v in committed), np.int64, nk)
        c_vt = np.fromiter((v[1] if v is not None else 0
                            for v in committed), np.int64, nk)

        # -- static (committed-version) conflict mask per read row -----
        # columnar rows: pure numpy compares against the join
        if p_rkid.size:
            pm_has = c_has[p_rkid]
            p_bad = (pm_has != pr_has) | (
                pm_has & pr_has
                & ((c_vb[p_rkid] != pr_vb) | (c_vt[p_rkid] != pr_vt)))
        else:
            p_bad = np.zeros(0, bool)
        # fallback rows: the generic formula verbatim (their versions
        # can exceed what the scanner's 9-byte varint cap admits)
        g_bad = [committed[k] != v for k, v in zip(g_rkid, g_rver)]

        # -- merge planes + extras into one tx-sorted row set ----------
        def merged(p_arr, g_list, dtype=np.int64):
            if not g_list:
                return np.asarray(p_arr, dtype)
            return np.concatenate(
                [np.asarray(p_arr, dtype), np.asarray(g_list, dtype)])

        def reorder_lists(p_list, g_list, order):
            joined = list(p_list) + g_list
            return [joined[i] for i in order]

        r_tx = merged(pr_tx, g_rtx)
        r_order = np.argsort(r_tx, kind="stable")
        r_tx = r_tx[r_order]
        r_nsi = merged(pr_nsi, g_rnsi)[r_order]
        r_kid = merged(p_rkid, g_rkid)[r_order]
        r_bad = merged(p_bad, g_bad, bool)[r_order]

        w_tx = merged(pw_tx, g_wtx)
        w_order = np.argsort(w_tx, kind="stable")
        w_olist = w_order.tolist()
        w_tx = w_tx[w_order]
        w_kid = merged(p_wkid, g_wkid)[w_order]
        w_ns = reorder_lists(pw_ns, g_wns, w_olist)
        w_key = reorder_lists(pw_key, g_wkey, w_olist)
        w_del = reorder_lists(pw_del, g_wdel, w_olist)
        w_val = reorder_lists(pw_val, g_wval, w_olist)

        q_tx = merged(pq_tx, g_qtx)
        q_order = np.argsort(q_tx, kind="stable")
        q_olist = q_order.tolist()
        q_tx = q_tx[q_order]
        q_nsi = merged(pq_nsi, g_qnsi)[q_order]
        q_ns = reorder_lists(pq_ns, g_qns, q_olist)
        q_rqi = reorder_lists(pq_rqi, g_qrqi, q_olist)

        mt_tx = merged(pm_tx, g_mtx)
        m_order = np.argsort(mt_tx, kind="stable")
        m_olist = m_order.tolist()
        mt_tx = mt_tx[m_order]
        mt_ns = reorder_lists(pm_ns, g_mns, m_olist)
        mt_key = reorder_lists(pm_key, g_mkey, m_olist)
        mt_ent = reorder_lists([dict(en) for en in pm_ent], g_ment,
                               m_olist)

        grid = np.arange(n + 1)
        rb = np.searchsorted(r_tx, grid)
        wb = np.searchsorted(w_tx, grid)
        qb = np.searchsorted(q_tx, grid)
        mb = np.searchsorted(mt_tx, grid)

        # -- the serial verdict loop over slice reductions -------------
        flags: List[int] = []
        batch = UpdateBatch()
        tx_writes: List[Tuple[int, str, str]] = []
        touched = np.zeros(max(nk, 1), bool)

        def walk(lo, hi, qlo, qhi):
            """Generic check order for a tx WITH range queries: per ns
            occurrence (nsi ascending), reads then ranges."""
            ri, qi = lo, qlo
            while ri < hi or qi < qhi:
                if qi >= qhi or (ri < hi and r_nsi[ri] <= q_nsi[qi]):
                    nsi = r_nsi[ri]
                    rj = ri
                    while rj < hi and r_nsi[rj] == nsi:
                        rj += 1
                    if r_bad[ri:rj].any() or touched[r_kid[ri:rj]].any():
                        return m.TxValidationCode.MVCC_READ_CONFLICT
                    ri = rj
                else:
                    nsi = q_nsi[qi]
                while qi < qhi and q_nsi[qi] == nsi:
                    if not validate_range_query(db, batch, q_ns[qi],
                                                q_rqi[qi]):
                        return m.TxValidationCode.PHANTOM_READ_CONFLICT
                    qi += 1
            return VALID

        for tx_num, (txid, rwset, incoming) in enumerate(txs):
            if incoming != VALID:
                flags.append(incoming)
                continue
            if bad_rwset[tx_num]:
                flags.append(m.TxValidationCode.BAD_RWSET)
                continue
            lo, hi = rb[tx_num], rb[tx_num + 1]
            qlo, qhi = qb[tx_num], qb[tx_num + 1]
            if qlo == qhi:
                verdict = VALID
                if lo < hi and (r_bad[lo:hi].any()
                                or touched[r_kid[lo:hi]].any()):
                    verdict = m.TxValidationCode.MVCC_READ_CONFLICT
            else:
                verdict = walk(lo, hi, qlo, qhi)
            if verdict != VALID:
                flags.append(verdict)
                continue
            wlo, whi = wb[tx_num], wb[tx_num + 1]
            for idx in range(wlo, whi):
                ns, key = w_ns[idx], w_key[idx]
                if w_del[idx]:
                    batch.delete(ns, key, (block_num, tx_num))
                else:
                    batch.put(ns, key, w_val[idx], (block_num, tx_num))
                tx_writes.append((tx_num, ns, key))
            if wlo < whi:
                touched[w_kid[wlo:whi]] = True
            for idx in range(mb[tx_num], mb[tx_num + 1]):
                batch.put_metadata(mt_ns[idx], mt_key[idx],
                                   mt_ent[idx], (block_num, tx_num))
            flags.append(VALID)
    return flags, batch, tx_writes
