"""Append-only block file store with index and crash recovery.

(reference: common/ledger/blkstorage/blockfile_mgr.go — rolling block
files with length-prefixed records, a leveldb index by number/hash/
txid, and checkpoint reconstruction by scanning the last file;
blockfile_helper.go crops torn writes.)

Record format per block:  u32 payload_len ‖ payload ‖ sha256(payload)
— the trailing digest makes torn tail writes detectable without a
separate checkpoint file; recovery truncates the file at the last
whole record.  The in-memory index (number -> (file, offset),
txid -> (number, txpos)) is rebuilt by scanning on open, which doubles
as the integrity pass.
"""
from __future__ import annotations

import hashlib
import os
import struct
from typing import Dict, Iterator, List, Optional, Tuple

from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil

_MAX_FILE = 64 * 1024 * 1024


class BlockStoreError(Exception):
    pass


def _tx_ids(block: m.Block) -> List[str]:
    ids = []
    for env in protoutil.get_envelopes(block):
        try:
            ch = protoutil.envelope_channel_header(env)
            ids.append(ch.tx_id)
        except Exception:
            ids.append("")
    return ids


class BlockStore:
    """One channel's block files under `dir_path`."""

    BASE_MARKER = "_base"

    def __init__(self, dir_path: str):
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)
        self._by_num: Dict[int, Tuple[int, int]] = {}    # num -> (file, off)
        self._by_txid: Dict[str, Tuple[int, int]] = {}   # txid -> (num, pos)
        self._height = 0
        self._last_hash = b""
        self._cur_file = 0
        # snapshot-bootstrapped stores begin above 0: blocks before the
        # base are pruned history (reference: kvledger snapshot
        # bootstrap, kv_ledger_provider.go CreateFromSnapshot)
        base = os.path.join(dir_path, self.BASE_MARKER)
        if os.path.exists(base):
            raw = open(base, "rb").read()
            if len(raw) >= 8 + 32:
                self._height = struct.unpack_from("<q", raw, 0)[0]
                self._last_hash = raw[8:40]
        self._load_pruned_txids()
        self._recover()
        self._fh = open(self._file_path(self._cur_file), "ab")

    @classmethod
    def write_base_marker(cls, dir_path: str, height: int,
                          last_hash: bytes) -> None:
        os.makedirs(dir_path, exist_ok=True)
        with open(os.path.join(dir_path, cls.BASE_MARKER), "wb") as f:
            f.write(struct.pack("<q", height))
            f.write(last_hash[:32].ljust(32, b"\x00"))

    PRUNED_TXIDS = "_pruned_txids"
    _PRUNED_LOC = (-1, -1)                 # txid exists; block pruned

    @classmethod
    def write_pruned_txids(cls, dir_path: str, txids) -> None:
        """Seed the txid index of a snapshot-bootstrapped store so
        duplicate detection covers the pruned range (reference: the
        snapshot's txids file import)."""
        os.makedirs(dir_path, exist_ok=True)
        with open(os.path.join(dir_path, cls.PRUNED_TXIDS), "wb") as f:
            for t in txids:
                b = t.encode()
                f.write(struct.pack("<I", len(b)))
                f.write(b)

    def _load_pruned_txids(self) -> None:
        path = os.path.join(self.dir, self.PRUNED_TXIDS)
        if not os.path.exists(path):
            return
        raw = open(path, "rb").read()
        pos = 0
        while pos + 4 <= len(raw):
            (ln,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            self._by_txid.setdefault(raw[pos:pos + ln].decode(),
                                     self._PRUNED_LOC)
            pos += ln

    def all_txids(self):
        return list(self._by_txid)

    # -- file layout -----------------------------------------------------
    def _file_path(self, n: int) -> str:
        return os.path.join(self.dir, f"blockfile_{n:06d}")

    def _files(self) -> List[int]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("blockfile_"):
                out.append(int(name.split("_")[1]))
        return sorted(out)

    # -- recovery scan ---------------------------------------------------
    def _recover(self) -> None:
        files = self._files()
        if not files:
            return
        stopped_at = files[-1]
        for fno in files:
            path = self._file_path(fno)
            raw = open(path, "rb").read()
            pos = 0
            good_end = 0
            while pos + 4 <= len(raw):
                (ln,) = struct.unpack_from("<I", raw, pos)
                end = pos + 4 + ln + 32
                if end > len(raw):
                    break                       # torn tail
                payload = raw[pos + 4:pos + 4 + ln]
                digest = raw[pos + 4 + ln:end]
                if hashlib.sha256(payload).digest() != digest:
                    break                       # corruption: crop here
                block = m.Block.decode(payload)
                num = block.header.number
                if num != self._height:
                    raise BlockStoreError(
                        f"block {num} out of order (height {self._height})")
                self._index_block(block, fno, pos)
                self._height = num + 1
                self._last_hash = protoutil.block_header_hash(block.header)
                pos = end
                good_end = end
            if good_end < len(raw):             # crop torn/corrupt tail
                with open(path, "r+b") as f:
                    f.truncate(good_end)
                stopped_at = fno
                break
        # anything after a cropped file cannot be contiguous: drop it
        for fno in files:
            if fno > stopped_at:
                os.remove(self._file_path(fno))
        self._cur_file = stopped_at

    def _index_block(self, block: m.Block, fno: int, off: int) -> None:
        num = block.header.number
        self._by_num[num] = (fno, off)
        for pos, txid in enumerate(_tx_ids(block)):
            if txid and txid not in self._by_txid:
                self._by_txid[txid] = (num, pos)

    # -- writes ----------------------------------------------------------
    def add_block(self, block: m.Block) -> None:
        num = block.header.number
        if num != self._height:
            raise BlockStoreError(
                f"expected block {self._height}, got {num}")
        if self._height > 0 and block.header.previous_hash != self._last_hash:
            raise BlockStoreError(f"block {num} previous_hash mismatch")
        payload = block.encode()
        if self._fh.tell() > _MAX_FILE:
            self._fh.close()
            self._cur_file += 1
            self._fh = open(self._file_path(self._cur_file), "ab")
        off = self._fh.tell()
        self._fh.write(struct.pack("<I", len(payload)))
        self._fh.write(payload)
        self._fh.write(hashlib.sha256(payload).digest())
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._index_block(block, self._cur_file, off)
        self._height = num + 1
        self._last_hash = protoutil.block_header_hash(block.header)

    # -- reads -----------------------------------------------------------
    @property
    def height(self) -> int:
        return self._height

    @property
    def last_block_hash(self) -> bytes:
        return self._last_hash

    def get_block_by_number(self, num: int) -> Optional[m.Block]:
        loc = self._by_num.get(num)
        if loc is None:
            return None
        fno, off = loc
        with open(self._file_path(fno), "rb") as f:
            f.seek(off)
            (ln,) = struct.unpack("<I", f.read(4))
            return m.Block.decode(f.read(ln))

    def get_block_by_txid(self, txid: str) -> Optional[m.Block]:
        loc = self._by_txid.get(txid)
        if loc is None or loc == self._PRUNED_LOC:
            return None                    # pruned: known txid, no block
        return self.get_block_by_number(loc[0])

    def get_tx_loc(self, txid: str) -> Optional[Tuple[int, int]]:
        return self._by_txid.get(txid)

    def get_tx_by_id(self, txid: str) -> Optional[m.Envelope]:
        loc = self._by_txid.get(txid)
        if loc is None or loc == self._PRUNED_LOC:
            return None
        block = self.get_block_by_number(loc[0])
        return protoutil.get_envelopes(block)[loc[1]]

    def iter_blocks(self, start: int = 0) -> Iterator[m.Block]:
        """Sequential scan through the block files (one open + linear
        read per file, not one open/seek per block).  Snapshot-
        bootstrapped stores have no blocks below their base: the scan
        starts at the first block actually present."""
        if not self._by_num:
            return
        cur_fno = None
        raw = b""
        for num in range(max(start, min(self._by_num)), self._height):
            fno, off = self._by_num[num]
            if fno != cur_fno:
                raw = open(self._file_path(fno), "rb").read()
                cur_fno = fno
            (ln,) = struct.unpack_from("<I", raw, off)
            yield m.Block.decode(raw[off + 4:off + 4 + ln])

    def close(self) -> None:
        self._fh.close()
