"""Operator ledger commands: reset, rollback, rebuild-dbs.

(reference: internal/peer/node/{reset,rollback,rebuild_dbs}.go +
core/ledger/kvledger/rollback.go:16 — offline maintenance run against
a stopped peer's ledger directory.)
"""
from __future__ import annotations

import os
import shutil

from fabric_mod_tpu.ledger.blkstorage import BlockStore


class AdminError(Exception):
    pass


def _require_ledger(ledger_dir: str) -> None:
    if not os.path.isdir(os.path.join(ledger_dir, "chains")):
        raise AdminError(f"{ledger_dir} holds no ledger")


def _bootstrap_base(ledger_dir: str) -> int:
    """Base height of a snapshot-bootstrapped store (0 = full chain)."""
    import struct
    marker = os.path.join(ledger_dir, "chains", BlockStore.BASE_MARKER)
    if not os.path.exists(marker):
        return 0
    raw = open(marker, "rb").read()
    return struct.unpack_from("<q", raw, 0)[0] if len(raw) >= 8 else 0


def rebuild_dbs(ledger_dir: str) -> None:
    """Drop all derived stores (state/history); the next open rebuilds
    them from the block store (reference: rebuild_dbs.go — the ledger
    IS the checkpoint, SURVEY §5.4).  Refused on snapshot-bootstrapped
    ledgers: the pre-snapshot state is NOT derivable from local blocks
    — re-join from a snapshot instead."""
    _require_ledger(ledger_dir)
    if _bootstrap_base(ledger_dir) > 0:
        raise AdminError(
            "ledger was bootstrapped from a snapshot: its state cannot "
            "be rebuilt from local blocks — re-join from a snapshot")
    for sub in ("state", "history"):
        path = os.path.join(ledger_dir, sub)
        if os.path.isdir(path):
            shutil.rmtree(path)
    snap = os.path.join(ledger_dir, "state.snap")
    if os.path.exists(snap):
        os.remove(snap)


# reset is rebuild-dbs in the reference's terms (state from blocks);
# kept as its own name for CLI parity
reset = rebuild_dbs


def rollback(ledger_dir: str, target_block: int) -> None:
    """Truncate the chain to `target_block` (inclusive) and drop the
    derived stores (reference: rollback.go:16 — offline block-store
    rollback + forced reconstruction).  Bootstrapped ledgers cannot
    roll back at all: their state below the tip is not reconstructible
    from local blocks."""
    _require_ledger(ledger_dir)
    if _bootstrap_base(ledger_dir) > 0:
        raise AdminError(
            "ledger was bootstrapped from a snapshot: rollback would "
            "need pre-snapshot blocks that were pruned")
    chains = os.path.join(ledger_dir, "chains")
    store = BlockStore(chains)
    if target_block >= store.height:
        store.close()
        raise AdminError(
            f"target {target_block} >= height {store.height}")
    blocks = [store.get_block_by_number(i)
              for i in range(target_block + 1)]
    if any(b is None for b in blocks):
        store.close()
        raise AdminError("missing blocks: cannot roll back")
    store.close()
    tmp = chains + ".rollback"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    new_store = BlockStore(tmp)
    for b in blocks:
        new_store.add_block(b)
    new_store.close()
    shutil.rmtree(chains)
    os.replace(tmp, chains)
    rebuild_dbs(ledger_dir)
