"""Chaincode lifecycle events + historical collection configs.

(reference: core/ledger/cceventmgmt/mgr.go — listeners fired when a
chaincode definition commits, used e.g. to create state-DB indexes —
and core/ledger/confighistory/mgr.go — the retriever answering "what
was this chaincode's collection config as of block N", which private
data reconciliation needs when configs changed since the data's
block.)

One module covers both because they watch the same signal: committed
writes to the lifecycle namespace.  KvLedger calls
`handle_block_writes` from its commit AND recovery-replay paths, so
the file-backed history self-heals from the block store the same way
state does; records are idempotent per (block, namespace).
"""
from __future__ import annotations

import base64
import json
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.observability.logging import get_logger
from fabric_mod_tpu.concurrency.locks import RegisteredLock

log = get_logger("ledger.confighistory")

LIFECYCLE_NS = "_lifecycle"


class ChaincodeDefinitionEvent:
    """What listeners receive (reference: cceventmgmt's
    ChaincodeDefinition + deploy callback)."""

    __slots__ = ("name", "version", "sequence", "collections",
                 "block_num")

    def __init__(self, name: str, version: str, sequence: int,
                 collections: bytes, block_num: int):
        self.name = name
        self.version = version
        self.sequence = sequence
        self.collections = collections
        self.block_num = block_num


class ConfigHistoryManager:
    """Records every committed (block, chaincode, collection-config)
    and answers most-recent-below queries; append-only JSONL file so
    reopen is O(history), not O(chain).

    (reference: confighistory/mgr.go — the compositeKV store keyed by
    (ns, blockNum) with reverse scans.)"""

    SP_EVERY = 256                       # savepoint persistence cadence

    def __init__(self, path: Optional[str] = None):
        self._path = path
        self._since_sp_write = 0
        self._lock = RegisteredLock("ledger.confighistory._lock")
        # ns -> sorted [(block_num, collections bytes)]
        self._by_ns: Dict[str, List[Tuple[int, bytes]]] = {}
        self._listeners: List[Callable] = []
        # last block OFFERED (not merely recorded): the ledger's
        # recovery floor — blocks above it must be replayed through
        # handle_block_writes or definitions would be lost to a crash
        # between state commit and our write
        self.savepoint = -1
        if path and os.path.exists(path):
            good_end = 0
            last_block = -1
            with open(path, "rb") as f:
                data = f.read()
            for line in data.splitlines(keepends=True):
                try:
                    rec = json.loads(line)
                    self._insert(rec["ns"], rec["block"],
                                 base64.b64decode(rec["collections"]))
                    last_block = max(last_block, rec["block"])
                except Exception:
                    break                  # torn tail: crop below
                good_end += len(line)
            if good_end < len(data):
                with open(path, "r+b") as f:
                    f.truncate(good_end)
            sp_path = path + ".sp"
            sp = -1
            if os.path.exists(sp_path):
                try:
                    sp = int(open(sp_path).read())
                except Exception:
                    sp = -1
            # a torn record invalidates the persisted savepoint: fall
            # back to the last intact record so recovery re-offers the
            # rest of the chain
            self.savepoint = (min(sp, last_block)
                              if good_end < len(data) else sp)

    # -- listeners (reference: cceventmgmt.Register) ----------------------
    def register_listener(self, cb: Callable) -> None:
        """cb(ChaincodeDefinitionEvent) fires on every committed
        definition (deploy/upgrade)."""
        self._listeners.append(cb)

    # -- ingestion --------------------------------------------------------
    def _insert(self, ns: str, block_num: int, collections: bytes) -> None:
        lst = self._by_ns.setdefault(ns, [])
        if lst and lst[-1][0] == block_num:
            lst[-1] = (block_num, collections)
        else:
            lst.append((block_num, collections))

    def handle_block_writes(self, block_num: int,
                            writes: List[Tuple[str, str, Optional[bytes]]]
                            ) -> None:
        """Scan one committed block's (ns, key, value) writes for
        lifecycle definitions; record configs + fire listeners."""
        events = []
        with self._lock:
            if block_num <= self.savepoint:
                return                     # replay of an offered block
            for ns, key, value in writes:
                if ns != LIFECYCLE_NS or value is None:
                    continue
                if not key.startswith("namespaces/") or "/" in \
                        key[len("namespaces/"):]:
                    continue               # only the definition records
                cc_name = key[len("namespaces/"):]
                try:
                    d = m.ChaincodeDefinition.decode(value)
                except Exception:
                    continue
                known = self._by_ns.get(cc_name, [])
                if known and known[-1][0] >= block_num:
                    continue               # replay of a recorded block
                self._insert(cc_name, block_num, d.collections)
                if self._path:
                    with open(self._path, "a") as f:
                        f.write(json.dumps({
                            "ns": cc_name, "block": block_num,
                            "collections": base64.b64encode(
                                d.collections).decode()}) + "\n")
                events.append(ChaincodeDefinitionEvent(
                    cc_name, d.version, d.sequence, d.collections,
                    block_num))
            self.savepoint = block_num
            # persist the savepoint only when a record landed or every
            # SP_EVERY blocks: the commit hot path must not pay a file
            # rename per block; a stale savepoint merely replays
            # (idempotent), it never loses records
            self._since_sp_write += 1
            if self._path and (events
                               or self._since_sp_write >= self.SP_EVERY):
                self._since_sp_write = 0
                tmp = self._path + ".sp.tmp"
                with open(tmp, "w") as f:
                    f.write(str(block_num))
                os.replace(tmp, self._path + ".sp")
        for ev in events:
            for cb in self._listeners:
                try:
                    cb(ev)
                except Exception as e:     # listeners must not wedge commit
                    log.debug("config-history listener raised: "
                              "%r", e)

    # -- queries (reference: confighistory retriever) --------------------
    def most_recent_collection_config_below(
            self, ns: str, block_num: int
            ) -> Optional[Tuple[int, m.CollectionConfigPackage]]:
        """The collection config in force for data written at
        `block_num`: the newest definition committed STRICTLY below
        it.  None when no definition predates the block."""
        with self._lock:
            lst = self._by_ns.get(ns, [])
            for bn, raw in reversed(lst):
                if bn < block_num:
                    if not raw:
                        return None
                    try:
                        return bn, m.CollectionConfigPackage.decode(raw)
                    except Exception:
                        return None
        return None

    def collection_config_history(self, ns: str
                                  ) -> List[Tuple[int, bytes]]:
        with self._lock:
            return list(self._by_ns.get(ns, []))
