"""Channel-sharded scale-out: horizontal placement of channels on
device-mesh slices behind one shared cross-channel verify service.

PAPER.md's L3 makes the channel the natural shard unit — one ledger,
one policy universe, one commit stream per channel — and every PR up
to 12 made ONE channel's commit path faster.  This package is the
layer that turns K chips x N channels into aggregate throughput:

* :mod:`shardmap` — deterministic channel -> mesh-slice placement
  with least-loaded assignment and bounded rebalance on join/leave;
* :mod:`router` — :class:`ChannelShardRouter`, which pins each
  channel's :class:`~fabric_mod_tpu.peer.commitpipe.PipelinedCommitter`
  and tensor-policy sessions (via the slice verifier its validator
  stages against) to its slice;
* :mod:`verifyservice` — :class:`CrossChannelVerifyService`, the
  generalization of :class:`~fabric_mod_tpu.bccsp.tpu.
  BatchingVerifyService` from one program to a service: ONE flusher
  coalescing VerifyItems from every channel, split at flush time into
  per-slice fused dispatches, tagged futures routing verdicts back
  per channel — small channels ride big channels' batches instead of
  each paying its own dispatch latency;
* :mod:`multihost` — the jax.distributed-shaped multi-host spec
  (documented + stubbed behind FABRIC_MOD_TPU_SHARDS).
"""
from fabric_mod_tpu.sharding.shardmap import ShardMap          # noqa: F401
from fabric_mod_tpu.sharding.router import (                   # noqa: F401
    ChannelShardRouter, ChannelVerifyHandle)
from fabric_mod_tpu.sharding.verifyservice import (            # noqa: F401
    CrossChannelVerifyService)
from fabric_mod_tpu.sharding.multihost import multihost_spec   # noqa: F401
