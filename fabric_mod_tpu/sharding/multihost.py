"""Multi-host sharding spec: jax.distributed-shaped process groups,
the SAME NamedShardings, a host-side router only.  Documented and
STUBBED behind FABRIC_MOD_TPU_SHARDS — single-host slice meshes are
the shipping path; this module pins down what multi-host adds so the
day hardware with >1 host is reachable nothing has to be redesigned.

The design (why nothing above this layer changes):

* **Devices.** Each host process runs ``jax.distributed.initialize``
  and sees the global device list; ``parallel.slice_meshes`` carves
  the GLOBAL list exactly as it carves a local one — a slice may span
  hosts (its limb/flag NamedShardings are host-agnostic; GSPMD
  inserts the cross-host collectives) or sit entirely on one host
  (the preferred placement: a channel's verify gather then never
  leaves the host's ICI domain).  ``FABRIC_MOD_TPU_SHARD_HOSTS``
  declares the expected process count so a misconfigured fleet fails
  loudly at spec time instead of hanging in a collective.
* **The router stays host-side and per-process.**  Every host runs
  its own ChannelShardRouter over the slices whose devices it
  PREFERS (process_index-partitioned round robin below); channel
  placement is deterministic (ShardMap is a pure function of the
  join/leave sequence), so all hosts agree on the map without a
  coordination service.  Blocks arrive per channel via gossip/deliver
  exactly as on one host — ordering is the orderer's job, not the
  mesh's.
* **The shared verify service stays per-host.**  Cross-channel
  coalescing is a HOST-side latency optimization (one flusher per
  process); items never need to cross hosts to batch, because every
  host only verifies traffic it already holds.

What is genuinely NOT built yet (the stub below raises): the
jax.distributed bring-up itself (coordinator address plumbing,
restart semantics under the soak harness's churn) and multi-host
placement of a single slice's fused program on real ICI.  Both are
measurement-gated — the scale curve in MULTICHIP_r*.json decides
whether cross-host slices are ever worth their collectives.
"""
from __future__ import annotations

from typing import Dict, List

from fabric_mod_tpu.utils import knobs


def multihost_spec(n_hosts: int = None, n_slices: int = None) -> Dict:
    """The process-group spec the multi-host bring-up will follow:
    pure arithmetic (no jax — DEVICE counts are a bring-up-time
    reality this spec deliberately does not guess at), so tests pin
    the shape today.

    Returns {hosts, slices, slices_per_host, process_groups:
    [{process_index, slices: [...]}], shardings, router} — slices are
    round-robin partitioned over hosts by preference; NamedShardings
    are unchanged by design (the whole point)."""
    if n_hosts is None:
        n_hosts = max(1, knobs.get_int("FABRIC_MOD_TPU_SHARD_HOSTS"))
    if n_slices is None:
        n_slices = max(1, knobs.get_int("FABRIC_MOD_TPU_SHARDS", 1))
    if n_slices % n_hosts != 0:
        raise ValueError(
            f"{n_slices} slices do not partition over {n_hosts} hosts "
            f"evenly — pad the slice count, not the fleet")
    groups: List[Dict] = []
    for p in range(n_hosts):
        groups.append({
            "process_index": p,
            "slices": list(range(p, n_slices, n_hosts)),
        })
    return {
        "hosts": n_hosts,
        "slices": n_slices,
        "slices_per_host": n_slices // n_hosts,
        "process_groups": groups,
        # the load-bearing invariants, recorded in the artifact so a
        # future bring-up can diff its reality against the spec
        "shardings": "identical NamedShardings (P(None,'dp') limbs, "
                     "P('dp') flags) over the global mesh",
        "router": "host-side, per-process, deterministic ShardMap",
    }


def initialize_multihost() -> None:
    """The bring-up stub: raises until the multi-host path is built.
    Gated on FABRIC_MOD_TPU_SHARD_HOSTS > 1 so single-host callers
    (everything today) pass through as a no-op."""
    n_hosts = knobs.get_int("FABRIC_MOD_TPU_SHARD_HOSTS")
    if n_hosts <= 1:
        return
    raise NotImplementedError(
        "multi-host sharding is specified (sharding/multihost.py) but "
        "not yet brought up: jax.distributed.initialize plumbing and "
        "churn-safe restart semantics land with the first multi-host "
        f"hardware window (asked for {n_hosts} hosts)")
