"""The shared cross-channel verify front door: one flusher, per-slice
fused dispatches, tagged futures routing verdicts back per channel.

`parallel.fused_verify_shardings` generalized from one program to a
SERVICE: the base :class:`BatchingVerifyService` already coalesces
concurrent submitters into deadline/size-batched dispatches against
ONE verifier; this subclass keeps that single flusher (one deadline
clock, one coalescing window for the whole process) and splits each
coalesced batch at flush time into per-slice groups — each group one
fused dispatch on its slice's mesh via that slice's verifier.  The
submit tag (the channel id) picks the group through the shard map, so

* a small channel's stray verifies ride the same flush window as a
  big channel's storm instead of each paying its own dispatch
  latency (the whole point of sharing the front door), and
* per-slice groups FAIL independently: a marshal error or injected
  fault on channel A's group completes only A's futures with the
  error — channel B's riders in the same flush window resolve
  normally (the isolation contract the sharding tests pin).

Whole-block batches do NOT come through here: the router pins each
channel's validator to its slice verifier directly (they are already
full fused dispatches; coalescing them would only serialize slices).
This service is the small-verify lane: gossip block verifies, config
signature sets, broadcast filters.
"""
from __future__ import annotations

from typing import Callable, Dict, Sequence

from fabric_mod_tpu import faults
from fabric_mod_tpu.bccsp.api import VerifyItem
from fabric_mod_tpu.bccsp.tpu import (BatchingVerifyService,
                                      _DEADLINE_KNOB)
from fabric_mod_tpu.observability import tracing
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)

_GROUPS_OPTS = MetricOpts(
    "fabric", "sharding", "dispatch_groups_total",
    help="Per-slice dispatch groups cut from coalesced cross-channel "
         "flush batches.", label_names=("slice",))


class _SliceLane:
    """One slice's dispatch lane: the slice verifier plus the chaos
    seam and span every routed group passes through.  Kept verifier-
    shaped so the base flusher dispatches it like any verifier."""

    def __init__(self, index: int, verifier):
        self.index = index
        self.verifier = verifier
        self._m_groups = default_provider().counter(
            _GROUPS_OPTS).with_labels(str(index))

    def verify_many_async(self, items: Sequence[VerifyItem]):
        # chaos seam: an injected failure here kills exactly one
        # slice-group of one flush — the cross-channel isolation
        # contract under test (other channels' futures must resolve)
        faults.point("sharding.dispatch")
        self._m_groups.add(1)
        with tracing.span("shard.dispatch", slice=self.index,
                          items=len(items)):
            fn = getattr(self.verifier, "verify_many_async", None)
            if fn is not None:
                return fn(items)
            mask = self.verifier.verify_many(items)
            return lambda: mask


class CrossChannelVerifyService(BatchingVerifyService):
    """BatchingVerifyService over a DICT of per-slice verifiers.

    `verifiers`: slice index -> verifier (TpuVerifier pinned to that
    slice's mesh in production; any verify_many[_async]-shaped object
    in tests/host mode).  `shard_of(tag) -> slice`: the placement
    lookup (ShardMap.slice_of with a default) — it must ACCEPT
    unknown tags (route them to a default slice) rather than raise,
    because one stray tag must never fail a whole coalesced batch.
    Untagged submits route to `default_slice`.

    Verifier LIFECYCLE stays with the caller (the router): slices are
    shared with the per-channel block path, so close() here tears
    down only the flusher/resolver threads.
    """

    def __init__(self, verifiers: Dict[int, object],
                 shard_of: Callable[[object], int],
                 default_slice: int = 0, **kwargs):
        if not verifiers:
            raise ValueError("need at least one slice verifier")
        if default_slice not in verifiers:
            raise ValueError(
                f"default slice {default_slice} has no verifier")
        self._lanes = {i: _SliceLane(i, v)
                       for i, v in verifiers.items()}
        self._shard_of = shard_of
        self._default_slice = default_slice
        super().__init__(verifier=self._lanes[default_slice], **kwargs)
        # the base class would close a verifier it built; ours are the
        # router's (shared with the block path) — never owned here
        self._owns_verifier = False

    # -- per-channel surface ---------------------------------------------
    def submit_for(self, channel_id: str, item: VerifyItem):
        return self.submit(item, tag=channel_id)

    def verify_many_for(self, channel_id: str,
                        items: Sequence[VerifyItem],
                        timeout=_DEADLINE_KNOB):
        return self.verify_many(items, timeout=timeout, tag=channel_id)

    # -- the routed flush -------------------------------------------------
    def _route_batch(self, batch):
        """Group one coalesced batch by mesh slice.  Slice order is
        sorted so the dispatch order (and with it the resolver's
        completion order) is deterministic for a given batch."""
        groups: Dict[int, list] = {}
        for item, fut in batch:
            tag = getattr(fut, "_fmt_shard_tag", None)
            s = (self._default_slice if tag is None
                 else self._shard_of(tag))
            if s not in self._lanes:
                s = self._default_slice
            groups.setdefault(s, []).append((item, fut))
        return [(self._lanes[s], groups[s]) for s in sorted(groups)]
