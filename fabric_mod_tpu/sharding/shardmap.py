"""Channel -> mesh-slice placement: deterministic, least-loaded,
rebalancing on leave.

Pure bookkeeping on purpose — no locks, no engines, no jax.  The
router (sharding/router.py) owns serialization and the expensive
consequences of a placement decision (pipeline rebuilds, verifier
pinning); this map only answers "which slice does channel X live on"
and "which channels must MOVE now that the population changed", so
the policy is unit-testable as a function of the join/leave sequence.

Placement policy:

* `assign` puts a new channel on the least-loaded slice (ties break
  to the lowest slice index) — with equal-size slices this is the
  balanced-number-of-channels heuristic; per-channel WEIGHTS (traffic
  share) are a later refinement the interface leaves room for.
* `release` frees the slot and, when rebalancing is enabled, returns
  a bounded MOVE PLAN: the newest channels of overloaded slices move
  to underloaded ones until the spread (max load - min load) is <= 1.
  Newest-first is deliberate: the channel placed last has the least
  accumulated device-side state (compile cache residency, verdict
  memo locality), so it is the cheapest to migrate.

Determinism contract: the same join/leave sequence always produces
the same placement and the same move plans — a rebalance is
replayable, which the soak harness's seeded churn relies on.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# (channel_id, from_slice, to_slice) — the router executes these
Move = Tuple[str, int, int]


class ShardMap:
    """Bookkeeping for N channels over `n_slices` mesh slices."""

    def __init__(self, n_slices: int, rebalance: bool = True):
        if n_slices <= 0:
            raise ValueError("n_slices must be positive")
        self.n_slices = n_slices
        self.rebalance = rebalance
        # insertion-ordered per slice: the tail is the newest (the
        # cheapest to move)
        self._slices: List[List[str]] = [[] for _ in range(n_slices)]
        self._of: Dict[str, int] = {}

    # -- queries ----------------------------------------------------------
    def __len__(self) -> int:
        return len(self._of)

    def __contains__(self, channel_id: str) -> bool:
        return channel_id in self._of

    def slice_of(self, channel_id: str,
                 default: Optional[int] = None) -> int:
        """The slice a channel lives on; `default` (when given) is
        returned for unknown channels — the cross-channel verify
        service routes untagged/foreign riders there instead of
        failing a whole coalesced batch on one stray tag."""
        got = self._of.get(channel_id)
        if got is None:
            if default is None:
                raise KeyError(f"unplaced channel {channel_id!r}")
            return default
        return got

    def channels(self, slice_index: int) -> List[str]:
        return list(self._slices[slice_index])

    def loads(self) -> List[int]:
        """Channels per slice, by slice index (the balance view the
        metrics gauge exports)."""
        return [len(s) for s in self._slices]

    # -- mutation ---------------------------------------------------------
    def assign(self, channel_id: str) -> int:
        """Place a channel (idempotent: an already-placed channel
        keeps its slice) on the least-loaded slice."""
        got = self._of.get(channel_id)
        if got is not None:
            return got
        loads = self.loads()
        target = loads.index(min(loads))
        self._slices[target].append(channel_id)
        self._of[channel_id] = target
        return target

    def release(self, channel_id: str) -> List[Move]:
        """Remove a channel; returns the move plan restoring balance
        (empty when rebalancing is off or the spread is already
        <= 1).  Unknown channels are a no-op."""
        got = self._of.pop(channel_id, None)
        if got is None:
            return []
        self._slices[got].remove(channel_id)
        if not self.rebalance:
            return []
        return self._plan_moves()

    def _plan_moves(self) -> List[Move]:
        """Move newest channels from overloaded to underloaded slices
        until the spread is <= 1; apply each move to the map as it is
        planned so the plan the router executes matches the state the
        map now describes."""
        moves: List[Move] = []
        while True:
            loads = self.loads()
            hi, lo = max(loads), min(loads)
            if hi - lo <= 1:
                return moves
            src = loads.index(hi)
            dst = loads.index(lo)
            cid = self._slices[src].pop()        # newest first
            self._slices[dst].append(cid)
            self._of[cid] = dst
            moves.append((cid, src, dst))
