"""ChannelShardRouter: pin N channels' commit engines to mesh slices
behind one shared cross-channel verify service.

The router is the ONLY stateful layer of the sharding subsystem; it
composes the three pieces:

* a :class:`~fabric_mod_tpu.sharding.shardmap.ShardMap` deciding
  which slice each channel lives on (least-loaded, rebalance on
  leave);
* one verifier PER SLICE (production: ``TpuVerifier(mesh=slice)``
  over ``parallel.slice_meshes``; host mode: whatever
  `verifier_factory` returns) — each channel's validator stages its
  whole-block fused dispatches (and with them its tensor-policy
  sessions, policy/tensorpolicy.py) against its slice's verifier, so
  N channels' block programs run side by side on disjoint devices;
* one :class:`~fabric_mod_tpu.sharding.verifyservice.
  CrossChannelVerifyService` over those verifiers — the shared
  small-verify front door every channel's gossip/MCS/config checks
  coalesce through;
* one :class:`~fabric_mod_tpu.peer.commitpipe.PipelinedCommitter`
  per channel, consumer-labeled by slice, with the peer.Channel
  rebuild-on-poison contract: a failed pipe surfaces its error to
  the caller that hit it, then the next `pipeline_for` drains the
  corpse and rebuilds from the committed height — one bad block
  never bricks a channel, and (the sharding-specific half) never
  touches any OTHER channel's pipe or the shared flusher.

Channel join/leave goes through `add_channel`/`remove_channel`; a
leave may return the map's rebalance plan, which the router executes
by draining the moving channel's pipe and rebuilding it pinned to the
new slice (its verify handle re-resolves the slice verifier on every
call, so in-flight small verifies need no coordination).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from fabric_mod_tpu.bccsp.api import VerifyItem
from fabric_mod_tpu.concurrency import RegisteredLock
from fabric_mod_tpu.observability.logging import get_logger
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.sharding.shardmap import ShardMap
from fabric_mod_tpu.sharding.verifyservice import CrossChannelVerifyService
from fabric_mod_tpu.utils import knobs

log = get_logger("sharding.router")

_CHANNELS_OPTS = MetricOpts(
    "fabric", "sharding", "channels",
    help="Channels currently placed on each mesh slice.",
    label_names=("slice",))
_MOVES_OPTS = MetricOpts(
    "fabric", "sharding", "rebalance_moves_total",
    help="Channels moved between slices by leave-time rebalancing.")
_REBUILD_OPTS = MetricOpts(
    "fabric", "sharding", "pipe_rebuilds_total",
    help="Poisoned per-channel commit pipelines discarded and rebuilt "
         "by the router (the channel-scoped recovery event; the "
         "shared verify service is untouched).")


def shard_count(default: int = 0) -> int:
    """The FABRIC_MOD_TPU_SHARDS knob: mesh slices the router carves;
    0/unset = sharding disabled (single-slice behavior)."""
    return max(0, knobs.get_int("FABRIC_MOD_TPU_SHARDS", default))


def shard_depth() -> int:
    """Per-channel commit-pipeline depth under the router: the
    FABRIC_MOD_TPU_SHARD_DEPTH knob, falling back to
    FABRIC_MOD_TPU_COMMIT_PIPELINE, and to depth 2 (the deliver
    client's default) when both are unset — floor 1 either way:
    router-bound channels always pipeline; serial behavior is depth
    1, not 'no engine'."""
    d = knobs.get_int("FABRIC_MOD_TPU_SHARD_DEPTH")
    if d <= 0:
        from fabric_mod_tpu.peer.commitpipe import pipeline_depth
        d = pipeline_depth(2)
    return max(1, d)


class ChannelVerifyHandle:
    """The per-channel verifier facade a Channel/TxValidator holds.

    Whole-block lanes (`verify_many_async`, `verify_many_fused_async`
    — the validator's staging seams, and with them the tensor-policy
    sessions) go STRAIGHT to the channel's slice verifier: they are
    already full fused dispatches, pinned to the slice mesh.  The
    small-verify lane (`verify_many`, `submit` — MCS block checks,
    config signature sets) rides the SHARED cross-channel service,
    tagged, so it coalesces with every other channel's traffic.

    Slice resolution is per-call through the router, so a rebalance
    move retargets the handle with no handshake.
    """

    def __init__(self, router: "ChannelShardRouter", channel_id: str):
        self._router = router
        self.channel_id = channel_id

    @property
    def slice_index(self) -> int:
        return self._router.slice_of(self.channel_id)

    def _slice_verifier(self):
        return self._router.slice_verifier(self.channel_id)

    # -- whole-block lane (slice-pinned) ---------------------------------
    def verify_many_async(self, items: Sequence[VerifyItem]):
        return self._slice_verifier().verify_many_async(items)

    def verify_many_fused_async(self, items: Sequence[VerifyItem]):
        v = self._slice_verifier()
        fn = getattr(v, "verify_many_fused_async", None)
        if fn is not None:
            return fn(items)
        return v.verify_many_async(items)

    # -- small-verify lane (shared, coalesced, tagged) -------------------
    def verify_many(self, items: Sequence[VerifyItem]):
        return self._router.service.verify_many_for(
            self.channel_id, items)

    def submit(self, item: VerifyItem):
        return self._router.service.submit_for(self.channel_id, item)


class _Binding:
    __slots__ = ("channel_id", "target", "handle", "pipe",
                 "rebuild_lock")

    def __init__(self, channel_id: str, handle: ChannelVerifyHandle):
        self.channel_id = channel_id
        self.target = None                  # stage_block/commit_staged
        self.handle = handle
        self.pipe = None
        self.rebuild_lock = RegisteredLock(
            f"sharding.rebuild[{channel_id}]")


class ChannelShardRouter:
    """Placement + aggregation over `n_slices` mesh slices.

    `meshes`: per-slice meshes (`parallel.slice_meshes(n)`), or None
    for HOST mode (no jax — tests, CPU soak, TPU-less deployments);
    `verifier_factory(slice_index, mesh)` builds each slice's
    verifier (default: ``TpuVerifier(mesh=mesh)``).  The router owns
    the verifiers it builds and the shared service; `close()` tears
    all of it down after draining every channel's pipe.
    """

    def __init__(self, n_slices: Optional[int] = None, meshes=None,
                 verifier_factory: Optional[Callable] = None,
                 depth: Optional[int] = None, rebalance: bool = True,
                 max_batch: int = 2048, deadline_s: float = 0.002):
        if n_slices is None:
            n_slices = max(1, shard_count())
        if meshes is not None and len(meshes) != n_slices:
            raise ValueError(
                f"{len(meshes)} meshes for {n_slices} slices")
        self.map = ShardMap(n_slices, rebalance=rebalance)
        self._depth = depth
        self._lock = RegisteredLock("sharding.router")
        self._bindings: Dict[str, _Binding] = {}
        self._closed = False
        if verifier_factory is None:
            from fabric_mod_tpu.bccsp.tpu import TpuVerifier
            verifier_factory = lambda i, mesh: TpuVerifier(mesh=mesh)
        self.verifiers = {
            i: verifier_factory(i, meshes[i] if meshes else None)
            for i in range(n_slices)}
        self.service = CrossChannelVerifyService(
            self.verifiers,
            lambda tag: self.map.slice_of(tag, default=0),
            max_batch=max_batch, deadline_s=deadline_s)
        prov = default_provider()
        self._m_channels = prov.gauge(_CHANNELS_OPTS)
        self._m_moves = prov.counter(_MOVES_OPTS)
        self._m_rebuilds = prov.counter(_REBUILD_OPTS)

    @property
    def n_slices(self) -> int:
        return self.map.n_slices

    # -- placement --------------------------------------------------------
    def slice_of(self, channel_id: str) -> int:
        with self._lock:
            return self.map.slice_of(channel_id)

    def slice_verifier(self, channel_id: str):
        return self.verifiers[self.slice_of(channel_id)]

    def _export_loads(self) -> None:
        for i, n in enumerate(self.map.loads()):
            self._m_channels.with_labels(str(i)).set(n)

    def add_channel(self, channel_id: str,
                    target=None) -> ChannelVerifyHandle:
        """Place a channel and return its verify handle.  `target`
        (stage_block/commit_staged/.ledger — a peer.Channel or a
        ValidatorCommitTarget) may be bound now or later via
        `bind_target` (a Channel needs the handle BEFORE it can be
        constructed)."""
        with self._lock:
            if self._closed:
                raise RuntimeError("shard router is closed")
            b = self._bindings.get(channel_id)
            if b is None:
                self.map.assign(channel_id)
                b = _Binding(channel_id,
                             ChannelVerifyHandle(self, channel_id))
                self._bindings[channel_id] = b
                self._export_loads()
            if target is not None:
                b.target = target
            return b.handle

    def bind_target(self, channel_id: str, target) -> None:
        with self._lock:
            self._bindings[channel_id].target = target

    def remove_channel(self, channel_id: str,
                       timeout_s: Optional[float] = None) -> List:
        """Drain + close the channel's pipe, free its slot, and
        execute the map's rebalance plan (each moved channel's pipe
        drains and rebuilds pinned to its new slice).  Returns the
        executed move list."""
        with self._lock:
            b = self._bindings.pop(channel_id, None)
            if b is None:
                return []
            moves = self.map.release(channel_id)
            self._export_loads()
        if b.pipe is not None:
            b.pipe.close(timeout_s)
        for cid, src, dst in moves:
            with self._lock:
                mb = self._bindings.get(cid)
            if mb is not None:
                # under the channel's rebuild lock: a concurrent
                # pipeline_for(cid) must not build a fresh engine
                # while the old one is still draining into the same
                # ledger — two engines never run against one ledger
                with mb.rebuild_lock:
                    with self._lock:
                        old, mb.pipe = mb.pipe, None
                    if old is not None:
                        old.close(timeout_s)   # drain on the OLD slice
            self._m_moves.add(1)
            log.info("sharding: channel %s moved slice %d -> %d",
                     cid, src, dst)
        return moves

    # -- per-channel commit engines --------------------------------------
    def pipeline_for(self, channel_id: str):
        """The channel's slice-pinned PipelinedCommitter, with the
        peer.Channel rebuild-on-poison contract: a healthy pipe is
        returned lock-free-ish; a poisoned/closed one is drained and
        replaced (two engines never run against one ledger at once).
        """
        def healthy():
            with self._lock:
                b = self._bindings.get(channel_id)
                if b is None:
                    raise KeyError(f"unplaced channel {channel_id!r}")
                pipe = b.pipe
            return b, (pipe if (pipe is not None and pipe.error is None
                                and not pipe.closed) else None)
        b, pipe = healthy()
        if pipe is not None:
            return pipe
        with b.rebuild_lock:
            b, pipe = healthy()
            if pipe is not None:
                return pipe                # another caller rebuilt
            with self._lock:
                if self._closed:
                    # a submit racing close(): rebuilding here would
                    # spawn workers over torn-down verifiers that
                    # nothing would ever join
                    raise RuntimeError("shard router is closed")
            if b.target is None:
                raise RuntimeError(
                    f"channel {channel_id!r} has no commit target")
            with self._lock:
                old, b.pipe = b.pipe, None
            if old is not None:
                old.close()                # drain the poisoned engine
                self._m_rebuilds.add(1)
            from fabric_mod_tpu.peer.commitpipe import PipelinedCommitter
            depth = self._depth if self._depth is not None \
                else shard_depth()
            with self._lock:
                slice_idx = self.map.slice_of(channel_id, 0)
            pipe = PipelinedCommitter(
                b.target, depth=depth,
                consumer=f"shard{slice_idx}")
            with self._lock:
                b.pipe = pipe
            return pipe

    def submit_block(self, channel_id: str, block) -> None:
        self.pipeline_for(channel_id).submit(block)

    def store_block(self, channel_id: str, block):
        """Synchronous commit through the channel's pipe, with the
        one-retry-through-a-fresh-pipe arbitration of
        peer.Channel.store_block (an inherited poison fails over; an
        own-error block fails again with its real cause)."""
        pipe = self.pipeline_for(channel_id)
        try:
            return pipe.store_block(block)
        except Exception:
            retry = self.pipeline_for(channel_id)
            if retry is pipe:
                raise
            return retry.store_block(block)

    # -- lifecycle --------------------------------------------------------
    def flush(self, timeout_s: Optional[float] = None) -> bool:
        ok = True
        with self._lock:
            pipes = [b.pipe for b in self._bindings.values()
                     if b.pipe is not None]
        for p in pipes:
            ok = p.flush(timeout_s) and ok
        return ok

    def close(self, timeout_s: Optional[float] = None) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            bindings = list(self._bindings.values())
        for b in bindings:
            # under the binding's rebuild lock: a pipeline_for rebuild
            # racing this close either finished (its fresh pipe is in
            # b.pipe and gets closed here) or blocks until we release
            # and then sees _closed and raises — no engine can be
            # built over the torn-down service/verifiers below
            with b.rebuild_lock:
                pipe, b.pipe = b.pipe, None
            if pipe is not None:
                try:
                    pipe.close(timeout_s)
                except Exception as e:     # noqa: BLE001
                    # teardown best-effort: the pipe's error already
                    # surfaced to its callers; log and keep closing
                    # the rest of the fleet
                    log.warning("sharding: pipe close for %s "
                                "raised: %r", b.channel_id, e)
        self.service.close()
        for v in self.verifiers.values():
            vclose = getattr(v, "close", None)
            if vclose is not None:
                vclose()
