#!/usr/bin/env bash
# CPU smoke target for the verify pipeline: the mixed-ladder verdict
# differential (incl. the fused-hash raw-vs-digest check) plus the
# fused hash->verify A/B, both on the CPU backend with a small batch —
# a wheel-less container can run this in a few minutes, no TPU needed.
#
#   scripts/verify_smoke.sh              # defaults (batch 64)
#   SMOKE_BATCH=256 scripts/verify_smoke.sh
#
# Exit status is nonzero if any verdict differential reports a
# mismatch (bench.py propagates per-metric rc).
set -euo pipefail
cd "$(dirname "$0")/.."
# CPU XLA compiles of the verify cores run multiple minutes each (the
# persistent compile cache is TPU-oriented); give the worker room.
export FABRIC_MOD_TPU_BENCH_TIMEOUT="${FABRIC_MOD_TPU_BENCH_TIMEOUT:-2400}"
exec python bench.py --cpu --batch "${SMOKE_BATCH:-64}" --reps 1 \
    --metric diffverify --metric hashverify
