#!/usr/bin/env bash
# CPU smoke target for the verify + commit pipeline:
#   0. the FMT_RACECHECK=1 canary slice (concurrency guards armed
#      over every retrofitted threaded structure) + the
#      deterministic-clock raft elections + the fault-injection
#      scenario tier (deliver drop/failover, device-error sw
#      fallback + circuit breaker, leader-crash broadcast retry,
#      commit crash-resume) run with the race guards armed
#   1. the mixed-ladder verdict differential (incl. the fused-hash
#      raw-vs-digest check)
#   2. the fused hash->verify A/B
#   3. the commit-pipeline differential: pipelined-vs-sync committed
#      blocks with mixed barrier/non-barrier streams, asserting
#      per-block txflags + final state-hash identity (sw verifier so
#      no XLA compile — the identity assertion runs on every change);
#      since PR 9 the metric also runs a FMT_TRACE-armed arm whose
#      verdicts/fingerprints must match AND whose sub-span totals
#      must explain the stage/await/commit buckets within 10%
# all on the CPU backend with a small batch — a wheel-less container
# can run this in a few minutes, no TPU needed.
#
#   scripts/verify_smoke.sh              # defaults (batch 64)
#   SMOKE_BATCH=256 scripts/verify_smoke.sh
#
# Exit status is nonzero if any verdict differential or the commitpipe
# identity assertion fails (bench.py propagates per-metric rc).
set -euo pipefail
cd "$(dirname "$0")/.."
# 00. the static-analysis gate (fmtlint): the repo's runtime
#     disciplines — knob registry, fault points, span names,
#     registered threads/locks, injectable clocks, swallowed
#     exceptions, JAX hot-path purity, README knob-table drift —
#     checked over the whole package in seconds, BEFORE any test or
#     bench time is spent; any finding fails the smoke
JAX_PLATFORMS=cpu python -m fabric_mod_tpu.analysis
# 0. the race tier's canary slice under FMT_RACECHECK=1: every guard
#    of fabric_mod_tpu/concurrency armed over the retrofitted
#    structures (gossip comm senders, the verify-service flusher, the
#    commit pipeline, deliverclient, election, the gossip drain) plus
#    the deterministic-clock raft election suite — cheap (<1 min) and
#    run on EVERY change, so a reintroduced race or lock inversion
#    fails the smoke before it ever flakes in CI
FMT_RACECHECK=1 JAX_PLATFORMS=cpu python -m pytest -q \
    -p no:cacheprovider -p no:randomly \
    tests/test_racecheck.py tests/test_raft_fakeclock.py
# 0b. the fault/chaos slice, ALSO under FMT_RACECHECK=1 (the
#     permanently-armed lane): one deliver-drop -> typed disconnect +
#     resume, one device-error -> sw-fallback (verdicts bit-identical,
#     breaker open/probe/re-close), one raft leader crash -> broadcast
#     NOT_LEADER retry on ManualClock, plus the commit crash-resume
#     fingerprint differential — every retry/failover thread runs with
#     the race guards armed, so new fault-handling code is race-checked
#     the day it lands
FMT_RACECHECK=1 JAX_PLATFORMS=cpu python -m pytest -q \
    -p no:cacheprovider -p no:randomly \
    tests/test_faults.py
# 0c. the backpressure slice, same permanently-armed FMT_RACECHECK=1
#     lane: token-bucket/watermark units, the knobs-unset blocking-put
#     differential, RESOURCE_EXHAUSTED + retry-after over a real gRPC
#     socket, and the in-process mini broadcast storm (admitted =>
#     committed exactly once, sheds typed) — every admission thread
#     runs with the race guards armed from the day it lands
FMT_RACECHECK=1 JAX_PLATFORMS=cpu python -m pytest -q \
    -p no:cacheprovider -p no:randomly -m 'not slow' \
    tests/test_backpressure.py
# 0d. the soak slice, same permanently-armed FMT_RACECHECK=1 lane: a
#     short DETERMINISTIC churn-soak (fixed seed 8, ManualClock-
#     accelerated raft elections, <=60 s) running all six churn-event
#     kinds — peer join + anti-entropy catch-up, ACL revocation
#     cutting a live subscriber, batch reconfig, consenter add/remove,
#     leader kill — under continuous mixed x509+idemix traffic with
#     the background fault plan armed; fingerprint convergence,
#     admitted=>committed-exactly-once, and the thread-leak sweep all
#     gate, and a failure prints the seed + schedule to replay
FMT_RACECHECK=1 JAX_PLATFORMS=cpu python -m pytest -q \
    -p no:cacheprovider -p no:randomly -m 'not slow' \
    tests/test_soak.py
# 0e. the trace slice, ARMED (FMT_TRACE=1) on top of the race lane:
#     the span/timeline layer runs live over the commitpipe
#     differential — verdicts and state fingerprints must stay
#     identical with tracing on (tests/test_tracing.py pins the
#     armed-vs-unarmed differential, the cross-thread context
#     propagation, the flight-recorder ring bounds, and the Chrome
#     trace-event export schema), and test_commitpipe re-runs its
#     whole differential with every span seam armed
FMT_TRACE=1 FMT_RACECHECK=1 JAX_PLATFORMS=cpu python -m pytest -q \
    -p no:cacheprovider -p no:randomly -m 'not slow' \
    tests/test_tracing.py tests/test_commitpipe.py
# 0f. the tensor-policy slice: the randomized tree differential
#     (tensor verdicts == closure verdicts incl. the greedy used-flag
#     edge cases), the numpy-vs-jax evaluator identity, the
#     non-tensorizable fallback path, the batch spine-decode
#     value-identity + fuzz, and the block-level differential through
#     the real validator — the tensor compiler is re-proven against
#     the closures on every change
JAX_PLATFORMS=cpu python -m pytest -q \
    -p no:cacheprovider -p no:randomly -m 'not slow' \
    tests/test_tensorpolicy.py tests/test_protos.py
# 0g. the shard slice, FMT_RACECHECK=1 over 8 fake host devices (the
#     conftest forces xla_force_host_platform_device_count=8): slice
#     meshes carve the virtual device set and run the REAL
#     multi-device sharding path, the tagged cross-channel flusher
#     routes per-slice groups, and the sharded-vs-independent
#     differential (per-channel txflags + state fingerprints
#     bit-identical) plus both isolation contracts (injected fault /
#     tamper on channel A never perturbs B; a poisoned per-channel
#     pipe never wedges the shared flusher) run with every race
#     guard armed
FMT_RACECHECK=1 JAX_PLATFORMS=cpu python -m pytest -q \
    -p no:cacheprovider -p no:randomly -m 'not slow' \
    tests/test_sharding.py tests/test_parallel.py
# 0h. the staged-ingress slice, FMT_RACECHECK=1: the coalescing lane
#     engine (verdicts identical to the per-envelope path, typed
#     per-envelope NotLeaderError retry/shed, config-vs-staged
#     sequence semantics, per-envelope note_latency) and the
#     group-commit WAL crash contract (torn-tail crop + repair
#     rejoin, N->O(1) fsync collapse) with every race guard armed;
#     the raft suite re-runs with all three ISSUE 16 knobs hot so the
#     pipelined replication path is exercised under the guards too
FMT_RACECHECK=1 JAX_PLATFORMS=cpu python -m pytest -q \
    -p no:cacheprovider -p no:randomly -m 'not slow' \
    tests/test_stagedbroadcast.py tests/test_wal_groupcommit.py
FMT_RACECHECK=1 JAX_PLATFORMS=cpu \
    FABRIC_MOD_TPU_WAL_GROUP_COMMIT=1 FABRIC_MOD_TPU_RAFT_PIPELINE=4 \
    python -m pytest -q \
    -p no:cacheprovider -p no:randomly -m 'not slow' \
    tests/test_raft.py tests/test_raft_fakeclock.py
# 0i. the deliver fan-out slice, FMT_RACECHECK=1: the shared-ring
#     byte-identity differentials (batch projection vs the per-tx
#     generic decoder, shared frames vs the per-stream sender, fuzzed
#     tx bodies), the CommitNotifier wake-exactness + cancellation
#     contracts (one notifier thread, zero tick wakeups), the batched
#     session-ACL once-per-(group, key) counting, the ring-overflow
#     fallback accounting, and the deliver.fanout kill seam — every
#     notifier/stream thread runs with the race guards armed, and the
#     event-service suite re-runs on the fanout-backed server
FMT_RACECHECK=1 JAX_PLATFORMS=cpu python -m pytest -q \
    -p no:cacheprovider -p no:randomly -m 'not slow' \
    tests/test_fanout.py tests/test_deliverevents.py
# 0j. the columnar-rwset slice, FMT_RACECHECK=1: the batch tx-body
#     decode identity + corruption fuzz (accepted rows bit-identical
#     to the generic decoder, corrupted rows COUNTED into the per-tx
#     fallback, never a differing verdict), the 60-block vectorized-
#     vs-generic MVCC differential with mixed columnar/materialized
#     routing, the knob-armed end-to-end committer differential, the
#     incremental-vs-full state-fingerprint oracle, and the durable
#     one-buffered-write batch contract
FMT_RACECHECK=1 JAX_PLATFORMS=cpu python -m pytest -q \
    -p no:cacheprovider -p no:randomly -m 'not slow' \
    tests/test_vectormvcc.py
# 0k. the dissemination slice, FMT_RACECHECK=1: RelayTree determinism
#     + reparent-plan units, the 5-peer relay world's frame
#     byte-identity (relayed bytes == a direct orderer pull's) +
#     single-deliver-stream + state-fingerprint convergence, the
#     bounded per-child queue shedding counted-not-lost, gap repair
#     under an armed dissemination.push drop (repair prod ->
#     anti-entropy pull), and the leadership flap (old root torn
#     down, new root relays from its current height) — the relay
#     push thread and every forwarding peer run with the race guards
#     armed from the day the subsystem lands
FMT_RACECHECK=1 JAX_PLATFORMS=cpu python -m pytest -q \
    -p no:cacheprovider -p no:randomly -m 'not slow' \
    tests/test_dissemination.py
# 0l. the crash-recovery slice, FMT_RACECHECK=1: the deterministic
#     crash seams behind the soak's PR 20 churn kinds — the
#     peer.ledger.crash fault between blockstore append and state
#     apply (reopen replays statedb-behind-blockstore, incremental
#     fingerprint == full-rescan oracle, crashed peer == uncrashed
#     differential), the orderer.wal.crash fault (synced prefix
#     survives bit-exact, the never-acked in-buffer tail never
#     surfaces), and the physically-torn WAL tail (CRC crop +
#     truncate, post-restart appends land on a clean end)
FMT_RACECHECK=1 JAX_PLATFORMS=cpu python -m pytest -q \
    -p no:cacheprovider -p no:randomly -m 'not slow' \
    tests/test_crash_recovery.py
# vectorized-armed commitpipe differential: the whole pipelined/sync/
# depth1/traced gate set re-run with FABRIC_MOD_TPU_VECTOR_MVCC hot,
# so the columnar MVCC path is proven inside the real commit pipeline
# (not just the dedicated statescale A/B) on every change
FABRIC_MOD_TPU_VECTOR_MVCC=1 python bench.py --cpu \
    --batch "${SMOKE_BATCH:-64}" --reps 1 \
    --metric commitpipe --commitpipe-verifier sw
# CPU XLA compiles of the verify cores run multiple minutes each (the
# persistent compile cache is TPU-oriented); give the worker room.
export FABRIC_MOD_TPU_BENCH_TIMEOUT="${FABRIC_MOD_TPU_BENCH_TIMEOUT:-2400}"
# broadcaststorm: the ingress admission A/B (gated vs ungated 4x
# overload burst, consistency gate: zero admitted-then-lost, sheds
# typed) — host-only, small N, bounded wall time; --staged-batch adds
# the unthrottled staged-vs-unstaged pair on the sw verifier (the
# correctness/consistency gate of the staged engine at smoke scale —
# the batch-ECONOMICS curve is the watcher's device-verifier job)
# commitpipe runs TENSOR-ARMED (--tensor-policy 1): its gates then
# include the tensor-vs-closure txflags + state-fingerprint identity
# on top of the pipelined/sync/traced differentials; policyeval is
# the dedicated tensor-vs-closure A/B over one mixed-verdict block
# multichannel: the channel-sharded scale sweep on host-mode slices
# (sw verifiers, no XLA) — every point's per-channel txflags + state
# fingerprints gate bit-identical sharded-vs-N-independent-unsharded
# before any rate lands in the curve
# deliverfanout: the shared fan-out A/B at smoke scale (sweep up to
# 400 subscribers, host-only) — the byte-identity gate + the
# once-per-(block, form) and once-per-(group, key) assertions run on
# every change; the 10k-subscriber point is the watcher's job
# statescale: the vectorized-MVCC state-scale differential at smoke
# sizes (top point 100k keys, host-only) — flags/fingerprint identity,
# the zero-fallback gate, and the stage+mvcc bucket reduction at the
# 100k point run on every change; the 1M point is the watcher's job
exec python bench.py --cpu --batch "${SMOKE_BATCH:-64}" --reps 1 \
    --metric diffverify --metric hashverify \
    --metric commitpipe --commitpipe-verifier sw --tensor-policy 1 \
    --metric policyeval --policyeval-verifier sw \
    --metric broadcaststorm --clients 4 --staged-batch 32 \
    --metric multichannel --multichannel-verifier sw --peers 8 \
    --metric deliverfanout --subscribers 400 \
    --metric statescale --state-keys 2000,20000,100000
