#!/usr/bin/env python
"""Opportunistic on-chip bench capture (VERDICT r4 item 1).

The axon TPU tunnel has been observed to hang for hours and then
revive; the end-of-round driver capture must never be the only shot at
a platform="tpu" number.  This watcher loops forever:

  1. probe `jax.devices()` in a throwaway child with a hard timeout;
  2. while the tunnel is dead, sleep and retry;
  3. the moment it answers, run the full bench matrix — each variant a
     supervised `bench.py` invocation — and persist every artifact
     under BENCH_TPU_CAPTURE/ plus a best-of BENCH_BEST_<metric>.json
     at the repo root (only overwritten when value improves on a real
     tpu record).

The matrix (ROUND4_NOTES "perf status" checklist):
  a. verify, f32/MXU XLA ladder (default path)
  b. verify, FABRIC_MOD_TPU_PALLAS=1  (Mosaic-compile the fused ladder)
  c. verify, FABRIC_MOD_TPU_UNROLL_LOW_CARRY=1 (XLA A/B)
  d. verify, FABRIC_MOD_TPU_PRECISION=high (vs default highest)
  e. block / e2e / idemix / gossip metrics

Each matrix entry has its own timeout so one hanging variant (Mosaic
compile is unproven on this kernel) cannot eat the session.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUTDIR = os.path.join(REPO, "BENCH_TPU_CAPTURE")
PROBE_TIMEOUT = float(os.environ.get("FMT_WATCH_PROBE_TIMEOUT", "150"))
PROBE_INTERVAL = float(os.environ.get("FMT_WATCH_INTERVAL", "300"))
RECAPTURE_INTERVAL = float(os.environ.get("FMT_WATCH_RECAPTURE", "3600"))


def log(msg: str) -> None:
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def probe() -> bool:
    """One tunnel-liveness check, reusing bench.py's probe so the
    watcher and the bench agree on what 'alive' means."""
    sys.path.insert(0, REPO)
    from bench import _preflight_probe
    platform, note = _preflight_probe(dict(os.environ), PROBE_TIMEOUT)
    log(f"probe: {note}")
    return platform is not None and platform != "cpu"


# (tag, bench argv, extra env, timeout_s).  An argv starting with
# "-m" runs that module instead of bench.py (the fmtlint gate).
MATRIX = [
    # tier-0 of the matrix: the fmtlint static gate — a drifted knob
    # table or an unregistered thread/lock/fault-point on the capture
    # host fails loudly in the log before any device time is spent
    ("fmtlint", ["-m", "fabric_mod_tpu.analysis"], {}, 120),
    ("verify_xla", ["--metric", "verify"], {}, 900),
    ("verify_pallas", ["--metric", "verify"],
     {"FABRIC_MOD_TPU_PALLAS": "1"}, 900),
    ("verify_unroll", ["--metric", "verify"],
     {"FABRIC_MOD_TPU_UNROLL_LOW_CARRY": "1"}, 900),
    ("verify_prec_high", ["--metric", "verify", "--precision", "high"],
     {}, 900),
    ("verify_mixed_add", ["--metric", "verify", "--mixed-add", "1"],
     {}, 900),
    ("diffverify_mixed", ["--metric", "diffverify", "--batch", "10240"],
     {}, 1200),
    ("marshal", ["--metric", "marshal"], {}, 300),
    ("block", ["--metric", "block"], {}, 1200),
    # tensor-vs-closure policy A/B with the DEVICE verifier: the
    # fused mask->policy program (verify_many_fused_async hands the
    # device-resident mask to the jitted tensor evaluator, no host
    # round trip) gets its first on-chip number, verdicts gated
    # identical to the closure walk before any rate
    ("policyeval", ["--metric", "policyeval", "--tensor-policy", "1"],
     {}, 1200),
    # commitpipe with the tensor path armed on hardware: the commit
    # bucket's policy share (stage_attribution.commit_policy_share)
    # measured with the device verifier + fused policy program
    ("commitpipe_tensor", ["--metric", "commitpipe",
                           "--tensor-policy", "1"], {}, 1500),
    ("e2e", ["--metric", "e2e"], {}, 1500),
    ("idemix", ["--metric", "idemix"], {}, 1500),
    ("gossip", ["--metric", "gossip"], {}, 900),
    ("gossip_inflight1", ["--metric", "gossip", "--inflight", "1"],
     {}, 900),
    ("gossip_nocache", ["--metric", "gossip", "--memo-cache", "0"],
     {}, 900),
    # the storm growth curve toward 500 peers (metric names carry the
    # count, so each lands as its own best-of record)
    ("gossip_150peer", ["--metric", "gossip", "--peers", "150"],
     {}, 1200),
    ("gossip_500peer", ["--metric", "gossip", "--peers", "500"],
     {}, 1800),
    # channel-sharded scale-out: N channels on mesh slices behind the
    # shared cross-channel verify service; per-channel txflags +
    # fingerprints gate bit-identical sharded-vs-independent before
    # any rate, then the (slices x channels x peers) scale curve is
    # captured and persist() writes it through to MULTICHIP_rTPU.json
    # — the on-chip answer to whether K chips x N channels aggregate
    ("multichannel", ["--metric", "multichannel", "--slices", "4",
                      "--channels", "4", "--peers", "50"], {}, 2400),
    # host-only but captured alongside: the ingress admission A/B
    # (gated vs ungated overload burst + consistency gate)
    ("broadcaststorm", ["--metric", "broadcaststorm", "--batch", "512"],
     {}, 900),
    # the staged-vs-unstaged ingress A/B at three client counts, with
    # the Writers verifies dispatched through the REAL device batch
    # verifier (--storm-verifier device): the scale curve for the
    # staged ingress engine — one coalesced dispatch per drain vs one
    # per submission — with the PR 7 admission pair still gating each
    # run.  The client count rides the bench metric name.
    ("broadcaststorm_staged_4client",
     ["--metric", "broadcaststorm", "--batch", "256", "--clients", "4",
      "--staged-batch", "64", "--storm-verifier", "device"], {}, 1500),
    ("broadcaststorm_staged_8client",
     ["--metric", "broadcaststorm", "--batch", "256", "--clients", "8",
      "--staged-batch", "64", "--storm-verifier", "device"], {}, 1500),
    ("broadcaststorm_staged_16client",
     ["--metric", "broadcaststorm", "--batch", "256", "--clients", "16",
      "--staged-batch", "64", "--storm-verifier", "device"], {}, 1500),
    # host-only churn soak over the FULL 9-kind plan (the crash-shaped
    # PR 20 kinds — peer_crash_rejoin, orderer_restart,
    # network_partition — included): a longer on-hardware schedule
    # (12 events, so the core catalog fires once plus repeats) with
    # the fixed seed — every convergence/exactly-once/leak invariant
    # plus the crash-replay and WAL-restart gates pass before the
    # sustained mixed tx/s is recorded, and the capture carries the
    # per-kind fabric_soak_recovery_seconds breakdown
    # (recovery_s_by_kind) for all nine kinds
    ("soak", ["--metric", "soak", "--soak-seed", "8",
              "--soak-events", "12"], {}, 1500),
    # host-only shared deliver fan-out at full scale: 10k mixed
    # full/filtered subscribers over sustained commit traffic; every
    # swept point gates byte-identity (shared frames == the per-stream
    # sender's output) + once-per-(block, form) materialization +
    # once-per-(group, key) session ACLs before blocks*subs/s lands
    ("deliverfanout_10k", ["--metric", "deliverfanout",
                           "--subscribers", "10000"], {}, 1200),
    # host-only deliver fan-out at the 100k-subscriber top point,
    # slow-marked as its own entry (the default smoke sweep stops at
    # 10k): the top point's chain is read back from a RELAYED
    # non-leader peer's ledger — the fan-out engine provably composes
    # with the dissemination tree path — and the byte-identity +
    # once-per-(block, form) + session-ACL gates run unchanged
    ("deliverfanout_100k", ["--metric", "deliverfanout",
                            "--subscribers", "100000"], {}, 2400),
    # host-only dissemination forest: relay-vs-all-pull at 8/32/128
    # peers over the live signed gossip comm layer; every point gates
    # relayed-frame byte-identity (== a direct orderer pull's bytes),
    # all-peer state-fingerprint convergence, and exactly ONE orderer
    # deliver stream per leader before blocks*peers/s lands
    ("dissemination_128peer", ["--metric", "dissemination",
                               "--peers", "128"], {}, 1800),
    # host-only vectorized-MVCC state-scale sweep: the same signed
    # stream committed into ledgers prefilled at 10k/100k/1M keys,
    # generic vs FABRIC_MOD_TPU_VECTOR_MVCC arms; per-point txflags +
    # state fingerprints gate bit-identical (and the incremental
    # fingerprint gates against the full-scan oracle) before any rate
    # or stage+mvcc bucket second is recorded
    ("statescale", ["--metric", "statescale",
                    "--state-keys", "10000,100000,1000000"], {}, 1200),
    # FMT_TRACE-armed commitpipe on the DEVICE verifier: the traced
    # arm's verdict/fingerprint identity + stage-attribution sum gate
    # run against real hardware, the span ring lands as a Perfetto-
    # loadable chrome trace, FMT_TRACE_JAX_PROFILE captures a one-shot
    # jax.profiler device profile around a batch dispatch, and
    # fabric_tpu_compiles_total counts XLA compiles/retraces — the
    # first on-chip answer to WHICH sub-stage the next kernel should
    # vectorize
    ("commitpipe_traced",
     ["--metric", "commitpipe", "--trace-out",
      os.path.join(OUTDIR, "commitpipe_trace.json")],
     {"FMT_TRACE": "1",
      "FMT_TRACE_JAX_PROFILE": os.path.join(OUTDIR, "jaxprof")}, 1500),
    # FMT_TRACE-armed e2e: the stage-attribution breakdown
    # (recv/unpack/der_marshal/device_dispatch/verdict_await/
    # policy_gather/policy_device/policy_finish/mvcc/ledger_write)
    # recorded on hardware, so the vectorized-policy/MVCC roadmap
    # item points at a measured number
    ("e2e_traced",
     ["--metric", "e2e", "--trace-out",
      os.path.join(OUTDIR, "e2e_trace.json")],
     {"FMT_TRACE": "1"}, 1500),
]


def run_variant(tag, argv, extra_env, timeout_s):
    env = dict(os.environ)
    env.update(extra_env)
    env.setdefault("FABRIC_MOD_TPU_JIT_CACHE",
                   os.path.expanduser("~/.cache/fabric_mod_tpu/jit"))
    # the watcher already probed; don't respend probe budget per variant
    env["FABRIC_MOD_TPU_BENCH_PROBE_TIMEOUT"] = "120"
    env["FABRIC_MOD_TPU_BENCH_TIMEOUT"] = str(int(timeout_s - 60))
    env["FABRIC_MOD_TPU_BENCH_ATTEMPTS"] = "1"
    if argv and argv[0] == "-m":
        # gate entries resolve the package from cwd, not the script
        # path — pin it so a $HOME-launched watcher still finds it
        cmd = [sys.executable] + argv
        run_cwd = REPO
    else:
        cmd = [sys.executable, os.path.join(REPO, "bench.py")] + argv
        run_cwd = None
    log(f"run {tag}: {' '.join(argv)} env={extra_env}")
    t0 = time.time()
    logpath = os.path.join(OUTDIR, f"{tag}.log")
    try:
        with open(logpath, "ab") as lf:
            proc = subprocess.run(cmd, env=env, timeout=timeout_s,
                                  cwd=run_cwd,
                                  stdout=subprocess.PIPE, stderr=lf)
    except subprocess.TimeoutExpired:
        log(f"{tag}: TIMED OUT after {timeout_s}s")
        return None
    dt = time.time() - t0
    if argv and argv[0] == "-m":
        # gate entries (fmtlint) emit no bench JSON: pass/fail is the
        # exit code, findings land in the per-tag log
        if proc.returncode == 0:
            log(f"{tag}: clean ({dt:.0f}s)")
            return None
        log(f"{tag}: FAILED rc={proc.returncode} — findings in "
            f"{logpath}")
        with open(logpath, "ab") as lf:
            lf.write(proc.stdout)
        return GATE_FAILED
    for line in reversed(proc.stdout.decode().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            rec["capture_tag"] = tag
            rec["capture_time"] = time.strftime("%Y-%m-%dT%H:%M:%S")
            rec["capture_wall_s"] = round(dt, 1)
            log(f"{tag}: {json.dumps(rec)}")
            return rec
    log(f"{tag}: rc={proc.returncode}, no JSON after {dt:.0f}s")
    return None


def persist(rec):
    os.makedirs(OUTDIR, exist_ok=True)
    tag = rec["capture_tag"]
    stamp = time.strftime("%H%M%S")
    with open(os.path.join(OUTDIR, f"{tag}_{stamp}.json"), "w") as f:
        json.dump(rec, f, indent=1)
    if rec.get("platform") != "tpu":
        return
    if rec.get("metric", "").startswith("multichannel"):
        # the MULTICHIP record grows up: not the bare {n_devices, ok}
        # dryrun stub, but the real scale curve — aggregate committed
        # tx/s per (slices x channels x peers) point, identity-gated
        # sharded-vs-independent by the bench before the rates were
        # reported.  One file, overwritten per capture: the curve is
        # a property of the hardware window, not a best-of race.
        multichip = {
            "n_devices": rec.get("n_devices"),
            "ok": True,
            "platform": "tpu",
            "agg_tx_per_sec": rec.get("value"),
            "serial_independent_tx_per_sec": rec.get(
                "serial_independent_tx_per_sec"),
            "axes": rec.get("axes"),
            "points": rec.get("points"),
            "sharded_vs_independent_identical": rec.get(
                "sharded_vs_independent_identical"),
            "capture_time": rec.get("capture_time"),
        }
        with open(os.path.join(REPO, "MULTICHIP_rTPU.json"), "w") as f:
            json.dump(multichip, f, indent=1)
        log("multichannel scale curve -> MULTICHIP_rTPU.json")
    # best-of per metric at repo root, tpu-only
    best_path = os.path.join(REPO, f"BENCH_BEST_{rec['metric']}.json")
    try:
        with open(best_path) as f:
            best = json.load(f)
    except (OSError, json.JSONDecodeError):
        best = None
    if best is None or rec.get("value", 0) > best.get("value", 0):
        with open(best_path, "w") as f:
            json.dump(rec, f, indent=1)
        log(f"new best for {rec['metric']}: {rec['value']} ({tag})")


# sentinel: a gate entry (fmtlint) failed — abort the capture instead
# of spending the device-bench budget on a tree that fails the gate
GATE_FAILED = object()


def capture_matrix():
    got_tpu = False
    for tag, argv, env, timeout_s in MATRIX:
        rec = run_variant(tag, argv, env, timeout_s)
        if rec is GATE_FAILED:
            log("gate failed; aborting this capture (fix the tree, "
                "the watcher will retry next interval)")
            return False
        if rec is not None:
            persist(rec)
            if rec.get("platform") == "tpu":
                got_tpu = True
        # quick re-probe between variants: if the tunnel died mid-
        # matrix, stop burning per-variant timeouts
        if rec is None and not probe():
            log("tunnel died mid-matrix; back to waiting")
            return got_tpu
    return got_tpu


def main():
    os.makedirs(OUTDIR, exist_ok=True)
    log(f"watcher up; probe every {PROBE_INTERVAL}s, "
        f"timeout {PROBE_TIMEOUT}s")
    last_full = 0.0
    while True:
        if probe():
            if time.time() - last_full >= RECAPTURE_INTERVAL:
                ok = capture_matrix()
                if ok:
                    last_full = time.time()
                    log("matrix captured on tpu; next recapture in "
                        f"{RECAPTURE_INTERVAL}s")
            else:
                log("tpu alive; matrix already captured recently")
        time.sleep(PROBE_INTERVAL)


if __name__ == "__main__":
    main()
