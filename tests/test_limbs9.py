"""f32/MXU limb layer (ops/limbs9) vs exact python-int math.

Every assertion is a bit-exact differential against python integers —
this is what guards the f32-mantissa bound analysis in the module
docstring (and the PRECISION setting of the constant matmuls): any
inexact product/sum shows up as a wrong limb, never as a tolerance.
"""
import numpy as np
import jax.numpy as jnp

from fabric_mod_tpu.ops import limbs9 as L

P256_P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
P256_N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
R = 1 << L.RBITS

FP = L.FieldSpec.make("p256.p", P256_P)
FN = L.FieldSpec.make("p256.n", P256_N)


def rand_ints(rng, n, bound):
    return [rng.randrange(bound) for _ in range(n)]


def batch_limbs(vals):
    """python ints -> (K, n) device-layout f32 limbs."""
    return L.to_device(np.stack([L.int_to_limbs(v) for v in vals]))


def col(arr, i):
    """(K, n) -> python int value of lane i."""
    return L.limbs_to_int(np.asarray(arr)[:, i])


def test_converters_roundtrip(rng):
    for v in rand_ints(rng, 20, 1 << 256):
        assert L.limbs_to_int(L.int_to_limbs(v)) == v
    vals = rand_ints(rng, 64, 1 << 256)
    buf = np.stack([
        np.frombuffer(v.to_bytes(32, "big"), np.uint8) for v in vals])
    lb = L.be_bytes_to_limbs(buf)
    for i, v in enumerate(vals):
        assert L.limbs_to_int(lb[i].astype(np.float32)) == v


def test_mont_mul_matches_int_math(rng):
    for spec, mod in [(FP, P256_P), (FN, P256_N)]:
        a = rand_ints(rng, 32, mod)
        b = rand_ints(rng, 32, mod)
        am, bm = batch_limbs(a), batch_limbs(b)
        out = np.asarray(L.mont_mul(am, bm, spec))
        for i in range(32):
            got = L.limbs_to_int(out[:, i]) % mod
            want = (a[i] * b[i] * pow(R, -1, mod)) % mod
            assert got == want
            # lazy-bound invariant from the module docstring
            assert np.abs(out[:, i]).max() <= 273


def test_mont_sqr_matches_mul(rng):
    a = rand_ints(rng, 16, P256_P)
    am = batch_limbs(a)
    sq = np.asarray(L.canonical(L.mont_sqr(am, FP), FP))
    for i in range(16):
        want = (a[i] * a[i] * pow(R, -1, P256_P)) % P256_P
        assert L.limbs_to_int(sq[:, i]) == want


def test_mont_roundtrip_and_addsub(rng):
    a = rand_ints(rng, 16, P256_P)
    b = rand_ints(rng, 16, P256_P)
    am = L.to_mont(batch_limbs(a), FP)
    bm = L.to_mont(batch_limbs(b), FP)
    back = np.asarray(L.canonical(L.from_mont(am, FP), FP))
    for i in range(16):
        assert L.limbs_to_int(back[:, i]) == a[i]
    s = np.asarray(L.canonical(L.from_mont(L.add(am, bm), FP), FP))
    d = np.asarray(L.canonical(L.from_mont(L.sub(am, bm), FP), FP))
    for i in range(16):
        assert L.limbs_to_int(s[:, i]) == (a[i] + b[i]) % P256_P
        assert L.limbs_to_int(d[:, i]) == (a[i] - b[i]) % P256_P


def test_deep_chain_differential(rng):
    """200 rounds of sqr/add/sub/mul with an int mirror: catches any
    slow drift of the lazy bounds or a single inexact matmul pass."""
    xs = rand_ints(rng, 32, P256_P)
    a = batch_limbs(xs)
    am = L.to_mont(a, FP)
    Rinv = pow(R, -1, P256_P)
    x_dev = am
    x_int = [x * R % P256_P for x in xs]
    for _ in range(200):
        t = L.add(L.mont_sqr(x_dev, FP), L.sub(x_dev, L.mul_small(x_dev, 3)))
        x_dev = L.mont_mul(t, am, FP)
        x_int = [((xi * xi * Rinv - 2 * xi) * (xs[i] * R) * Rinv) % P256_P
                 for i, xi in enumerate(x_int)]
    assert np.abs(np.asarray(x_dev)).max() <= 273
    canon = np.asarray(L.canonical(x_dev, FP))
    for i in range(32):
        assert L.limbs_to_int(canon[:, i]) == x_int[i]


def test_canonical_and_eq_zero(rng):
    vals = [0, 1, P256_P - 1]
    vm = batch_limbs(vals)
    c = np.asarray(L.canonical(vm, FP))
    for i, v in enumerate(vals):
        assert L.limbs_to_int(c[:, i]) == v % P256_P
    multiples = batch_limbs([P256_P, 2 * P256_P])
    assert np.asarray(L.eq_zero(multiples, FP)).all()
    assert not np.asarray(L.eq_zero(batch_limbs([1]), FP)).any()
    neg = L.sub(batch_limbs([1]), batch_limbs([2]))
    c = np.asarray(L.canonical(neg, FP))
    assert L.limbs_to_int(c[:, 0]) == P256_P - 1


def test_pow_and_inverse(rng):
    a = rand_ints(rng, 8, P256_N - 1)
    a = [v + 1 for v in a]
    am = L.to_mont(batch_limbs(a), FN)
    inv = L.inv_mont(am, FN)
    got = np.asarray(L.canonical(L.from_mont(inv, FN), FN))
    for i in range(8):
        assert L.limbs_to_int(got[:, i]) == pow(a[i], -1, P256_N)


def test_bits_le(rng):
    vals = rand_ints(rng, 8, P256_N)
    c = L.canonical(batch_limbs(vals), FN)
    bits = np.asarray(L.bits_le(c))
    for i, v in enumerate(vals):
        want = [(v >> j) & 1 for j in range(256)]
        assert bits[:, i].tolist() == want


def test_mul_small(rng):
    a = rand_ints(rng, 8, P256_P)
    out = L.mul_small(batch_limbs(a), 13)
    got = np.asarray(L.canonical(out, FP))
    for i in range(8):
        assert L.limbs_to_int(got[:, i]) == (13 * a[i]) % P256_P
