"""Client event deliver service: Deliver / DeliverFiltered over gRPC.

(reference test model: core/peer/deliverevents_test.go — filtered
block construction, ACL gating, and the SDK commit-listener flow:
submit -> wait on DeliverFiltered -> learn the tx validation code.)
"""
import threading
import time

import pytest

from fabric_mod_tpu.comm.grpc_comm import GRPCClient
from fabric_mod_tpu.e2e import Network
from fabric_mod_tpu.msp import ca as calib
from fabric_mod_tpu.msp.identities import SigningIdentity
from fabric_mod_tpu.peer.aclmgmt import ACLProvider
from fabric_mod_tpu.peer.deliverevents import (
    EventDeliverClient, EventDeliverServer, EventStreamError,
    filtered_block, make_signed_seek_envelope)
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil

V = m.TxValidationCode


@pytest.fixture()
def world(tmp_path):
    net = Network(str(tmp_path), batch_timeout="100ms",
                  max_message_count=25)
    acl = ACLProvider(net.channel.bundle)
    server = EventDeliverServer(net.channel_id, net.ledger, acl)
    server.start()
    client = GRPCClient(f"127.0.0.1:{server.port}")
    yield net, server, client
    client.close()
    server.stop()
    net.close()


def _events_client(net, client):
    return EventDeliverClient(client, net.channel_id, net.client)


def test_filtered_stream_reports_validation_codes(world):
    net, _, grpc_client = world
    txids = [net.invoke([b"put", b"k%d" % i, b"v%d" % i])
             for i in range(10)]
    net.pump_committed(10)
    evc = _events_client(net, grpc_client)
    seen = {}
    for fb in evc.filtered_blocks(start=0, stop=net.ledger.height - 1):
        assert fb.channel_id == net.channel_id
        for ftx in fb.filtered_transactions:
            if ftx.type == m.HeaderType.ENDORSER_TRANSACTION:
                seen[ftx.txid] = ftx.tx_validation_code
    for txid in txids:
        assert seen[txid] == V.VALID


def test_non_seek_envelope_rejected_bad_request(world):
    """A WELL-SIGNED envelope whose channel header is not
    DELIVER_SEEK_INFO must be refused with BAD_REQUEST — any other
    type decoding as SeekInfo is a wire-format accident, not a seek
    (ADVICE r5; reference: the deliver handler's header-type check)."""
    net, server, _ = world
    seek = m.SeekInfo(
        start=m.SeekPosition(specified=m.SeekSpecified(number=0)),
        stop=m.SeekPosition(specified=m.SeekSpecified(number=0)),
        behavior=m.SeekBehavior.BLOCK_UNTIL_READY)
    ch = protoutil.make_channel_header(
        m.HeaderType.ENDORSER_TRANSACTION, net.channel_id)
    sh = protoutil.make_signature_header(net.client.serialize(),
                                         protoutil.new_nonce())
    payload = protoutil.make_payload(ch, sh, seek.encode())
    env = protoutil.sign_envelope(payload, net.client)
    status, got, _ = server._check_request(env.encode(), filtered=True)
    assert status == m.Status.BAD_REQUEST and got is None
    # control: the correctly-typed envelope still passes
    good = make_signed_seek_envelope(net.channel_id, 0, 0, net.client)
    status, got, recheck = server._check_request(good.encode(),
                                                 filtered=True)
    assert status == m.Status.SUCCESS and got is not None
    recheck()                              # session re-check callable


def test_wait_for_tx_learns_code_across_commit(world):
    """The SDK flow: subscribe, submit, learn VALID — exercising
    BLOCK_UNTIL_READY against the ledger's commit notification."""
    net, _, grpc_client = world
    evc = _events_client(net, grpc_client)
    # ordered but NOT yet committed on the peer ledger ...
    txid = net.invoke([b"put", b"late", b"v"])

    def commit_later():
        time.sleep(0.3)
        net.pump_committed(1)

    t = threading.Thread(target=commit_later, daemon=True)
    t.start()
    # ... so the stream must block at the tip and wake on the ledger's
    # commit notification
    code = evc.wait_for_tx(txid, timeout_s=20)
    t.join()
    assert code == V.VALID


def test_full_block_stream_matches_ledger(world):
    net, _, grpc_client = world
    net.invoke([b"put", b"a", b"1"])
    net.pump_committed(1)
    evc = _events_client(net, grpc_client)
    blocks = list(evc.blocks(start=0, stop=net.ledger.height - 1))
    assert len(blocks) == net.ledger.height
    for blk in blocks:
        want = net.ledger.get_block_by_number(blk.header.number)
        assert blk.header.data_hash == want.header.data_hash


def test_chaincode_events_stripped_on_filtered_stream(world):
    net, _, grpc_client = world
    txid = net.invoke([b"putev", b"evk", b"payload-secret"])
    net.pump_committed(1)
    evc = _events_client(net, grpc_client)
    found = None
    for fb in evc.filtered_blocks(start=0, stop=net.ledger.height - 1):
        for ftx in fb.filtered_transactions:
            if ftx.txid == txid:
                found = ftx
    assert found is not None and found.tx_validation_code == V.VALID
    acts = found.transaction_actions.chaincode_actions
    assert len(acts) == 1
    ev = acts[0].chaincode_event
    assert ev.event_name == "kv-put" and ev.chaincode_id == "mycc"
    assert ev.payload == b""           # stripped, never leaked
    # the FULL block stream still carries the payload for entitled
    # readers (reference: Deliver vs DeliverFiltered contract)
    blk = next(iter(evc.blocks(start=1, stop=1)))
    assert b"payload-secret" in blk.encode()


def test_invalid_tx_code_visible_to_clients(world):
    """An endorsement-policy failure commits as invalid; the event
    stream must say so (that is its whole point)."""
    net, _, grpc_client = world
    txid = net.invoke([b"put", b"k", b"v"],
                      endorsing_orgs=[list(net.endorsers)[0]])
    net.pump_committed(1)
    evc = _events_client(net, grpc_client)
    code = evc.wait_for_tx(txid, timeout_s=10)
    assert code == V.ENDORSEMENT_POLICY_FAILURE


def test_acl_rejects_foreign_identity(world):
    net, _, grpc_client = world
    rogue_ca = calib.CA("ca.rogue", "RogueOrg")
    cert, key = rogue_ca.issue("intruder", "RogueOrg", ous=["client"])
    rogue = SigningIdentity("Org1", cert, calib.key_pem(key), net.csp)
    evc = EventDeliverClient(grpc_client, net.channel_id, rogue)
    with pytest.raises(EventStreamError) as ei:
        list(evc.filtered_blocks(start=0, stop=0))
    assert ei.value.status == m.Status.FORBIDDEN


def test_wrong_channel_rejected(world):
    net, _, grpc_client = world
    evc = EventDeliverClient(grpc_client, "nosuchchannel", net.client)
    with pytest.raises(EventStreamError) as ei:
        list(evc.filtered_blocks(start=0, stop=0))
    assert ei.value.status == m.Status.NOT_FOUND


def test_filtered_block_projection_unit():
    """filtered_block on a hand-built block: malformed envelope tagged
    with its flag, missing flags default NOT_VALIDATED."""
    envs = [m.Envelope(payload=b"\xff\xfegarbage")]
    blk = protoutil.new_block(7, b"", envs)
    protoutil.set_block_txflags(blk, bytes([V.BAD_PAYLOAD]))
    fb = filtered_block("ch", blk)
    assert fb.number == 7
    assert fb.filtered_transactions[0].tx_validation_code == V.BAD_PAYLOAD


# --- mid-stream ACL re-evaluation at config blocks -------------------------

class _RevocableAcl:
    """Real ACLProvider behavior until `revoked` flips — the stand-in
    for a config update whose new MSP/CRL rejects the subscriber (the
    bundle-backed provider re-reads the CURRENT config on every
    check, so the flip models exactly what a committed revocation
    changes)."""

    def __init__(self, inner):
        self._inner = inner
        self.revoked = False
        self.checks = 0

    def check_acl(self, resource, sds):
        self.checks += 1
        if self.revoked:
            raise PermissionError("identity revoked by channel config")
        return self._inner.check_acl(resource, sds)


def _commit_config_block(net):
    """Append a genuine CONFIG-type block to the peer ledger (the
    config machinery upstream swaps the bundle; the ledger commit is
    what the event stream observes)."""
    ch = protoutil.make_channel_header(
        m.HeaderType.CONFIG, net.channel_id, tx_id="cfg-revoke")
    sh = protoutil.make_signature_header(net.client.serialize(),
                                         protoutil.new_nonce())
    payload = protoutil.make_payload(ch, sh, b"new-config-bytes")
    env = protoutil.sign_envelope(payload, net.client)
    h = net.ledger.height
    prev = protoutil.block_header_hash(
        net.ledger.get_block_by_number(h - 1).header)
    blk = protoutil.new_block(h, prev, [env])
    net.ledger.commit_block(blk, [V.VALID])
    return h


def test_revoked_subscriber_cut_off_at_config_block(tmp_path):
    """A revoked identity holding a BLOCK_UNTIL_READY subscription is
    terminated with FORBIDDEN when the config block commits — it
    receives neither the config block nor anything after it
    (reference: common/deliver/deliver.go:157-199)."""
    from fabric_mod_tpu.e2e import Network
    from fabric_mod_tpu.peer.aclmgmt import ACLProvider

    net = Network(str(tmp_path), batch_timeout="100ms",
                  max_message_count=25)
    acl = _RevocableAcl(ACLProvider(net.channel.bundle))
    server = EventDeliverServer(net.channel_id, net.ledger, acl)
    server.start()
    grpc_client = GRPCClient(f"127.0.0.1:{server.port}")
    try:
        net.invoke([b"put", b"k0", b"v0"])
        net.pump_committed(1)
        evc = _events_client(net, grpc_client)

        got, outcome = [], {}

        def subscribe():
            try:
                for fb in evc.filtered_blocks(start=0, stop=None,
                                              timeout_s=30):
                    got.append(fb.number)
            except EventStreamError as e:
                outcome["status"] = e.status

        t = threading.Thread(target=subscribe, daemon=True)
        t.start()
        # the subscriber reaches the tip and parks there
        deadline = time.time() + 10
        while time.time() < deadline and \
                len(got) < net.ledger.height:
            time.sleep(0.02)
        assert len(got) == net.ledger.height
        # the revoking config commits
        acl.revoked = True
        cfg_num = _commit_config_block(net)
        t.join(timeout=15)
        assert not t.is_alive(), "revoked stream did not terminate"
        assert outcome.get("status") == m.Status.FORBIDDEN
        assert cfg_num not in got, \
            "revoked subscriber received the config block"
        # a still-authorized subscriber DOES get the config block and
        # keeps streaming (the re-check only bites revoked sessions)
        acl.revoked = False
        nums = [fb.number for fb in
                evc.filtered_blocks(start=0,
                                    stop=net.ledger.height - 1)]
        assert cfg_num in nums
    finally:
        grpc_client.close()
        server.stop()
        net.close()


def test_real_revocation_cuts_actively_streaming_subscriber_under_load(
        tmp_path):
    """The PR 4 mid-stream re-check at system scale: a REAL config
    update (Org3 removed from the Application group, signed by a
    majority of admins, through Broadcast -> solo consenter -> deliver
    -> peer bundle swap) lands while an Org3 subscriber is ACTIVELY
    receiving blocks under continuous load — the stream must end
    FORBIDDEN without delivering the revocation block or anything
    after it, while the load keeps committing for everyone else."""
    from fabric_mod_tpu.channelconfig import (compute_update,
                                              signed_update_envelope)
    from fabric_mod_tpu.channelconfig.bundle import (APPLICATION,
                                                     groups_of, set_group)
    from fabric_mod_tpu.soak.harness import _first_config_block_at_or_after

    net = Network(str(tmp_path), batch_timeout="100ms",
                  max_message_count=4)
    acl = ACLProvider(net.channel.bundle)
    server = EventDeliverServer(net.channel_id, net.ledger, acl)
    server.start()
    grpc_client = GRPCClient(f"127.0.0.1:{server.port}")
    stop = threading.Event()
    pump = net.deliver_client()
    threads = []
    try:
        # continuous load: a submit loop + the deliver pump committing
        def load():
            i = 0
            while not stop.is_set():
                try:
                    net.invoke([b"put", b"lk%d" % i, b"lv%d" % i])
                except Exception:
                    pass                   # post-revocation churn: retry
                i += 1
                time.sleep(0.05)

        threads.append(threading.Thread(target=load, daemon=True))
        threads.append(threading.Thread(
            target=lambda: pump.run(idle_timeout_s=30.0), daemon=True))
        for t in threads:
            t.start()

        # the Org3 subscriber, streaming FULL blocks from 0
        cert, key = net.cas["Org3"].issue("sub@org3", "Org3",
                                          ous=["client"])
        sub_signer = SigningIdentity("Org3", cert, calib.key_pem(key),
                                     net.csp)
        evc = EventDeliverClient(grpc_client, net.channel_id, sub_signer)
        got, outcome = [], {}

        def subscribe():
            try:
                for blk in evc.blocks(start=0, stop=None, timeout_s=90):
                    got.append(blk.header.number)
            except EventStreamError as e:
                outcome["status"] = e.status

        sub = threading.Thread(target=subscribe, daemon=True)
        sub.start()

        # the subscriber is ACTIVELY streaming: it keeps receiving
        # new blocks the load commits (not parked at a stale tip)
        base = len(got)
        deadline = time.time() + 60
        while time.time() < deadline and len(got) < base + 3:
            time.sleep(0.05)
        assert len(got) >= base + 3, "subscriber never streamed under load"

        # the revocation: remove Org3, signed by the Org1+Org2 admins
        # (the MAJORITY of the 3 app-org Admins policy)
        pre_h = net.ledger.height
        cur = net.support.bundle().config
        desired = m.ConfigGroup.decode(cur.channel_group.encode())
        app = groups_of(desired)[APPLICATION]
        app.groups = [e for e in app.groups if e.key != "Org3"]
        set_group(desired, APPLICATION, app)
        update = compute_update(net.channel_id, cur, desired)
        env = signed_update_envelope(
            net.channel_id, update,
            [net.admins["Org1"], net.admins["Org2"]])
        net.broadcast.submit(env)

        # the revoked stream terminates FORBIDDEN...
        sub.join(timeout=60)
        assert not sub.is_alive(), "revoked stream did not terminate"
        assert outcome.get("status") == m.Status.FORBIDDEN
        # ...without EVER delivering a post-revocation block
        deadline = time.time() + 30
        cfg_num = None
        while time.time() < deadline and cfg_num is None:
            cfg_num = _first_config_block_at_or_after(net.ledger, pre_h)
            time.sleep(0.05)
        assert cfg_num is not None, "revocation block never committed"
        late = [n for n in got if n >= cfg_num]
        assert not late, f"revoked subscriber saw {late} (cfg {cfg_num})"

        # the load is still committing for the surviving orgs: an
        # Org1 subscriber streams PAST the revocation block
        h0 = net.ledger.height
        deadline = time.time() + 60
        while time.time() < deadline and net.ledger.height <= h0:
            time.sleep(0.05)
        assert net.ledger.height > h0, "load stalled after revocation"
        evc_ok = _events_client(net, grpc_client)
        nums = [fb.number for fb in
                evc_ok.filtered_blocks(start=cfg_num,
                                       stop=net.ledger.height - 1)]
        assert cfg_num in nums
    finally:
        stop.set()
        pump.stop()
        for t in threads:
            t.join(timeout=15)
        grpc_client.close()
        server.stop()
        net.close()
