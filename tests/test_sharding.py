"""Channel-sharding subsystem tests: deterministic placement +
rebalance, the shared cross-channel verify service's tagged per-slice
routing, CROSS-CHANNEL ISOLATION (a fault or tamper on channel A's
batch never perturbs channel B's txflags or fingerprint; a poisoned
per-channel pipe never wedges the shared flusher), and the acceptance
differential: an N-channel sharded run is bit-identical — per-channel
txflags AND state fingerprints — to N independent unsharded runs.

Host-mode slices (FakeBatchVerifier per slice) keep the routing
machinery fully real without XLA compiles; the REAL multi-device
slice-mesh path runs in test_parallel.py on the virtual 8-device CPU
mesh."""
import threading

import numpy as np
import pytest

from fabric_mod_tpu import faults
from fabric_mod_tpu.bccsp.sw import SwCSP
from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier
from fabric_mod_tpu.ledger import KvLedger
from fabric_mod_tpu.msp import ca as calib
from fabric_mod_tpu.msp.identities import SigningIdentity
from fabric_mod_tpu.msp.mspimpl import Msp, MspManager
from fabric_mod_tpu.peer import (Committer, TxValidator,
                                 ValidationInfoProvider,
                                 ValidatorCommitTarget)
from fabric_mod_tpu.policy import ApplicationPolicyEvaluator, from_string
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.sharding import (ChannelShardRouter,
                                     CrossChannelVerifyService, ShardMap,
                                     multihost_spec)
from fabric_mod_tpu.sharding.multihost import initialize_multihost
from fabric_mod_tpu.utils.fixtures import (independent_baseline,
                                           make_channel_stream,
                                           make_verify_items)

V = m.TxValidationCode


# --------------------------------------------------------------------------
# ShardMap: placement policy as a pure function of the join/leave seq
# --------------------------------------------------------------------------

def test_shardmap_least_loaded_assignment_is_deterministic():
    a = ShardMap(3)
    b = ShardMap(3)
    for mp in (a, b):
        got = [mp.assign(f"ch{i}") for i in range(7)]
        assert got == [0, 1, 2, 0, 1, 2, 0]
    assert a.loads() == [3, 2, 2]
    # idempotent: re-assign keeps the slice
    assert a.assign("ch1") == 1
    assert len(a) == 7 and "ch3" in a


def test_shardmap_release_rebalances_newest_first():
    mp = ShardMap(2)
    for i in range(4):
        mp.assign(f"ch{i}")                    # [ch0, ch2], [ch1, ch3]
    moves = mp.release("ch0")
    assert mp.loads() == [1, 2] or mp.loads() == [2, 1]
    # spread 1 <-> 2 is within tolerance: no move yet
    assert moves == []
    moves = mp.release("ch2")                  # slice0 empty, spread 2
    assert moves == [("ch3", 1, 0)]            # newest of the loaded
    assert mp.slice_of("ch3") == 0
    assert mp.loads() == [1, 1]


def test_shardmap_rebalance_off_and_unknown_channels():
    mp = ShardMap(2, rebalance=False)
    for i in range(4):
        mp.assign(f"ch{i}")
    assert mp.release("ch0") == []
    assert mp.release("ch2") == []             # no plan when off
    assert mp.loads() == [0, 2]
    assert mp.release("ghost") == []           # unknown: no-op
    with pytest.raises(KeyError):
        mp.slice_of("ghost")
    assert mp.slice_of("ghost", default=0) == 0
    with pytest.raises(ValueError):
        ShardMap(0)


# --------------------------------------------------------------------------
# CrossChannelVerifyService: one flusher, per-slice groups, isolation
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def csp():
    return SwCSP()


def _service(csp, n_slices=2):
    mp = ShardMap(n_slices)
    verifiers = {i: FakeBatchVerifier(csp) for i in range(n_slices)}
    svc = CrossChannelVerifyService(
        verifiers, lambda tag: mp.slice_of(tag, default=0),
        deadline_s=0.005)
    return svc, mp


def test_tagged_items_route_per_slice_and_verdicts_come_back(csp):
    svc, mp = _service(csp)
    mp.assign("big")                           # slice 0
    mp.assign("small")                         # slice 1
    items, expect = make_verify_items(6, invalid_every=3)
    try:
        futs = ([svc.submit_for("big", it) for it in items]
                + [svc.submit_for("small", it) for it in items])
        got = [f.result(timeout=60) for f in futs]
        assert got == expect + expect
        # the verify_many_for surface gives the same verdicts
        assert svc.verify_many_for("small", items, timeout=60) == expect
    finally:
        svc.close()


def test_untagged_and_unknown_tags_ride_the_default_slice(csp):
    svc, mp = _service(csp)
    items, expect = make_verify_items(4, invalid_every=2)
    try:
        # untagged (the base-service surface) and a tag the map never
        # placed both route to the default slice instead of raising —
        # one stray tag must never fail a coalesced batch
        assert svc.verify_many(items, timeout=60) == expect
        assert svc.verify_many_for("never-placed", items,
                                   timeout=60) == expect
    finally:
        svc.close()


def test_one_channels_injected_fault_never_touches_the_other(csp):
    """The flush-group isolation contract: an injected fault on one
    slice's dispatch group fails exactly that group's futures, typed;
    the other channel's riders in the SAME flush window resolve."""
    svc, mp = _service(csp)
    mp.assign("victim")                        # slice 0
    mp.assign("bystander")                     # slice 1
    items, expect = make_verify_items(4, invalid_every=2)
    plan = faults.FaultPlan().add("sharding.dispatch", nth=1, times=1)
    try:
        with faults.active(plan):
            # one batch, two groups: victim's group dispatches first
            # (slice order is sorted) and eats the nth=1 fault
            vf = [svc.submit_for("victim", it) for it in items]
            bf = [svc.submit_for("bystander", it) for it in items]
            got_b = [f.result(timeout=60) for f in bf]
            assert got_b == expect             # untouched
            for f in vf:
                with pytest.raises(faults.InjectedFault):
                    f.result(timeout=60)
        # after the plan's times cap, the victim heals
        assert svc.verify_many_for("victim", items, timeout=60) == expect
    finally:
        svc.close()


# --------------------------------------------------------------------------
# Router + commit engines: the block-path worlds
# --------------------------------------------------------------------------

CC_POLICY = "OutOf(2, 'Org1.peer', 'Org2.peer', 'Org3.peer')"


@pytest.fixture(scope="module")
def world(csp):
    msps, signers = [], {}
    for org in ("Org1", "Org2", "Org3"):
        ca = calib.CA(f"ca.{org.lower()}", org)
        msps.append(Msp(org, csp, [ca.cert]))
        cert, key = ca.issue(f"peer0.{org.lower()}", org, ous=["peer"])
        signers[org] = SigningIdentity(org, cert, calib.key_pem(key),
                                       csp)
    policy = m.ApplicationPolicy(
        signature_policy=from_string(CC_POLICY)).encode()
    return dict(csp=csp, mgr=MspManager(msps), signers=signers,
                policy=policy)


def _stream(world, cid: str, n_blocks: int = 3, txs: int = 3):
    """The SHARED oracle stream generator (utils/fixtures.py — same
    under-endorsed cadence and per-channel keys bench --metric
    multichannel gates against, so the two differentials can never
    drift apart)."""
    return make_channel_stream(world["signers"], cid, n_blocks, txs)


@pytest.fixture(scope="module")
def streams(world):
    return {f"ch{i}": _stream(world, f"ch{i}") for i in range(3)}


def _target(world, cid: str, verifier, root) -> ValidatorCommitTarget:
    led = KvLedger(str(root), cid)
    validator = TxValidator(
        cid, world["mgr"], ApplicationPolicyEvaluator(world["mgr"]),
        verifier, ValidationInfoProvider(world["policy"]),
        tx_id_exists=led.tx_id_exists)
    return ValidatorCommitTarget(validator, led)


def _independent_baseline(world, streams, root):
    """N unsharded runs through the SHARED oracle helper
    (fixtures.independent_baseline): per channel, its own verifier +
    sync Committer into a fresh ledger — what the sharded run must
    match bit-for-bit."""
    return independent_baseline(
        streams,
        lambda cid: _target(world, cid, FakeBatchVerifier(world["csp"]),
                            root / f"base-{cid}"))


def test_sharded_run_bit_identical_to_independent_runs(
        world, streams, tmp_path):
    """THE acceptance differential: 3 channels placed on 2 host-mode
    slices behind one router + shared verify service, blocks submitted
    round-robin across channels (real interleaving through the
    per-channel pipes), per-channel txflags and state fingerprints
    asserted identical to 3 independent unsharded sync runs."""
    baseline = _independent_baseline(world, streams, tmp_path)
    router = ChannelShardRouter(
        n_slices=2, depth=2,
        verifier_factory=lambda i, mesh: FakeBatchVerifier(world["csp"]))
    flags = {cid: [] for cid in streams}
    targets = {}
    try:
        for cid in streams:
            handle = router.add_channel(cid)
            targets[cid] = _target(world, cid, handle,
                                   tmp_path / f"shard-{cid}")
            router.bind_target(cid, targets[cid])
        # round-robin interleave: every channel's pipe is live at once
        max_len = max(len(s) for s in streams.values())
        for n in range(max_len):
            for cid, raws in streams.items():
                if n < len(raws):
                    router.submit_block(cid, m.Block.decode(raws[n]))
        assert router.flush(timeout_s=120)
        for cid, raws in streams.items():
            led = targets[cid].ledger
            assert led.height == len(raws)
            for n in range(len(raws)):
                blk = led.get_block_by_number(n)
                flags[cid].append(list(protoutil.block_txflags(blk)))
            assert flags[cid] == baseline[cid][0], cid
            assert led.state_fingerprint() == baseline[cid][1], cid
        # the flags carried signal (under-endorsed lanes flipped)
        distinct = {f for per in flags.values()
                    for blk in per for f in blk}
        assert V.ENDORSEMENT_POLICY_FAILURE in distinct
        assert V.VALID in distinct
    finally:
        router.close()


def test_poisoned_channel_pipe_never_wedges_the_rest(
        world, streams, tmp_path):
    """Channel A's commit pipe is poisoned mid-stream (its target
    crashes on commit); B keeps committing through the shared router
    AND the shared verify service keeps answering riders; A's next
    store_block rebuilds a fresh pipe from the committed height and
    the channel recovers — bit-identical to its baseline."""
    baseline = _independent_baseline(world, streams, tmp_path)
    router = ChannelShardRouter(
        n_slices=2, depth=2,
        verifier_factory=lambda i, mesh: FakeBatchVerifier(world["csp"]))
    cid_a, cid_b = "ch0", "ch1"
    boom = {"armed": False}

    class CrashingTarget:
        def __init__(self, inner):
            self._inner = inner
            self.validator = inner.validator
            self.ledger = inner.ledger

        def stage_block(self, block):
            return self._inner.stage_block(block)

        def commit_staged(self, staged):
            if boom["armed"]:
                boom["armed"] = False
                raise RuntimeError("injected commit crash")
            return self._inner.commit_staged(staged)

    try:
        ta = CrashingTarget(_target(world, cid_a,
                                    router.add_channel(cid_a),
                                    tmp_path / "iso-a"))
        router.bind_target(cid_a, ta)
        tb = _target(world, cid_b, router.add_channel(cid_b),
                     tmp_path / "iso-b")
        router.bind_target(cid_b, tb)

        raws_a = streams[cid_a]
        raws_b = streams[cid_b]
        # poison A on its first block
        boom["armed"] = True
        with pytest.raises(Exception):
            pipe = router.pipeline_for(cid_a)
            pipe.submit(m.Block.decode(raws_a[0]))
            pipe.flush(timeout_s=60)
        assert router.pipeline_for(cid_a) is not pipe  # rebuilt

        # B commits its whole stream while A is (was) poisoned
        for raw in raws_b:
            router.store_block(cid_b, m.Block.decode(raw))
        assert tb.ledger.state_fingerprint() == baseline[cid_b][1]

        # the shared flusher still answers riders from every channel
        items, expect = make_verify_items(4, invalid_every=2)
        assert router.service.verify_many_for(cid_b, items,
                                              timeout=60) == expect
        assert router.service.verify_many_for(cid_a, items,
                                              timeout=60) == expect

        # A recovers through a fresh pipe, bit-identical
        for raw in raws_a:
            router.store_block(cid_a, m.Block.decode(raw))
        assert ta.ledger.state_fingerprint() == baseline[cid_a][1]
    finally:
        router.close()


def test_tampered_channel_batch_never_perturbs_the_other(
        world, streams, tmp_path):
    """Channel A validates a block whose signatures are all garbage
    (every tx flagged invalid) CONCURRENTLY with channel B's clean
    stream — B's flags and fingerprint must equal its solo baseline."""
    baseline = _independent_baseline(world, streams, tmp_path)
    router = ChannelShardRouter(
        n_slices=2, depth=2,
        verifier_factory=lambda i, mesh: FakeBatchVerifier(world["csp"]))
    cid_a, cid_b = "ch0", "ch1"
    try:
        ta = _target(world, cid_a, router.add_channel(cid_a),
                     tmp_path / "tam-a")
        router.bind_target(cid_a, ta)
        tb = _target(world, cid_b, router.add_channel(cid_b),
                     tmp_path / "tam-b")
        router.bind_target(cid_b, tb)
        # tamper every envelope signature of A's first block
        blk_a = m.Block.decode(streams[cid_a][0])
        for i, raw_env in enumerate(blk_a.data.data):
            env = m.Envelope.decode(raw_env)
            env.signature = bytes(len(env.signature))
            blk_a.data.data[i] = env.encode()

        done = threading.Event()
        a_flags = []

        def run_a():
            try:
                a_flags.append(router.store_block(cid_a, blk_a))
            finally:
                done.set()

        t = threading.Thread(target=run_a, daemon=True)
        t.start()
        for raw in streams[cid_b]:
            router.store_block(cid_b, m.Block.decode(raw))
        assert done.wait(timeout=120) and t is not None
        t.join(timeout=10)
        # A's garbage flagged invalid, not crashed
        assert a_flags and all(f != V.VALID for f in a_flags[0])
        # B untouched, bit-identical to its solo baseline
        led_b = tb.ledger
        got_b = [list(protoutil.block_txflags(led_b.get_block_by_number(n)))
                 for n in range(led_b.height)]
        assert got_b == baseline[cid_b][0]
        assert led_b.state_fingerprint() == baseline[cid_b][1]
    finally:
        router.close()


def test_rebalance_on_leave_moves_and_rebuilds_pipes(
        world, streams, tmp_path):
    """Four channels on two slices -> removing a spread-1 neighbor
    forces no move (spread <= 1 is balanced); stranding one slice
    entirely moves the other slice's NEWEST channel over, and the
    moved channel's next pipe is consumer-labeled for its NEW slice
    while still committing correctly."""
    router = ChannelShardRouter(
        n_slices=2, depth=1,
        verifier_factory=lambda i, mesh: FakeBatchVerifier(world["csp"]))
    try:
        tgts = {}
        for cid in ("ch0", "ch1", "ch2", "chX"):
            handle = router.add_channel(cid)
            tgts[cid] = _target(world, cid, handle,
                                tmp_path / f"reb-{cid}")
            router.bind_target(cid, tgts[cid])
        assert router.map.loads() == [2, 2]    # ch0+ch2 / ch1+chX
        # place a pipe on chX so the move (below) has one to rebuild;
        # chX replays ch1's stream (same channel id inside the blocks
        # is irrelevant to routing — the ledger key-space is its own)
        router.store_block("chX", m.Block.decode(streams["ch1"][0]))
        p1 = router.pipeline_for("chX")
        assert p1.consumer == "shard1"
        # a spread-1 leave rebalances nothing...
        assert router.remove_channel("ch0") == []
        # ...stranding slice 0 moves the newest of slice 1 (chX)
        moves = router.remove_channel("ch2")
        assert moves == [("chX", 1, 0)]
        assert router.slice_of("chX") == 0
        # the old pipe was drained+closed; the fresh one is pinned to
        # the new slice and the channel keeps committing in order
        router.store_block("chX", m.Block.decode(streams["ch1"][1]))
        p1b = router.pipeline_for("chX")
        assert p1b is not p1 and p1.closed
        assert p1b.consumer == "shard0"
        assert tgts["chX"].ledger.height == 2
    finally:
        router.close()


def test_router_rejects_unplaced_and_closed_use(world, tmp_path):
    router = ChannelShardRouter(
        n_slices=1,
        verifier_factory=lambda i, mesh: FakeBatchVerifier(world["csp"]))
    with pytest.raises(KeyError):
        router.pipeline_for("nope")
    router.add_channel("t")                    # no target bound
    with pytest.raises(RuntimeError):
        router.pipeline_for("t")
    router.close()
    with pytest.raises(RuntimeError):
        router.add_channel("late")
    router.close()                             # idempotent


def test_sharded_commit_on_real_slice_meshes(world, tmp_path):
    """The acceptance differential on the REAL multi-device path: two
    channels pinned to the two 4-device slice meshes of the virtual
    8-device CPU mesh, whole commit stack (validator staging ->
    slice-pinned device dispatch -> pipelined commit) — per-channel
    txflags + fingerprints bit-identical to independent unsharded
    device runs.  Tiny blocks on purpose: the batches stay in the
    bucket-8 program shapes test_parallel already compiles."""
    from fabric_mod_tpu.bccsp.tpu import TpuVerifier
    from fabric_mod_tpu.parallel import slice_meshes

    streams = {cid: _stream(world, cid, n_blocks=2, txs=2)
               for cid in ("dev0", "dev1")}
    baseline = {}
    for cid, raws in streams.items():
        t = _target(world, cid, TpuVerifier(cache_size=0),
                    tmp_path / f"devbase-{cid}")
        flags = [list(Committer(t.validator, t.ledger).store_block(
            m.Block.decode(raw))) for raw in raws]
        baseline[cid] = (flags, t.ledger.state_fingerprint())

    router = ChannelShardRouter(
        n_slices=2, meshes=slice_meshes(2), depth=2,
        verifier_factory=lambda i, mesh: TpuVerifier(mesh=mesh,
                                                     cache_size=0))
    try:
        targets = {}
        for cid in streams:
            handle = router.add_channel(cid)
            targets[cid] = _target(world, cid, handle,
                                   tmp_path / f"devsh-{cid}")
            router.bind_target(cid, targets[cid])
        for n in range(2):
            for cid in streams:
                router.submit_block(cid,
                                    m.Block.decode(streams[cid][n]))
        assert router.flush(timeout_s=600)
        for cid in streams:
            led = targets[cid].ledger
            got = [list(protoutil.block_txflags(
                led.get_block_by_number(n))) for n in range(led.height)]
            assert got == baseline[cid][0], cid
            assert led.state_fingerprint() == baseline[cid][1], cid
    finally:
        router.close()


# --------------------------------------------------------------------------
# Multi-host spec: shape pinned, bring-up stubbed
# --------------------------------------------------------------------------

def test_multihost_spec_partitions_slices_round_robin():
    spec = multihost_spec(n_hosts=2, n_slices=8)
    assert spec["hosts"] == 2 and spec["slices"] == 8
    groups = {g["process_index"]: g["slices"]
              for g in spec["process_groups"]}
    assert groups == {0: [0, 2, 4, 6], 1: [1, 3, 5, 7]}
    # every slice exactly once across hosts
    flat = sorted(s for g in groups.values() for s in g)
    assert flat == list(range(8))
    with pytest.raises(ValueError):
        multihost_spec(n_hosts=3, n_slices=8)


def test_multihost_initialize_is_a_stub_behind_the_knob(monkeypatch):
    monkeypatch.delenv("FABRIC_MOD_TPU_SHARD_HOSTS", raising=False)
    initialize_multihost()                     # single host: no-op
    monkeypatch.setenv("FABRIC_MOD_TPU_SHARD_HOSTS", "2")
    with pytest.raises(NotImplementedError):
        initialize_multihost()


def test_shard_knob_defaults_route_single_slice(monkeypatch):
    from fabric_mod_tpu.sharding.router import shard_count, shard_depth
    monkeypatch.delenv("FABRIC_MOD_TPU_SHARDS", raising=False)
    monkeypatch.delenv("FABRIC_MOD_TPU_SHARD_DEPTH", raising=False)
    monkeypatch.delenv("FABRIC_MOD_TPU_COMMIT_PIPELINE", raising=False)
    assert shard_count() == 0                  # sharding off by default
    assert shard_depth() >= 1                  # router-bound: floor 1
    monkeypatch.setenv("FABRIC_MOD_TPU_SHARDS", "4")
    monkeypatch.setenv("FABRIC_MOD_TPU_SHARD_DEPTH", "3")
    assert shard_count() == 4 and shard_depth() == 3
