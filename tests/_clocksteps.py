"""Shared fake-clock stepping for the deterministic raft tiers.

One implementation for test_raft.py / test_cluster.py /
test_raft_fakeclock.py (each wraps it with its own step sizes): step
fake time finely so the EARLIEST pending timer fires alone — a coarse
jump would expire every node's timeout in one wave and split the vote;
randomized timeouts only help when time moves continuously.  Between
steps, real-time-settle the FSM threads: message passing is still
thread-based, only TIMERS are faked.
"""
import time


def settle(pred, timeout=5.0, poll=0.005):
    """Wait (REAL time) for the FSM threads to process queued work."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return pred()


def advance_until(clock, pred, step=0.02, max_steps=150,
                  settle_timeout=0.2, settle_poll=0.005,
                  final_timeout=5.0):
    for _ in range(max_steps):
        if settle(pred, timeout=settle_timeout, poll=settle_poll):
            return True
        clock.advance(step)
    return settle(pred, timeout=final_timeout)


def leader_known_by_all(chains):
    """True once exactly ONE chain leads and EVERY chain's raft layer
    has learned that leader's id.  Ordering through a follower before
    this point is legitimately lossy: a leaderless follower DROPS
    forwarded submits (clients retry, by design), so election waits
    that gate a follower-side `order()` must use this predicate, not
    `any(is_leader)` — under suite load the unknown-leader window
    otherwise widens into a dropped-batch flake."""
    leaders = [i for i, c in chains.items() if c.is_leader]
    if len(leaders) != 1:
        return False
    return all(c.leader_id == leaders[0] for c in chains.values())
