"""Ledger snapshots + operator maintenance commands.

(reference test model: kvledger snapshot generation/bootstrap tests +
the node reset/rollback command suites.)
"""
import os

import pytest

from fabric_mod_tpu.ledger import admin
from fabric_mod_tpu.ledger.kvledger import KvLedger
from fabric_mod_tpu.ledger.snapshot import (
    SnapshotError, bootstrap_from_snapshot, generate_snapshot,
    verify_snapshot)
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil

V = m.TxValidationCode.VALID


def _make_block(num, prev, n_txs, led=None):
    from fabric_mod_tpu.ledger.rwsetutil import RWSetBuilder
    envs = []
    for i in range(n_txs):
        b = RWSetBuilder()
        b.add_write("cc", f"k{num}-{i}", b"v%d" % num)
        ch = protoutil.make_channel_header(
            m.HeaderType.ENDORSER_TRANSACTION, "ch",
            tx_id=f"tx{num}-{i}")
        sh = protoutil.make_signature_header(b"c", b"n")
        tx = m.Transaction(actions=[m.TransactionAction(
            payload=m.ChaincodeActionPayload(
                action=m.ChaincodeEndorsedAction(
                    proposal_response_payload=m.ProposalResponsePayload(
                        extension=m.ChaincodeAction(
                            results=b.build().encode()).encode()
                    ).encode())).encode())])
        payload = protoutil.make_payload(ch, sh, tx.encode())
        envs.append(m.Envelope(payload=payload.encode()))
    return protoutil.new_block(num, prev, envs)


def _fill(led, n_blocks, txs_per_block=3):
    prev = (protoutil.block_header_hash(
        led.get_block_by_number(led.height - 1).header)
        if led.height else b"")
    for num in range(led.height, led.height + n_blocks):
        blk = _make_block(num, prev, txs_per_block)
        led.commit_block(blk, [V] * txs_per_block)
        prev = protoutil.block_header_hash(blk.header)


def test_snapshot_roundtrip_and_bootstrap(tmp_path):
    led = KvLedger(str(tmp_path / "src"), "ch")
    _fill(led, 6)
    snap = str(tmp_path / "snap")
    meta = generate_snapshot(led, snap)
    assert meta["height"] == 6
    assert verify_snapshot(snap)["channel"] == "ch"

    led2 = bootstrap_from_snapshot(snap, str(tmp_path / "joined"))
    assert led2.height == 6
    # state is present, pruned blocks are not
    assert led2.state.get_state("cc", "k3-1")[0] == b"v3"
    assert led2.get_block_by_number(2) is None
    # the chain continues from the snapshot tip
    tip = led.get_block_by_number(5)
    blk6 = _make_block(6, protoutil.block_header_hash(tip.header), 2)
    led2.commit_block(blk6, [V] * 2)
    assert led2.height == 7
    assert led2.state.get_state("cc", "k6-0")[0] == b"v6"
    # reopen: recovery must not try to replay the pruned range
    led2.close()
    led3 = KvLedger(str(tmp_path / "joined"), "ch")
    assert led3.height == 7
    assert led3.state.get_state("cc", "k6-1")[0] == b"v6"
    led3.close()
    led.close()


def test_snapshot_preserves_metadata_and_txids(tmp_path):
    """Key metadata (endorsement pins) and pruned-range txids survive
    the snapshot join (regressions: SBE policies lost, duplicate txid
    gate bypassed)."""
    led = KvLedger(str(tmp_path / "src"), "ch")
    _fill(led, 3)
    # attach a VALIDATION_PARAMETER to a key
    from fabric_mod_tpu.ledger.statedb import UpdateBatch
    batch = UpdateBatch()
    batch.put_metadata("cc", "k1-0",
                       {"VALIDATION_PARAMETER": b"pinned"}, (2, 99))
    led.state.apply_updates(batch, led.state.savepoint)
    snap = str(tmp_path / "snap")
    generate_snapshot(led, snap)

    led2 = bootstrap_from_snapshot(snap, str(tmp_path / "joined"))
    assert led2.state.get_metadata("cc", "k1-0") == {
        "VALIDATION_PARAMETER": b"pinned"}
    # pruned-range txids still trip duplicate detection
    assert led2.tx_id_exists("tx1-0")
    assert led2.get_transaction_by_id("tx1-0") is None  # block pruned
    led2.close()
    # ...and the index survives a reopen
    led3 = KvLedger(str(tmp_path / "joined"), "ch")
    assert led3.tx_id_exists("tx2-1")
    led3.close()
    led.close()


def test_admin_refuses_bootstrapped_ledgers(tmp_path):
    led = KvLedger(str(tmp_path / "src"), "ch")
    _fill(led, 3)
    snap = str(tmp_path / "snap")
    generate_snapshot(led, snap)
    led.close()
    joined = str(tmp_path / "joined")
    led2 = bootstrap_from_snapshot(snap, joined)
    led2.close()
    with pytest.raises(admin.AdminError):
        admin.rebuild_dbs(joined)
    with pytest.raises(admin.AdminError):
        admin.rollback(joined, 1)


def test_snapshot_checksum_tamper_detected(tmp_path):
    led = KvLedger(str(tmp_path / "src"), "ch")
    _fill(led, 2)
    snap = str(tmp_path / "snap")
    generate_snapshot(led, snap)
    with open(os.path.join(snap, "state.dat"), "r+b") as f:
        f.seek(10)
        f.write(b"\xff")
    with pytest.raises(SnapshotError):
        verify_snapshot(snap)
    led.close()


def test_rebuild_dbs_rebuilds_from_blocks(tmp_path):
    d = str(tmp_path / "led")
    led = KvLedger(d, "ch")
    _fill(led, 4)
    led.close()
    admin.rebuild_dbs(d)
    assert not os.path.isdir(os.path.join(d, "state"))
    led2 = KvLedger(d, "ch")
    assert led2.height == 4
    assert led2.state.get_state("cc", "k2-0")[0] == b"v2"
    assert led2.history.get_history_for_key("cc", "k2-0") == [(2, 0)]
    led2.close()


def test_rollback_truncates_and_rebuilds(tmp_path):
    d = str(tmp_path / "led")
    led = KvLedger(d, "ch")
    _fill(led, 6)
    led.close()
    admin.rollback(d, 2)
    led2 = KvLedger(d, "ch")
    assert led2.height == 3
    assert led2.state.get_state("cc", "k2-0")[0] == b"v2"
    assert led2.state.get_state("cc", "k4-0") is None
    led2.close()
    with pytest.raises(admin.AdminError):
        admin.rollback(d, 99)
