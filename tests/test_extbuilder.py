"""External chaincode: CCaaS protocol round-trip, the launcher's
package resolution, and script builders.

(reference test model: core/container/externalbuilder tests + the
chaincode-as-a-service integration suite — the peer connects to a
running chaincode server and drives state callbacks through the live
simulator.)
"""
import json
import os
import stat
import threading
import time

import pytest

from fabric_mod_tpu.e2e import Network
from fabric_mod_tpu.peer.ccpackage import PackageStore, build_package
from fabric_mod_tpu.peer.chaincode import (
    ChaincodeError, ChaincodeRegistry, ChaincodeStub, KvContract)
from fabric_mod_tpu.peer.extbuilder import (
    ChaincodeLauncher, ChaincodeServer, ExternalBuilder,
    ExternalBuilderRegistry, ExternalContract, ExternalBuilderError)
from fabric_mod_tpu.protos import protoutil


def test_ccaas_roundtrip_over_tcp(tmp_path):
    """A contract served out-of-process over the TCP protocol behaves
    exactly like the in-process one — including state callbacks, range
    reads, rich queries, transient maps, and private data."""
    srv = ChaincodeServer(KvContract())
    srv.start()
    try:
        net = Network(str(tmp_path), batch_timeout="100ms",
                      max_message_count=10)
        try:
            ext = ExternalContract({"address": srv.address})
            net.chaincodes.register("extcc", ext)
            # endorse a put through the remote contract
            sp, _p, txid = protoutil.create_chaincode_proposal(
                net.channel_id, "extcc", [b"put", b"k1", b"v1"],
                net.client)
            resp = net.endorsers["Org1"].process_proposal(sp)
            assert resp.response.status == 200
            assert resp.response.payload == b"ok"
            # reads flow back through the callback channel
            sp, _p, _ = protoutil.create_chaincode_proposal(
                net.channel_id, "extcc", [b"get", b"missing"],
                net.client)
            resp = net.endorsers["Org1"].process_proposal(sp)
            assert resp.response.status == 200
            assert resp.response.payload == b""
            # transient map + private data over the wire
            sp, _p, _ = protoutil.create_chaincode_proposal(
                net.channel_id, "extcc", [b"putpvt", b"col1", b"pk"],
                net.client, transient={"value": b"secret"})
            resp = net.endorsers["Org1"].process_proposal(sp)
            assert resp.response.status == 200
            # error propagation
            sp, _p, _ = protoutil.create_chaincode_proposal(
                net.channel_id, "extcc", [b"nosuch"], net.client)
            resp = net.endorsers["Org1"].process_proposal(sp)
            assert resp.response.status != 200
            ext.close()
        finally:
            net.close()
    finally:
        srv.stop()


def test_ccaas_server_down_is_clean_error(tmp_path):
    ext = ExternalContract({"address": "127.0.0.1:1"})

    stub = ChaincodeStub("x", None, [b"get", b"k"], "tx1", "chan")
    with pytest.raises(ChaincodeError):
        ext.invoke(stub)


def test_launcher_resolves_python_package(tmp_path):
    store = PackageStore(str(tmp_path / "pkgs"))
    code = (
        b"from fabric_mod_tpu.peer.chaincode import KvContract\n"
        b"contract = KvContract()\n")
    store.save(build_package("pycc", code, cc_type="python"))
    launcher = ChaincodeLauncher(store)
    reg = ChaincodeRegistry()
    reg.set_resolver(launcher.resolve)
    assert reg.get("pycc") is not None
    assert reg.get("pycc") is reg.get("pycc")     # cached
    assert reg.get("absent") is None


def test_launcher_resolves_ccaas_package(tmp_path):
    srv = ChaincodeServer(KvContract())
    srv.start()
    try:
        store = PackageStore(str(tmp_path / "pkgs"))
        conn = json.dumps({"address": srv.address}).encode()
        store.save(build_package("remote-cc", conn, cc_type="ccaas"))
        launcher = ChaincodeLauncher(store)
        cc = launcher.resolve("remote-cc")
        assert isinstance(cc, ExternalContract)
        cc.close()
    finally:
        srv.stop()


def test_launcher_unknown_type_raises(tmp_path):
    store = PackageStore(str(tmp_path / "pkgs"))
    store.save(build_package("gocc", b"package main", cc_type="golang"))
    launcher = ChaincodeLauncher(store)
    with pytest.raises(ExternalBuilderError):
        launcher.resolve("gocc")


def test_script_builder_contract(tmp_path):
    """detect/build scripts run as subprocesses with the reference's
    argument contract; first detect() wins."""
    root = tmp_path / "builders"
    for name, detect_rc in (("never", 1), ("claims", 0)):
        bdir = root / name / "bin"
        os.makedirs(bdir)
        for script, body in (
                ("detect", f"#!/bin/sh\nexit {detect_rc}\n"),
                ("build", "#!/bin/sh\ncp -r \"$1\"/. \"$3\"/\n"
                          "echo built > \"$3\"/marker\n")):
            p = bdir / script
            p.write_text(body)
            p.chmod(p.stat().st_mode | stat.S_IEXEC)
    reg = ExternalBuilderRegistry(str(root))
    assert [b.name for b in reg.builders] == ["claims", "never"]
    meta = tmp_path / "meta"
    os.makedirs(meta)
    chosen = reg.detect(str(meta))
    assert chosen is not None and chosen.name == "claims"
    src = tmp_path / "src"
    os.makedirs(src)
    (src / "code.py").write_text("x = 1\n")
    out = tmp_path / "out"
    os.makedirs(out)
    chosen.build(str(src), str(meta), str(out))
    assert (out / "marker").read_text() == "built\n"
    assert (out / "code.py").exists()


def test_launcher_builds_and_runs_via_external_builder(tmp_path):
    """The full detect/build/run path: an unknown package type is
    claimed by a builder whose bin/run launches a chaincode server
    process and publishes its address; the launcher dials it."""
    import sys
    root = tmp_path / "builders"
    bdir = root / "pyrun" / "bin"
    os.makedirs(bdir)
    runner_py = tmp_path / "runner.py"
    runner_py.write_text(
        "import json, sys, time\n"
        "sys.path.insert(0, '/root/repo')\n"
        "from fabric_mod_tpu.peer.chaincode import KvContract\n"
        "from fabric_mod_tpu.peer.extbuilder import ChaincodeServer\n"
        "run_meta = sys.argv[1]\n"
        "meta = json.load(open(run_meta + '/chaincode.json'))\n"
        "srv = ChaincodeServer(KvContract())\n"
        "srv.start()\n"
        "with open(meta['address_file'] + '.tmp', 'w') as f:\n"
        "    f.write(srv.address)\n"
        "import os; os.replace(meta['address_file'] + '.tmp',\n"
        "                      meta['address_file'])\n"
        "while True:\n"
        "    time.sleep(1)\n")
    scripts = {
        "detect": "#!/bin/sh\nexit 0\n",
        "build": "#!/bin/sh\ncp -r \"$1\"/. \"$3\"/\n",
        "run": f"#!/bin/sh\nexec {sys.executable} {runner_py} \"$2\"\n",
    }
    for name, body in scripts.items():
        p = bdir / name
        p.write_text(body)
        p.chmod(p.stat().st_mode | stat.S_IEXEC)
    store = PackageStore(str(tmp_path / "pkgs"))
    store.save(build_package("runcc", b"ignored-payload",
                             cc_type="custom"))
    launcher = ChaincodeLauncher(
        store, ExternalBuilderRegistry(str(root)))
    try:
        cc = launcher.resolve("runcc")
        assert isinstance(cc, ExternalContract)
        stub = ChaincodeStub("runcc", None, [b"nosuch"], "t1", "ch")
        with pytest.raises(ChaincodeError):
            cc.invoke(stub)               # reaches the REMOTE contract
        cc.close()
    finally:
        launcher.close()


def test_launcher_rejects_ambiguous_label(tmp_path):
    store = PackageStore(str(tmp_path / "pkgs"))
    store.save(build_package("dupcc", b"v1", cc_type="python"))
    store.save(build_package("dupcc", b"v2", cc_type="python"))
    launcher = ChaincodeLauncher(store)
    with pytest.raises(ExternalBuilderError, match="ambiguous"):
        launcher.resolve("dupcc")
